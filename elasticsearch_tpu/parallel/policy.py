"""Host-side mesh routing policy: single-device vs SPMD per dispatch.

The reference routes every search through a coordinator that fans out to
however many shards the index was created with — shard count is a static
index property. Here the analogous decision is DYNAMIC and per dispatch:
a corpus small enough that one chip's matmul beats the all-gather merge
should stay on one device, a corpus at HBM scale must spread. This module
owns that decision for every serving leg (exact kNN, IVF, BM25), plus the
process-wide serving mesh the sharded kernels execute on, and the
counters `_nodes/stats indices.mesh` / `profile.mesh` report.

Settings (read once at node boot, `node.py` calls `configure`):

  search.mesh.enabled      true | false | unset (auto: mesh when >1
                           device is visible)
  search.mesh.num_shards   mesh shard-axis size (default: all visible
                           devices / dp)
  search.mesh.dp           data-parallel axis size (default 1; floored
                           to a power of two). dp > 1 replicates the
                           sharded corpus across dp device groups so
                           independent query batches execute
                           CONCURRENTLY on disjoint groups — the
                           throughput axis, where more shards is the
                           latency axis. Replication costs dp× HBM.
  search.mesh.min_rows     corpora below this many rows stay
                           single-device (the all-gather merge + per-leg
                           SPMD overhead only pays for itself once the
                           local matmul dominates; default 32768)
  search.mesh.hbm_budget_bytes
                           device-memory budget for mesh-resident corpus
                           copies. Replication costs dp× device bytes,
                           so with dp > 1 a corpus is mesh-eligible only
                           while dp × its estimated device footprint
                           (the columnar store's per-field accounting,
                           `vectors/store.device_corpus_nbytes`) fits
                           the budget — before this gate only
                           `min_rows` guarded eligibility, and a large
                           corpus under dp=4 quadrupled HBM silently.
                           Default unset: no budget (real budgets come
                           from deployment sizing; CPU-sim hosts have
                           no HBM to guard).

With dp > 1 the router additionally chooses a dp-vs-shard SPLIT per
dispatch: a batch under queue pressure lands on one dp group (round-
robin — queued batches overlap on the other groups), an idle batch on a
large corpus spreads over the full mesh (all devices cooperate, queries
split along dp). The load signal is the continuous batcher's live
scheduler state (queued + in-flight dispatches) × corpus size; every
split decision is counted with its reason in `stats()["router"]["dp"]`.

The policy is process-wide like `ops/dispatch.DISPATCH` — one physical
mesh serves every index on the node, so per-index state would only
duplicate the counters.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)

# below this many corpus rows the single-device program wins: the sharded
# program's fixed costs (S-way dispatch, [S, Q, k] all-gather, merge) are
# corpus-size independent, while the local matmul saving scales with rows
DEFAULT_MIN_ROWS = 32_768

_lock = threading.Lock()
_cfg = {"enabled": None, "num_shards": None, "min_rows": DEFAULT_MIN_ROWS,
        "dp": None, "hbm_budget_bytes": None}
_mesh = None          # cached jax Mesh (built lazily)
_mesh_built = False   # latch: None is a valid cache value (no mesh)
# dp-group submeshes per FULL mesh, keyed by mesh equality: the dispatch
# cache keys executables on mesh identity, so the router and the warmup
# grid must hand out ONE set of group objects per serving mesh
_groups: dict = {}
# secondary meshes for consumers whose shard count is fixed by the index
# (the node.py multi-shard adapter), built through the same path so the
# dp setting applies everywhere or nowhere — keyed by shard count
_shard_meshes: dict = {}
_rr = 0               # round-robin dp-group cursor

_counters = {
    "decisions_mesh": 0,
    "decisions_single_device": 0,
    "searches": {"knn": 0, "ivf": 0, "bm25": 0},
    "reasons": {},            # reason -> count (single-device routes)
    # dp-vs-shard split of mesh-accepted dispatches (dp > 1 only):
    # "shard" = full-mesh program, "dp" = one dp-group submesh
    "dp_routes": {"shard": 0, "dp": 0},
    "dp_reasons": {},         # split reason -> count
    "dp_group_dispatches": {},  # group index -> dispatches routed to it
    # dp-aware HBM budget gate (eligible()): corpora whose dp-replicated
    # device footprint exceeded search.mesh.hbm_budget_bytes
    "hbm_rejections": 0,
    "hbm_last_rejected_bytes": 0,
    "hbm_accepted_bytes": 0,    # high-water accepted dp× footprint
    # per-leg timing: local = the SPMD program (shard-local score + ICI
    # merge, one compiled unit), merge = host-side result shaping
    "legs": {},               # leg -> {local_nanos, merge_nanos,
                              #         collective_bytes, dispatches}
}


_UNSET = object()

# bumped on every configure()/full reset(): the request-cache "live
# settings epoch" component (search/caches.request_cache_key) — a
# serving-policy change must MISS the read-path caches, not serve a
# result (and its route diagnostics) computed under the old config
_cfg_epoch = 0


def config_epoch() -> int:
    return _cfg_epoch


def configure(enabled=_UNSET, num_shards=_UNSET, min_rows=_UNSET,
              dp=_UNSET, hbm_budget_bytes=_UNSET) -> None:
    """Install `search.mesh.*` settings. PARTIAL update: only the
    keyword arguments the caller passes change — a node that sets one
    key must not clobber the others an earlier in-process node
    configured (same rule as the dispatcher's warmup policy). Passing
    None explicitly resets that key to auto/default. Drops the cached
    mesh (and its dp groups / secondary shard meshes) so the next
    dispatch rebuilds against the new config."""
    global _mesh, _mesh_built, _cfg_epoch
    with _lock:
        _cfg_epoch += 1
        if enabled is not _UNSET:
            _cfg["enabled"] = enabled
        if num_shards is not _UNSET:
            _cfg["num_shards"] = (int(num_shards)
                                  if num_shards is not None else None)
        if min_rows is not _UNSET:
            _cfg["min_rows"] = (int(min_rows) if min_rows is not None
                                else DEFAULT_MIN_ROWS)
        if dp is not _UNSET:
            _cfg["dp"] = int(dp) if dp is not None else None
        if hbm_budget_bytes is not _UNSET:
            _cfg["hbm_budget_bytes"] = (int(hbm_budget_bytes)
                                        if hbm_budget_bytes is not None
                                        else None)
        _mesh, _mesh_built = None, False
        _groups.clear()
        _shard_meshes.clear()


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _effective_dp(n_devices: int) -> int:
    """Configured dp clamped to the device budget and floored to a power
    of two (query buckets are pow-2, so only a pow-2 dp divides every
    full-mesh batch)."""
    dp = _cfg["dp"] or 1
    dp = max(1, min(int(dp), max(n_devices, 1)))
    floored = _pow2_floor(dp)
    if floored != dp:
        logger.warning("search.mesh.dp=%d floored to %d (power of two "
                       "required for bucket divisibility)", dp, floored)
    return floored


def min_rows() -> int:
    return _cfg["min_rows"]


def serving_mesh():
    """The process-wide (dp=R, shard=S) serving mesh, or None when mesh
    execution is off (disabled, or fewer than 2 usable devices). R comes
    from `search.mesh.dp` (default 1); S from `search.mesh.num_shards`
    (default: remaining devices per dp group)."""
    global _mesh, _mesh_built
    with _lock:
        if _mesh_built:
            return _mesh
    mesh = None
    if _cfg["enabled"] is not False:
        try:
            import jax

            from elasticsearch_tpu.parallel import mesh as mesh_lib
            n_dev = len(jax.devices())
            dp = _effective_dp(n_dev)
            n = _cfg["num_shards"] if _cfg["num_shards"] else n_dev // dp
            n = max(1, min(n, n_dev // dp))
            # dp groups of a single shard are still a mesh (pure
            # replication — the throughput-only shape); a 1x1 "mesh" is
            # just the single device and stays off
            if dp * n >= 2:
                mesh = mesh_lib.make_mesh(num_shards=n, dp=dp)
        except Exception:
            # the latch below caches this None for the process lifetime:
            # without a log line a multi-chip node would silently serve
            # single-device until restart (stats only show available:
            # false, not why)
            logger.warning("mesh serving disabled: serving-mesh build "
                           "failed (latched off until restart or "
                           "reconfigure)", exc_info=True)
            mesh = None
    with _lock:
        if _mesh_built:
            # another thread won the build race: keep ITS object — the
            # identity-compared caches (store append path, lexical
            # mesh-CSR, sharded IVF pytree) all key on the cached mesh,
            # and caching a second equal-but-distinct Mesh would force
            # each of them through one redundant corpus re-upload
            return _mesh
        _mesh, _mesh_built = mesh, True
        return _mesh


def num_shards() -> int:
    mesh = serving_mesh()
    if mesh is None:
        return 0
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    return mesh.shape[mesh_lib.SHARD_AXIS]


def dp_size() -> int:
    """dp-axis size of the serving mesh (0 = no mesh)."""
    mesh = serving_mesh()
    if mesh is None:
        return 0
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    return mesh_lib.dp_size(mesh)


def dp_groups(mesh=None):
    """The dp-group submeshes of `mesh` (default: the serving mesh) —
    ONE canonical tuple per mesh, because the dispatch cache keys
    executables on mesh identity: the router's group pick and the warmup
    grid must name the same objects or warmed programs would never be
    hit. Keyed by mesh equality, so an equal-but-distinct mesh resolves
    to the same group set."""
    if mesh is None:
        mesh = serving_mesh()
    if mesh is None:
        return ()
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    with _lock:
        groups = _groups.get(mesh)
        if groups is None:
            groups = (mesh_lib.dp_submeshes(mesh)
                      if mesh_lib.dp_size(mesh) > 1 else (mesh,))
            _groups[mesh] = groups
        return groups


def mesh_for_shards(n_shards: int):
    """One mesh build path for EVERY consumer whose shard count is fixed
    externally (the node multi-shard adapter maps one engine shard per
    mesh column) — previously a second hand-rolled `make_mesh(dp=1)`
    beside the serving mesh, which is exactly how a dp setting
    half-applies. Returns the serving mesh when its shard axis already
    matches, else builds (and caches per shard count) a mesh with the
    configured dp clamped to the device budget; None when `n_shards`
    devices aren't available."""
    n_shards = int(n_shards)
    mesh = serving_mesh()
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    if mesh is not None and mesh_lib.shard_size(mesh) == n_shards:
        return mesh
    with _lock:
        if n_shards in _shard_meshes:
            return _shard_meshes[n_shards]
    built = None
    try:
        import jax
        n_dev = len(jax.devices())
        if n_shards >= 1 and n_shards <= n_dev:
            dp = min(_effective_dp(n_dev), _pow2_floor(n_dev // n_shards))
            built = mesh_lib.make_mesh(num_shards=n_shards, dp=max(dp, 1))
    except Exception:
        logger.warning("mesh_for_shards(%d) build failed", n_shards,
                       exc_info=True)
        built = None
    with _lock:
        return _shard_meshes.setdefault(n_shards, built)


def eligible(n_rows: int, device_bytes: Optional[int] = None) -> bool:
    """Build-time check (no routing decision counted): is this corpus
    one the router could ever send to the mesh? Gates the sharded
    upload at refresh so small indexes never pay the second resident
    copy.

    `device_bytes` is the field's estimated single-copy device
    footprint (the columnar store's per-field accounting). Replication
    multiplies it by the dp-axis size — each dp group holds the whole
    sharded corpus — so with a `search.mesh.hbm_budget_bytes` budget
    configured, a corpus whose dp× footprint exceeds the budget stays
    single-device (counted under `stats()["hbm"]`)."""
    if (n_rows < _cfg["min_rows"] or _cfg["enabled"] is False):
        return False
    mesh = serving_mesh()
    if mesh is None:
        return False
    return hbm_allows(device_bytes, mesh)


def hbm_allows(device_bytes: Optional[int], mesh=None) -> bool:
    """The budget-only half of `eligible()`, for consumers whose mesh
    participation is fixed externally (the node.py multi-shard adapter
    maps one engine shard per mesh column regardless of `min_rows`):
    with `search.mesh.hbm_budget_bytes` configured, a dp-replicated
    footprint past the budget is rejected and counted."""
    budget = _cfg["hbm_budget_bytes"]
    if budget is None or device_bytes is None:
        return True
    if mesh is None:
        mesh = serving_mesh()
    if mesh is None:
        return True
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    dp = mesh_lib.dp_size(mesh)
    need = int(device_bytes) * max(dp, 1)
    if need > budget:
        with _lock:
            _counters["hbm_rejections"] += 1
            _counters["hbm_last_rejected_bytes"] = need
        return False
    with _lock:
        _counters["hbm_accepted_bytes"] = max(
            _counters["hbm_accepted_bytes"], need)
    return True


def _choose_split(batch, n_rows: int, queue_depth: int, dp: int,
                  n_shards: int):
    """dp-vs-shard split for one mesh-accepted dispatch.

    "dp" sends the batch to ONE dp group (S shards, 1/dp of the
    devices), leaving the other groups free — concurrent batches overlap
    on disjoint device groups, the throughput shape. "shard" runs the
    full-mesh program (queries split along dp, corpus along shard) — all
    devices cooperate on this one batch, the latency shape. The decision
    is the unified dispatch cost model's (serving/router.py): queue wait
    vs device-leg estimate per route, calibrated so the historical
    min_rows*dp break-even (and the five pinned reason strings) hold."""
    from elasticsearch_tpu.serving import router as dispatch_router
    return dispatch_router.choose_split(
        batch, n_rows, int(queue_depth), dp, n_shards, _cfg["min_rows"])


def decide(leg: str, n_rows: int, has_mesh_state: bool = True,
           batch=None, queue_depth: int = 0):
    """Route one serving dispatch: returns the mesh to execute on —
    the full serving mesh, or (dp > 1) one dp-group submesh — or None
    for single-device. Counts the decision (the router half of
    `_nodes/stats indices.mesh`).

    `batch` is the dispatch's PADDED query bucket (full-mesh programs
    split it along dp, so it must divide); `queue_depth` the caller's
    live load signal — queued + in-flight dispatches beyond this one
    (the continuous batcher's scheduler state)."""
    global _rr
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    mesh = serving_mesh()
    reason = None
    if mesh is None:
        reason = "no_mesh"
    elif not has_mesh_state:
        reason = "no_sharded_corpus"
    elif n_rows < _cfg["min_rows"]:
        reason = "corpus_below_min_rows"
    split = group_idx = None
    if reason is None:
        dp = mesh_lib.dp_size(mesh)
        if dp > 1:
            split, split_reason = _choose_split(
                batch, n_rows, int(queue_depth), dp,
                mesh_lib.shard_size(mesh))
    with _lock:
        _counters["searches"][leg] = _counters["searches"].get(leg, 0) + 1
        if reason is not None:
            _counters["decisions_single_device"] += 1
            _counters["reasons"][reason] = \
                _counters["reasons"].get(reason, 0) + 1
            return None
        _counters["decisions_mesh"] += 1
        if split is not None:
            _counters["dp_routes"][split] += 1
            _counters["dp_reasons"][split_reason] = \
                _counters["dp_reasons"].get(split_reason, 0) + 1
            if split == "dp":
                group_idx = _rr
                _rr = (_rr + 1) % mesh_lib.dp_size(mesh)
                gd = _counters["dp_group_dispatches"]
                gd[group_idx] = gd.get(group_idx, 0) + 1
    if group_idx is not None:
        return dp_groups(mesh)[group_idx]
    return mesh


def reclassify_single(reason: str) -> None:
    """A leg accepted a mesh route but discovered mid-leg that the
    sharded program can't hold its result contract (e.g. a BM25 ranked
    window deeper than one shard's slot range): move the already-counted
    mesh decision over to single-device so the router stats reflect
    where the dispatch actually ran."""
    with _lock:
        if _counters["decisions_mesh"] > 0:
            _counters["decisions_mesh"] -= 1
        _counters["decisions_single_device"] += 1
        _counters["reasons"][reason] = \
            _counters["reasons"].get(reason, 0) + 1


def record_leg(leg: str, local_nanos: int, merge_nanos: int,
               collective_bytes: int) -> None:
    """Accumulate one sharded dispatch's timings: `local` is the SPMD
    program (shard-local work + the in-program ICI merge), `merge` the
    host-side result shaping, `collective_bytes` the analytic all-gather
    payload (S * Q * k * (score + id bytes))."""
    with _lock:
        entry = _counters["legs"].setdefault(
            leg, {"local_nanos": 0, "merge_nanos": 0,
                  "collective_bytes": 0, "dispatches": 0})
        entry["local_nanos"] += int(local_nanos)
        entry["merge_nanos"] += int(merge_nanos)
        entry["collective_bytes"] += int(collective_bytes)
        entry["dispatches"] += 1


def gather_bytes(n_shards: int, n_queries: int, k: int,
                 bytes_per_slot: int = 8) -> int:
    """Analytic all-gather payload of one [S, Q, k] candidate merge
    (f32 score + int32 id = 8 bytes/slot by default)."""
    return int(n_shards) * int(n_queries) * int(k) * int(bytes_per_slot)


def stats() -> dict:
    """`_nodes/stats indices.mesh` section."""
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.serving import router as dispatch_router
    mesh = serving_mesh()
    # shard-axis size, not devices.size: the two differ once dp > 1
    n_shards = 0 if mesh is None else mesh_lib.shard_size(mesh)
    dp = 0 if mesh is None else mesh_lib.dp_size(mesh)
    with _lock:
        return {
            "available": mesh is not None,
            "num_shards": n_shards,
            "dp": dp,
            "devices": {"total": n_shards * dp, "shard_axis": n_shards,
                        "dp_axis": dp},
            "min_rows": _cfg["min_rows"],
            "hbm": {
                "budget_bytes": _cfg["hbm_budget_bytes"],
                "rejections": _counters["hbm_rejections"],
                "last_rejected_bytes":
                    _counters["hbm_last_rejected_bytes"],
                "accepted_bytes_high_water":
                    _counters["hbm_accepted_bytes"],
            },
            "router": {
                "mesh": _counters["decisions_mesh"],
                "single_device": _counters["decisions_single_device"],
                "reasons": dict(_counters["reasons"]),
                "searches": dict(_counters["searches"]),
                # dp-vs-shard split of mesh-accepted dispatches, with
                # reasons and the per-group round-robin spread (dp > 1)
                "dp": {
                    "routes": dict(_counters["dp_routes"]),
                    "reasons": dict(_counters["dp_reasons"]),
                    "group_dispatches": {
                        str(g): n for g, n in sorted(
                            _counters["dp_group_dispatches"].items())},
                },
                # unified per-dispatch cost router (serving/router.py):
                # copy-selection / split / placement decisions with
                # reasons, plus the live per-node cost estimates
                "dispatch": dispatch_router.stats(),
            },
            "legs": {leg: dict(v)
                     for leg, v in sorted(_counters["legs"].items())},
        }


def reset(full: bool = False) -> None:
    """Zero the counters (tests). full=True also drops the config and the
    cached mesh back to auto defaults."""
    global _mesh, _mesh_built, _rr, _cfg_epoch
    from elasticsearch_tpu.serving import router as dispatch_router
    dispatch_router.reset()
    with _lock:
        _cfg_epoch += 1
        _counters["decisions_mesh"] = 0
        _counters["decisions_single_device"] = 0
        _counters["reasons"].clear()
        _counters["legs"].clear()
        _counters["dp_routes"] = {"shard": 0, "dp": 0}
        _counters["dp_reasons"].clear()
        _counters["dp_group_dispatches"].clear()
        _counters["hbm_rejections"] = 0
        _counters["hbm_last_rejected_bytes"] = 0
        _counters["hbm_accepted_bytes"] = 0
        _rr = 0
        for leg in _counters["searches"]:
            _counters["searches"][leg] = 0
        if full:
            _cfg.update({"enabled": None, "num_shards": None,
                         "min_rows": DEFAULT_MIN_ROWS, "dp": None,
                         "hbm_budget_bytes": None})
            _mesh, _mesh_built = None, False
            _groups.clear()
            _shard_meshes.clear()
