"""Host-side mesh routing policy: single-device vs SPMD per dispatch.

The reference routes every search through a coordinator that fans out to
however many shards the index was created with — shard count is a static
index property. Here the analogous decision is DYNAMIC and per dispatch:
a corpus small enough that one chip's matmul beats the all-gather merge
should stay on one device, a corpus at HBM scale must spread. This module
owns that decision for every serving leg (exact kNN, IVF, BM25), plus the
process-wide serving mesh the sharded kernels execute on, and the
counters `_nodes/stats indices.mesh` / `profile.mesh` report.

Settings (read once at node boot, `node.py` calls `configure`):

  search.mesh.enabled      true | false | unset (auto: mesh when >1
                           device is visible)
  search.mesh.num_shards   mesh shard-axis size (default: all visible
                           devices)
  search.mesh.min_rows     corpora below this many rows stay
                           single-device (the all-gather merge + per-leg
                           SPMD overhead only pays for itself once the
                           local matmul dominates; default 32768)

The policy is process-wide like `ops/dispatch.DISPATCH` — one physical
mesh serves every index on the node, so per-index state would only
duplicate the counters.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)

# below this many corpus rows the single-device program wins: the sharded
# program's fixed costs (S-way dispatch, [S, Q, k] all-gather, merge) are
# corpus-size independent, while the local matmul saving scales with rows
DEFAULT_MIN_ROWS = 32_768

_lock = threading.Lock()
_cfg = {"enabled": None, "num_shards": None, "min_rows": DEFAULT_MIN_ROWS}
_mesh = None          # cached jax Mesh (built lazily)
_mesh_built = False   # latch: None is a valid cache value (no mesh)

_counters = {
    "decisions_mesh": 0,
    "decisions_single_device": 0,
    "searches": {"knn": 0, "ivf": 0, "bm25": 0},
    "reasons": {},            # reason -> count (single-device routes)
    # per-leg timing: local = the SPMD program (shard-local score + ICI
    # merge, one compiled unit), merge = host-side result shaping
    "legs": {},               # leg -> {local_nanos, merge_nanos,
                              #         collective_bytes, dispatches}
}


_UNSET = object()


def configure(enabled=_UNSET, num_shards=_UNSET, min_rows=_UNSET) -> None:
    """Install `search.mesh.*` settings. PARTIAL update: only the
    keyword arguments the caller passes change — a node that sets one
    key must not clobber the others an earlier in-process node
    configured (same rule as the dispatcher's warmup policy). Passing
    None explicitly resets that key to auto/default. Drops the cached
    mesh so the next dispatch rebuilds against the new config."""
    global _mesh, _mesh_built
    with _lock:
        if enabled is not _UNSET:
            _cfg["enabled"] = enabled
        if num_shards is not _UNSET:
            _cfg["num_shards"] = (int(num_shards)
                                  if num_shards is not None else None)
        if min_rows is not _UNSET:
            _cfg["min_rows"] = (int(min_rows) if min_rows is not None
                                else DEFAULT_MIN_ROWS)
        _mesh, _mesh_built = None, False


def min_rows() -> int:
    return _cfg["min_rows"]


def serving_mesh():
    """The process-wide (dp=1, shard=S) serving mesh, or None when mesh
    execution is off (disabled, or fewer than 2 usable devices)."""
    global _mesh, _mesh_built
    with _lock:
        if _mesh_built:
            return _mesh
    mesh = None
    if _cfg["enabled"] is not False:
        try:
            import jax

            from elasticsearch_tpu.parallel import mesh as mesh_lib
            n_dev = len(jax.devices())
            n = _cfg["num_shards"] if _cfg["num_shards"] else n_dev
            n = min(n, n_dev)
            if n >= 2:
                mesh = mesh_lib.make_mesh(num_shards=n, dp=1)
        except Exception:
            # the latch below caches this None for the process lifetime:
            # without a log line a multi-chip node would silently serve
            # single-device until restart (stats only show available:
            # false, not why)
            logger.warning("mesh serving disabled: serving-mesh build "
                           "failed (latched off until restart or "
                           "reconfigure)", exc_info=True)
            mesh = None
    with _lock:
        if _mesh_built:
            # another thread won the build race: keep ITS object — the
            # identity-compared caches (store append path, lexical
            # mesh-CSR, sharded IVF pytree) all key on the cached mesh,
            # and caching a second equal-but-distinct Mesh would force
            # each of them through one redundant corpus re-upload
            return _mesh
        _mesh, _mesh_built = mesh, True
        return _mesh


def num_shards() -> int:
    mesh = serving_mesh()
    if mesh is None:
        return 0
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    return mesh.shape[mesh_lib.SHARD_AXIS]


def eligible(n_rows: int) -> bool:
    """Build-time check (no decision counted): is this corpus one the
    router could ever send to the mesh? Gates the sharded upload at
    refresh so small indexes never pay the second resident copy."""
    return (n_rows >= _cfg["min_rows"] and _cfg["enabled"] is not False
            and serving_mesh() is not None)


def decide(leg: str, n_rows: int, has_mesh_state: bool = True):
    """Route one serving dispatch: returns the mesh to execute on, or
    None for single-device. Counts the decision (the router half of
    `_nodes/stats indices.mesh`)."""
    mesh = serving_mesh()
    reason = None
    if mesh is None:
        reason = "no_mesh"
    elif not has_mesh_state:
        reason = "no_sharded_corpus"
    elif n_rows < _cfg["min_rows"]:
        reason = "corpus_below_min_rows"
    with _lock:
        _counters["searches"][leg] = _counters["searches"].get(leg, 0) + 1
        if reason is None:
            _counters["decisions_mesh"] += 1
            return mesh
        _counters["decisions_single_device"] += 1
        _counters["reasons"][reason] = \
            _counters["reasons"].get(reason, 0) + 1
        return None


def reclassify_single(reason: str) -> None:
    """A leg accepted a mesh route but discovered mid-leg that the
    sharded program can't hold its result contract (e.g. a BM25 ranked
    window deeper than one shard's slot range): move the already-counted
    mesh decision over to single-device so the router stats reflect
    where the dispatch actually ran."""
    with _lock:
        if _counters["decisions_mesh"] > 0:
            _counters["decisions_mesh"] -= 1
        _counters["decisions_single_device"] += 1
        _counters["reasons"][reason] = \
            _counters["reasons"].get(reason, 0) + 1


def record_leg(leg: str, local_nanos: int, merge_nanos: int,
               collective_bytes: int) -> None:
    """Accumulate one sharded dispatch's timings: `local` is the SPMD
    program (shard-local work + the in-program ICI merge), `merge` the
    host-side result shaping, `collective_bytes` the analytic all-gather
    payload (S * Q * k * (score + id bytes))."""
    with _lock:
        entry = _counters["legs"].setdefault(
            leg, {"local_nanos": 0, "merge_nanos": 0,
                  "collective_bytes": 0, "dispatches": 0})
        entry["local_nanos"] += int(local_nanos)
        entry["merge_nanos"] += int(merge_nanos)
        entry["collective_bytes"] += int(collective_bytes)
        entry["dispatches"] += 1


def gather_bytes(n_shards: int, n_queries: int, k: int,
                 bytes_per_slot: int = 8) -> int:
    """Analytic all-gather payload of one [S, Q, k] candidate merge
    (f32 score + int32 id = 8 bytes/slot by default)."""
    return int(n_shards) * int(n_queries) * int(k) * int(bytes_per_slot)


def stats() -> dict:
    """`_nodes/stats indices.mesh` section."""
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    mesh = serving_mesh()
    # shard-axis size, not devices.size: the two differ once dp > 1
    n_shards = (0 if mesh is None
                else int(mesh.shape[mesh_lib.SHARD_AXIS]))
    with _lock:
        return {
            "available": mesh is not None,
            "num_shards": n_shards,
            "min_rows": _cfg["min_rows"],
            "router": {
                "mesh": _counters["decisions_mesh"],
                "single_device": _counters["decisions_single_device"],
                "reasons": dict(_counters["reasons"]),
                "searches": dict(_counters["searches"]),
            },
            "legs": {leg: dict(v)
                     for leg, v in sorted(_counters["legs"].items())},
        }


def reset(full: bool = False) -> None:
    """Zero the counters (tests). full=True also drops the config and the
    cached mesh back to auto defaults."""
    global _mesh, _mesh_built
    with _lock:
        _counters["decisions_mesh"] = 0
        _counters["decisions_single_device"] = 0
        _counters["reasons"].clear()
        _counters["legs"].clear()
        for leg in _counters["searches"]:
            _counters["searches"][leg] = 0
        if full:
            _cfg.update({"enabled": None, "num_shards": None,
                         "min_rows": DEFAULT_MIN_ROWS})
            _mesh, _mesh_built = None, False
