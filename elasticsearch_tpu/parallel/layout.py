"""Spec-driven mesh layout: ONE partition-rule table for every corpus pytree.

Before the dp axis, each mesh kernel hand-built its PartitionSpecs three
times over — once for the host→device upload (`build_sharded_corpus`,
`build_sharded_partitions`, the BM25 tile mirrors), once for the
`shard_map` in_specs, and once for the warmup ShapeDtypeStructs — and a
dp-replicated layout would have meant widening every copy by hand. This
module is the `make_shard_and_gather_fns` shape from the reference pjit
stacks (SNIPPETS.md [2]/[3]): partition rules keyed by REGEX over leaf
names in the corpus pytree, expanded rank-aware into PartitionSpecs, so
one table drives

  * `shard_put`    — host pytree → mesh-resident pytree (one sharded
                     device_put per leaf; replication across the dp axis
                     falls out of the NamedSharding, no per-kernel code),
  * `view_for`     — an already-resident pytree re-laid onto another
                     mesh (the dp-group views: the target group's devices
                     already hold every shard of a dp-replicated array,
                     so this is device-side, never a host round-trip),
  * `in_specs_for` — the `shard_map` in_specs for a kernel consuming the
                     pytree,
  * `shape_specs`  — ShapeDtypeStructs with NamedShardings baked in (the
                     AOT warmup grid keys to the same executables live
                     traffic dispatches).

Rule kinds (expanded against each leaf's rank):

  replicated    P()                    — routing tables every shard scans
                                         (IVF centroids, BM25 tile CSR)
  shard_rows    P("shard", None, ...)  — corpus rows split over the shard
                                         axis, replicated across dp
  dp_batch      P("dp", None, ...)     — query batches split over dp,
                                         replicated across shards
  dp_by_shard   P("dp", "shard", ...)  — per-query row masks: batch over
                                         dp, row dimension over shard
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

from elasticsearch_tpu.parallel import mesh as mesh_lib

REPLICATED = "replicated"
SHARD_ROWS = "shard_rows"
DP_BATCH = "dp_batch"
DP_BY_SHARD = "dp_by_shard"

# the corpus-pytree rule table: first regex match over the leaf name
# wins. Names come from the pytree path (NamedTuple field / dict key) —
# one table covers the exact-kNN corpus, the IVF layout, and the BM25
# tile mirrors, so a new field type gets its layout by naming, not by a
# new hand-built spec.
PARTITION_RULES: Tuple[Tuple[str, str], ...] = (
    (r"centroid", REPLICATED),        # IVF routing tables
    (r"tile_", REPLICATED),           # lexical impact CSR (scan is
                                      # replicated; the board shards)
    (r"quer", DP_BATCH),              # query batches
    # corpus rows + per-row metadata — including the packed
    # quantization-ladder leaves (int4 nibble / binary sign-bit
    # matrices) and their per-row aux scales: the codec packs WITHIN a
    # row (`quant/codec.py`), so packed matrices shard over rows
    # exactly like f32 ones and need no rule of their own
    (r".*", SHARD_ROWS),
)


def _expand(kind: str, rank: int):
    """Rule kind → concrete PartitionSpec at this leaf's rank."""
    from jax.sharding import PartitionSpec as P
    if kind == REPLICATED:
        return P()
    if kind == SHARD_ROWS:
        return P(mesh_lib.SHARD_AXIS, *([None] * (rank - 1)))
    if kind == DP_BATCH:
        return P(mesh_lib.DP_AXIS, *([None] * (rank - 1)))
    if kind == DP_BY_SHARD:
        return P(mesh_lib.DP_AXIS, mesh_lib.SHARD_AXIS,
                 *([None] * (rank - 2)))
    raise ValueError(f"unknown partition rule kind [{kind}]")


def spec_for(name: str, rank: int,
             rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """PartitionSpec for one named leaf (first matching rule wins)."""
    for pattern, kind in rules:
        if re.search(pattern, name):
            return _expand(kind, rank)
    raise ValueError(f"no partition rule matches leaf [{name}]")


def _leaf_name(path) -> str:
    """Normalized leaf name from a tree path (NamedTuple attr / dict
    key / sequence index)."""
    import jax
    return re.sub(r"[^A-Za-z0-9_]+", "", jax.tree_util.keystr(path))


def tree_specs(tree, rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """Pytree of PartitionSpecs matching `tree`'s structure, rule-matched
    by leaf name and expanded by leaf rank."""
    import jax

    def one(path, leaf):
        return spec_for(_leaf_name(path), getattr(leaf, "ndim", 0), rules)
    return jax.tree_util.tree_map_with_path(one, tree)


def make_shard_and_gather_fns(mesh, tree,
                              rules: Sequence[Tuple[str, str]]
                              = PARTITION_RULES):
    """(shard_fns, gather_fns) pytrees for `tree` on `mesh` — the
    SNIPPETS exemplar shape. shard_fns place host leaves onto the mesh
    with their rule-matched sharding; gather_fns bring mesh leaves back
    to host numpy."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_specs(tree, rules)

    def make_shard(spec):
        return lambda x: jax.device_put(x, NamedSharding(mesh, spec))

    def make_gather(spec):
        return lambda x: jax.device_get(x)

    return (jax.tree_util.tree_map(make_shard, specs),
            jax.tree_util.tree_map(make_gather, specs))


def shard_put(tree, mesh,
              rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """Host pytree → mesh-resident pytree: one sharded device_put per
    leaf, specs from the rule table. A spec that leaves the dp axis
    unmapped (everything but `dp_batch`) replicates across dp rows by
    construction — every dp group holds a full copy of the sharded
    corpus, which is what makes the group views in `view_for` free."""
    import jax
    from jax.sharding import NamedSharding

    def one(path, leaf):
        spec = spec_for(_leaf_name(path), getattr(leaf, "ndim", 0), rules)
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map_with_path(one, tree)


def view_for(tree, mesh,
             rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """Re-lay an already-mesh-resident pytree onto `mesh` (a dp-group
    submesh of the mesh it lives on) with the same rule-matched specs.

    Because the source is dp-replicated, the target group's devices
    already hold every shard this view needs, so the device_put aliases
    resident buffers (measured ~free) — a group view is a ZERO-COPY
    window onto one coherent corpus snapshot, never a second version."""
    return shard_put(tree, mesh, rules)


def shape_specs(tree, mesh,
                rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """ShapeDtypeStruct pytree with NamedShardings baked in — warmup
    entries built from this key to the SAME AOT executables the live
    sharded dispatches use (`ops/dispatch._leaf_sig` keys on the
    NamedSharding)."""
    import jax
    from jax.sharding import NamedSharding

    def one(path, leaf):
        spec = spec_for(_leaf_name(path), getattr(leaf, "ndim", 0), rules)
        return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype,
                                    sharding=NamedSharding(mesh, spec))
    return jax.tree_util.tree_map_with_path(one, tree)


def in_specs_for(tree,
                 rules: Sequence[Tuple[str, str]] = PARTITION_RULES):
    """`shard_map` in_specs pytree for a kernel consuming `tree` — the
    same rule table that laid the data out, so the specs can never drift
    from the residency (the hand-built-spec divergence class TPU007
    lints for)."""
    return tree_specs(tree, rules)


def query_spec(rank: int = 2):
    """Query-batch spec: split over dp, replicated across shards."""
    return _expand(DP_BATCH, rank)


def rows_spec(rank: int):
    """Corpus-row spec: rows over shard, replicated across dp."""
    return _expand(SHARD_ROWS, rank)


def replicated_spec():
    return _expand(REPLICATED, 0)


def mask_spec(rank: int):
    """Filter-mask spec: [rows] masks shard with the corpus, [Q, rows]
    masks split batch over dp and rows over shard."""
    return _expand(SHARD_ROWS if rank == 1 else DP_BY_SHARD, rank)
