"""Server bootstrap: `python -m elasticsearch_tpu.server [--port N] [--data DIR]`.

The CLI/bootstrap layer (reference: `bootstrap/Elasticsearch.main:75` →
`Bootstrap.init:334` → `Node.start:682`): builds the node, registers REST
handlers, binds HTTP, installs signal handlers, runs until stopped.
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def _honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS from the environment actually stick.

    The experimental axon TPU plugin force-sets `jax_platforms="axon,cpu"`
    at import, overriding the environment variable; a CPU-only deployment
    (or CI) would then block on TPU tunnel initialization at the first
    device query. Apply the operator's env choice through jax.config BEFORE
    any backend touch — harmless when unset (TPU stays the default)."""
    import os
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already initialized or jax absent: leave as-is


def _http_ssl_context(settings):
    """http.ssl.* -> server SSLContext (xpack.security.http.ssl analog):
    client certificates optional by default; plaintext on a TLS port
    fails the handshake."""
    from elasticsearch_tpu.transport.tls import TlsConfig
    cfg = TlsConfig.from_settings(settings or {}, prefix="http.ssl",
                                  default_client_auth="none")
    return cfg.server_context() if cfg is not None else None


def main(argv=None) -> int:
    _honor_jax_platforms_env()
    parser = argparse.ArgumentParser(prog="elasticsearch-tpu")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--data", default="./data")
    parser.add_argument("--name", default="node-0")
    parser.add_argument("--cluster-name", default="tpu-search")
    parser.add_argument("-E", action="append", default=[], metavar="KEY=VALUE",
                        help="setting override, e.g. -E xpack.security.enabled=true")
    args = parser.parse_args(argv)
    settings = {}
    for kv in args.E:
        key, _, value = kv.partition("=")
        settings[key] = {"true": True, "false": False}.get(value.lower(), value)

    from elasticsearch_tpu import bootstrap
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer

    # bootstrap checks + native hardening BEFORE the node exists
    # (reference: Bootstrap.init → initializeNatives → BootstrapChecks) —
    # both the single-node and the clustered deployment path run them
    check_settings = dict(settings)
    check_settings.setdefault("path.data", args.data)
    enforce = args.host not in ("127.0.0.1", "localhost", "::1")
    try:
        warnings = bootstrap.run_bootstrap_checks(check_settings,
                                                  enforce=enforce)
    except bootstrap.BootstrapCheckFailure as e:
        print(f"bootstrap checks failed: {e}", file=sys.stderr)
        return 78  # EX_CONFIG
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    natives = bootstrap.initialize_natives(check_settings)
    for err in natives.errors:
        print(f"warning: {err}", file=sys.stderr)

    def _csv(value):
        if value is None:
            return []
        if isinstance(value, (list, tuple)):
            return list(value)
        return [v.strip() for v in str(value).split(",") if v.strip()]

    seed_hosts = _csv(settings.get("discovery.seed_hosts"))
    seed_providers_configured = bool(settings.get("discovery.seed_providers"))
    if seed_providers_configured:
        # dynamic seed discovery (discovery-ec2/gce + the file provider)
        # appends to any static list; provider outages log, never block
        # boot — the discovery loop re-resolves, so peers that were
        # unreachable at boot are found later
        from elasticsearch_tpu.cluster.seed_providers import (
            resolve_seed_hosts,
        )
        seed_hosts = list(dict.fromkeys(
            seed_hosts + resolve_seed_hosts(settings, args.data)))
    initial_masters = _csv(settings.get("cluster.initial_master_nodes"))
    # a configured provider makes this a CLUSTER node even when its first
    # resolution came back empty (a cloud-API blip must not silently boot
    # an independent single-node cluster on the shared data dir)
    cluster_mode = bool(seed_hosts or initial_masters
                        or seed_providers_configured)

    if cluster_mode:
        return _run_clustered(args, settings, seed_hosts, initial_masters,
                              bootstrap)

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all

    node = Node(args.data, node_name=args.name, cluster_name=args.cluster_name,
                settings=settings)
    node.natives = natives
    controller = RestController()
    register_all(controller, node)
    server = HttpServer(controller, host=args.host, port=args.port,
                        thread_pool=node.thread_pool,
                        ssl_context=_http_ssl_context(settings))

    async def run():
        # even a single-node deployment binds the binary transport when
        # transport.port is set: that's the endpoint OTHER clusters dial
        # for CCS/CCR (reference: every node binds 9300)
        transport = None
        if settings.get("transport.port") is not None:
            from elasticsearch_tpu.transport.tcp import TcpTransportService
            from elasticsearch_tpu.xpack.remote_cluster import (
                register_remote_handlers,
            )
            transport = TcpTransportService(
                args.name, host=args.host,
                port=int(settings["transport.port"]),
                loop=asyncio.get_running_loop())
            host, port = await transport.bind()
            register_remote_handlers(transport, node)
            print(f"[{args.name}] transport bound on {host}:{port}",
                  flush=True)
        await server.start()
        print(f"[{args.name}] listening on http://{args.host}:{server.port} "
              f"(data: {args.data})", flush=True)
        bootstrap.sd_notify("READY=1")  # systemd readiness, if supervised
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        if transport is not None:
            await transport.close()
        await server.stop()
        node.close()

    asyncio.run(run())
    return 0


def _run_clustered(args, settings, seed_hosts, initial_masters, bootstrap) -> int:
    """Boot a clustered node: transport bind → coordinator initial join →
    HTTP last (reference start order: `node/Node.java:682`)."""
    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.cluster.rest_node import ClusterAwareNode
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.cluster_actions import (
        ClusterRestAdapter, register_cluster_overrides,
    )
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer
    from elasticsearch_tpu.transport.tcp import (
        AsyncioScheduler, TcpTransportService,
    )

    node_id = args.name
    transport_port = int(settings.get("transport.port", 9300))
    if not initial_masters:
        print("cluster.initial_master_nodes is required with "
              "discovery.seed_hosts", file=sys.stderr)
        return 78

    # transport TLS + inter-node auth from settings/keystore
    # (xpack.security.transport.ssl analog; key material is secure settings)
    from elasticsearch_tpu.transport.tls import TlsConfig, TransportAuth
    try:
        tls = TlsConfig.from_settings(settings)
    except Exception as e:
        print(f"transport TLS misconfigured: {e}", file=sys.stderr)
        return 78
    auth = None
    auth_key = settings.get("cluster.auth.key")
    if not auth_key:
        # fail CLOSED on keystore errors: a wrong password must not boot
        # the node with transport auth silently disabled
        from elasticsearch_tpu.common.keystore import load_node_keystore
        try:
            ks = load_node_keystore(settings, args.data)
        except Exception as e:
            print(f"keystore load failed: {e}", file=sys.stderr)
            return 78
        if ks is not None:
            auth_key = ks.get("cluster.auth.key")
    if auth_key:
        auth = TransportAuth(str(auth_key).encode("utf-8"))

    async def run():
        loop = asyncio.get_running_loop()
        scheduler = AsyncioScheduler(loop)
        transport = TcpTransportService(node_id, host=args.host,
                                        port=transport_port,
                                        tls=tls, auth=auth)
        host, port = await transport.bind()
        address = f"{host}:{port}"
        print(f"[{node_id}] transport bound on {address}", flush=True)

        initial = bootstrap_state(initial_masters,
                                  cluster_name=args.cluster_name)
        cluster_node = ClusterNode(
            node_id, args.data, transport, scheduler,
            seed_peers=[m for m in initial_masters if m != node_id],
            initial_state=initial, address=address)
        cluster_node.start()

        # seed-host discovery loop (PeerFinder analog): keep probing the
        # configured addresses until every one resolves to a node id, and
        # keep re-probing slowly afterwards so restarted peers re-resolve.
        # Configured providers re-resolve every pass (the reference's
        # FileBasedSeedHostsProvider / cloud providers are live lists:
        # autoscaling additions and unicast_hosts.txt edits take effect
        # without a restart).
        async def discover():
            use_providers = bool(settings.get("discovery.seed_providers"))
            targets = list(seed_hosts)
            while True:
                if use_providers:
                    from elasticsearch_tpu.cluster.seed_providers import (
                        resolve_seed_hosts,
                    )
                    dynamic = await asyncio.to_thread(
                        resolve_seed_hosts, settings, args.data)
                    static = settings.get("discovery.seed_hosts") or ""
                    static_list = ([s.strip() for s in str(static).split(",")
                                    if s.strip()]
                                   if not isinstance(static, (list, tuple))
                                   else list(static))
                    targets = list(dict.fromkeys(static_list + dynamic))
                all_known = True
                for hp in targets:
                    h, _, p = hp.rpartition(":")
                    h = h.strip("[]")  # bracketed IPv6
                    if not h or not p.isdigit():
                        continue
                    try:
                        await transport.probe_address(h, int(p))
                    except Exception:
                        all_known = False
                await asyncio.sleep(1.0 if not all_known else 5.0)

        discovery_task = loop.create_task(discover())

        controller = RestController()
        # ONE feature surface for both deployment shapes: the full Node
        # route set backed by distributed data-path overrides, with the
        # cluster-authoritative routes (health/state/index admin) layered
        # on top (last registration wins)
        import os as _os
        aware = ClusterAwareNode(
            _os.path.join(args.data, "_node_local"), cluster_node, loop,
            node_name=node_id, cluster_name=args.cluster_name,
            settings=settings)
        register_all(controller, aware)
        adapter = ClusterRestAdapter(cluster_node, loop)
        register_cluster_overrides(controller, adapter, aware=aware)
        # remote-cluster (CCS/CCR) server actions ride the same transport
        # the cluster uses internally (reference: one 9300 endpoint)
        from elasticsearch_tpu.xpack.remote_cluster import (
            register_remote_handlers,
        )
        register_remote_handlers(transport, aware)
        server = HttpServer(controller, host=args.host, port=args.port,
                            thread_pool=aware.thread_pool,
                            ssl_context=_http_ssl_context(settings))
        await server.start()
        aware.register_builtin_persistent_tasks()
        print(f"[{node_id}] listening on http://{args.host}:{server.port} "
              f"(data: {args.data}, cluster: {args.cluster_name})", flush=True)
        bootstrap.sd_notify("READY=1")

        stop = asyncio.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        discovery_task.cancel()
        await server.stop()
        cluster_node.stop()
        await transport.close()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
