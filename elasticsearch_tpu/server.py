"""Server bootstrap: `python -m elasticsearch_tpu.server [--port N] [--data DIR]`.

The CLI/bootstrap layer (reference: `bootstrap/Elasticsearch.main:75` →
`Bootstrap.init:334` → `Node.start:682`): builds the node, registers REST
handlers, binds HTTP, installs signal handlers, runs until stopped.
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="elasticsearch-tpu")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--data", default="./data")
    parser.add_argument("--name", default="node-0")
    parser.add_argument("--cluster-name", default="tpu-search")
    parser.add_argument("-E", action="append", default=[], metavar="KEY=VALUE",
                        help="setting override, e.g. -E xpack.security.enabled=true")
    args = parser.parse_args(argv)
    settings = {}
    for kv in args.E:
        key, _, value = kv.partition("=")
        settings[key] = {"true": True, "false": False}.get(value.lower(), value)

    from elasticsearch_tpu import bootstrap
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer

    # bootstrap checks + native hardening BEFORE the node exists
    # (reference: Bootstrap.init → initializeNatives → BootstrapChecks)
    check_settings = dict(settings)
    check_settings.setdefault("path.data", args.data)
    enforce = args.host not in ("127.0.0.1", "localhost", "::1")
    try:
        warnings = bootstrap.run_bootstrap_checks(check_settings,
                                                  enforce=enforce)
    except bootstrap.BootstrapCheckFailure as e:
        print(f"bootstrap checks failed: {e}", file=sys.stderr)
        return 78  # EX_CONFIG
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    natives = bootstrap.initialize_natives(check_settings)
    for err in natives.errors:
        print(f"warning: {err}", file=sys.stderr)

    node = Node(args.data, node_name=args.name, cluster_name=args.cluster_name,
                settings=settings)
    node.natives = natives
    controller = RestController()
    register_all(controller, node)
    server = HttpServer(controller, host=args.host, port=args.port)

    async def run():
        await server.start()
        print(f"[{args.name}] listening on http://{args.host}:{server.port} "
              f"(data: {args.data})", flush=True)
        bootstrap.sd_notify("READY=1")  # systemd readiness, if supervised
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        await server.stop()
        node.close()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
