"""The per-shard storage engine: versioned indexing, NRT refresh, commits.

Re-design of `index/engine/InternalEngine.java` (SURVEY.md §2.4, §3.3):

- every operation gets a seq_no from the LocalCheckpointTracker (`:821`)
  and an internal version; the LiveVersionMap resolves id→latest for
  version conflicts and realtime get (`planIndexingAsPrimary:996`);
- documents land in an in-memory SegmentBuilder; `refresh()` seals it into
  an immutable searchable segment (NRT visibility, default 1s in the
  reference `IndexService.maybeRefreshEngine:757`);
- `flush()` persists sealed segments + commit metadata and trims the
  translog below the commit, like Lucene commits + translog generations;
- updates/deletes are tombstones over earlier rows; `merge()` compacts
  segments dropping dead docs (Lucene background merges);
- on open, the engine recovers: load last commit, replay translog ops
  above the commit's local checkpoint (`recoverFromTranslog`).

Each document occupies a global "row" (monotonic per shard). The dense-vector
columns of sealed segments feed the device vector store at refresh; rows are
the join key between host postings/doc-values and device matrices.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from elasticsearch_tpu.common.errors import (
    DocumentMissingError, IllegalArgumentError, SearchEngineError,
    VersionConflictError,
)
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.index.segment import (
    Segment, SegmentBuilder, SegmentView, ShardReader,
)
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker, NO_OPS_PERFORMED
from elasticsearch_tpu.index.translog import OP_DELETE, OP_INDEX, OP_NOOP, Translog


class VersionValue(NamedTuple):
    seq_no: int
    primary_term: int
    version: int
    row: int          # global row of the live doc; -1 if deleted
    deleted: bool


class EngineResult(NamedTuple):
    doc_id: str
    seq_no: int
    primary_term: int
    version: int
    result: str       # "created" | "updated" | "deleted" | "noop"
    row: int


class Engine:
    def __init__(self, path: str, mapper_service: MapperService,
                 primary_term: int = 1, translog_sync: str = "request",
                 index_sort=None):
        self.path = path
        self.mapper_service = mapper_service
        self.primary_term = primary_term
        # (field, "asc"|"desc") — physical segment ordering at seal; must be
        # set BEFORE recovery so translog-replayed segments sort too
        self.index_sort = index_sort
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()

        self.segments: List[Segment] = []
        self.deleted_rows: Dict[int, set] = {}     # seg_id -> set(local ids)
        self.version_map: Dict[str, VersionValue] = {}
        self.tracker = LocalCheckpointTracker()
        self._next_row = 0
        self._next_seg_id = 0
        self._builder: Optional[SegmentBuilder] = None
        self._refresh_listeners: List[Callable[[ShardReader], None]] = []
        self._reader: Optional[ShardReader] = None
        # checkpoint of the newest durable commit: ops at or below this are
        # only guaranteed in the commit, not the translog (flush trims)
        self.last_commit_checkpoint: Optional[int] = None
        # shard layer installs a provider returning the minimum seq_no that
        # retention leases require kept (ReplicationTracker
        # .min_retained_seq_no); flush skips translog trimming while any
        # lease still needs history the commit would discard
        self.retained_seq_no_provider: Optional[Callable[[], int]] = None

        self._load_commit()
        self.translog = Translog(os.path.join(path, "translog"), sync_policy=translog_sync)
        self._recover_from_translog()
        self.refresh()

    # ------------------------------------------------------------------ write
    def index(self, doc_id: str, source: dict, *,
              seq_no: Optional[int] = None,
              primary_term: Optional[int] = None,
              version: Optional[int] = None,
              version_type: str = "internal",
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              op_type: str = "index",
              origin: str = "primary",
              routing: Optional[str] = None) -> EngineResult:
        """Index one document (primary assigns seq_no; replica replays it).

        Reference: `InternalEngine.index:843` → plan (`:996`) → Lucene add
        (`:902`) → translog (`:911`).
        """
        with self._lock:
            existing = self.version_map.get(doc_id)

            if origin == "primary":
                self._check_conflicts(doc_id, existing, version, version_type,
                                      if_seq_no, if_primary_term, op_type)
                seq_no = self.tracker.generate_seq_no()
                primary_term = self.primary_term
                if version_type in ("external", "external_gt",
                                    "external_gte"):
                    new_version = version
                else:
                    new_version = 1 if existing is None or existing.deleted else existing.version + 1
            else:
                if seq_no is None:
                    raise SearchEngineError("replica operations require a seq_no")
                primary_term = primary_term if primary_term is not None else self.primary_term
                new_version = version if version is not None else 1
                # replica out-of-order delivery: ignore ops older than current
                if existing is not None and existing.seq_no >= seq_no:
                    self.tracker.mark_processed(seq_no)
                    return EngineResult(doc_id, seq_no, primary_term,
                                        existing.version, "noop", existing.row)

            parsed = self.mapper_service.parse_document(doc_id, source)
            if routing is not None:
                # _routing metadata field: a doc value, so it survives
                # refresh/commit and returns on GET (RoutingFieldMapper)
                parsed.doc_values["_routing"] = routing
            # _primary_term/_version as doc values so search hits can
            # return them (seq_no itself lives in the segment)
            parsed.doc_values["_primary_term"] = int(primary_term or 1)
            parsed.doc_values["_version"] = int(new_version)
            builder = self._get_builder()
            local = builder.add(parsed, seq_no)
            row = builder.base + local
            self._next_row = row + 1

            created = existing is None or existing.deleted
            if existing is not None and not existing.deleted:
                self._tombstone(existing.row)

            self.version_map[doc_id] = VersionValue(seq_no, primary_term, new_version, row, False)
            op_entry = {"op": OP_INDEX, "id": doc_id, "source": source,
                        "seq_no": seq_no, "primary_term": primary_term,
                        "version": new_version}
            if routing is not None:
                op_entry["routing"] = routing
            self.translog.add(op_entry)
            self.tracker.mark_processed(seq_no)
            return EngineResult(doc_id, seq_no, primary_term, new_version,
                                "created" if created else "updated", row)

    def delete(self, doc_id: str, *,
               seq_no: Optional[int] = None,
               primary_term: Optional[int] = None,
               version: Optional[int] = None,
               version_type: str = "internal",
               if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None,
               origin: str = "primary") -> EngineResult:
        with self._lock:
            existing = self.version_map.get(doc_id)

            if origin == "primary":
                if existing is None or existing.deleted:
                    raise DocumentMissingError(f"[{doc_id}]: document missing")
                self._check_conflicts(doc_id, existing, version, version_type,
                                      if_seq_no, if_primary_term, "delete")
                seq_no = self.tracker.generate_seq_no()
                primary_term = self.primary_term
                new_version = existing.version + 1 if version_type == "internal" else version
            else:
                if seq_no is None:
                    raise SearchEngineError("replica operations require a seq_no")
                primary_term = primary_term if primary_term is not None else self.primary_term
                new_version = version if version is not None else 1
                if existing is not None and existing.seq_no >= seq_no:
                    self.tracker.mark_processed(seq_no)
                    return EngineResult(doc_id, seq_no, primary_term,
                                        existing.version, "noop", existing.row)

            if existing is not None and not existing.deleted:
                self._tombstone(existing.row)
            self.version_map[doc_id] = VersionValue(seq_no, primary_term,
                                                    new_version or 1, -1, True)
            self.translog.add({"op": OP_DELETE, "id": doc_id, "seq_no": seq_no,
                               "primary_term": primary_term, "version": new_version or 1})
            self.tracker.mark_processed(seq_no)
            return EngineResult(doc_id, seq_no, primary_term, new_version or 1,
                                "deleted", -1)

    def noop(self, seq_no: int, reason: str = "") -> None:
        """Fill a seq_no gap (reference: InternalEngine.noOp for primary failover)."""
        with self._lock:
            self.translog.add({"op": OP_NOOP, "seq_no": seq_no, "reason": reason,
                               "primary_term": self.primary_term})
            self.tracker.mark_processed(seq_no)

    def _check_conflicts(self, doc_id, existing, version, version_type,
                         if_seq_no, if_primary_term, op_type) -> None:
        if op_type == "create" and existing is not None and not existing.deleted:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, document already exists "
                f"(current version [{existing.version}])")
        if if_seq_no is not None or if_primary_term is not None:
            if existing is None or existing.deleted:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, document does not exist")
            if (if_seq_no is not None and existing.seq_no != if_seq_no) or \
               (if_primary_term is not None and existing.primary_term != if_primary_term):
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, required seqNo [{if_seq_no}], "
                    f"primary term [{if_primary_term}], current document has "
                    f"seqNo [{existing.seq_no}] and primary term [{existing.primary_term}]")
        if version_type in ("external", "external_gt", "external_gte") \
                and version is None:
            raise IllegalArgumentError(
                f"[{doc_id}]: external version type requires an explicit "
                f"version")
        if version_type in ("external", "external_gt", "external_gte") \
                and version is not None:
            # a missing doc compares as NOT_FOUND (-1), so external
            # version 0 is creatable (VersionType.EXTERNAL)
            current = -1 if existing is None or existing.deleted \
                else existing.version
            # external requires strictly greater; external_gte allows equal
            if (version < current) if version_type == "external_gte" \
                    else (version <= current):
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, current version [{current}] is higher "
                    f"or equal to the one provided [{version}]")
        elif version is not None and version_type == "internal":
            current = None if existing is None or existing.deleted else existing.version
            if current != version:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, current version [{current}] is "
                    f"different than the one provided [{version}]")

    def _tombstone(self, row: int) -> None:
        for seg in self.segments:
            if seg.base <= row < seg.base + seg.num_docs:
                self.deleted_rows.setdefault(seg.seg_id, set()).add(row - seg.base)
                return
        builder = self._builder
        if builder is not None and builder.base <= row < builder.base + builder.num_docs:
            # tombstone applies when the builder seals
            self.deleted_rows.setdefault(builder.seg_id, set()).add(row - builder.base)

    def _get_builder(self) -> SegmentBuilder:
        if self._builder is None:
            self._builder = SegmentBuilder(self._next_seg_id, self._next_row)
            self._next_seg_id += 1
        return self._builder

    # ------------------------------------------------------------------- read
    def get(self, doc_id: str, realtime: bool = True) -> Optional[dict]:
        """Realtime GET (reference: ShardGetService — reads through the
        version map / translog without waiting for refresh)."""
        with self._lock:
            vv = self.version_map.get(doc_id)
            if vv is None or vv.deleted:
                return None
            if not realtime:
                reader = self.acquire_searcher()
                src = reader.get_source(vv.row)
                out = None if src is None else {
                    "_id": doc_id, "_version": vv.version, "_seq_no": vv.seq_no,
                    "_primary_term": vv.primary_term, "_source": src, "_row": vv.row}
            else:
                src = self._source_of_row(vv.row)
                out = None if src is None else {
                    "_id": doc_id, "_version": vv.version, "_seq_no": vv.seq_no,
                    "_primary_term": vv.primary_term, "_source": src,
                    "_row": vv.row}
            if out is not None:
                routing = self._routing_of_row(vv.row)
                if routing is not None:
                    out["_routing"] = routing
            return out

    def _routing_of_row(self, row: int) -> Optional[str]:
        for seg in self.segments:
            if seg.base <= row < seg.base + seg.num_docs:
                col = seg.doc_values.get("_routing")
                return col.get(row - seg.base) if col else None
        b = self._builder
        if b is not None and b.base <= row < b.base + b.num_docs:
            return b._doc_values.get("_routing", {}).get(row - b.base)
        return None

    def _source_of_row(self, row: int) -> Optional[dict]:
        for seg in self.segments:
            if seg.base <= row < seg.base + seg.num_docs:
                return seg.sources[row - seg.base]
        b = self._builder
        if b is not None and b.base <= row < b.base + b.num_docs:
            return b._sources[row - b.base]
        return None

    def _seal_builder(self):
        """Seal the current buffer, applying the index sort when configured
        (index.sort.field: docs reorder physically; row-keyed bookkeeping —
        version map, tombstones — is remapped to the new locals)."""
        builder = self._builder
        sort = getattr(self, "index_sort", None)
        order = None
        if sort:
            field, direction = sort
            vals = builder._doc_values.get(field, {})

            def sort_key(local):
                v = vals[local]
                if isinstance(v, list):  # multi-valued: min (asc) / max
                    v = (max(v) if direction == "desc" else min(v))                         if v else None
                # type-ranked tuple: mixed numeric/str values must not
                # TypeError the seal (the docs were already accepted)
                return (v is None, isinstance(v, str), v if v is not None
                        else 0)

            present = [l for l in range(builder.num_docs) if l in vals]
            absent = [l for l in range(builder.num_docs) if l not in vals]
            present.sort(key=sort_key, reverse=(direction == "desc"))
            # index.sort.missing defaults to _last for either direction
            order = present + absent
        seg = builder.seal(order=order)
        if order is not None:
            base = builder.base
            # O(buffered docs): rows come from the sealed segment's id order
            for local, doc_id in enumerate(seg.ids):
                vv = self.version_map.get(doc_id)
                if vv is not None and base <= vv.row < base + seg.num_docs:
                    self.version_map[doc_id] = vv._replace(row=base + local)
            inv = {old: new for new, old in enumerate(order)}
            dels = self.deleted_rows.get(builder.seg_id)
            if dels:
                self.deleted_rows[builder.seg_id] = {inv[l] for l in dels}
        return seg

    def refresh(self) -> ShardReader:
        """Seal the indexing buffer; make recent ops searchable (NRT refresh)."""
        with self._lock:
            if self._builder is not None and self._builder.num_docs > 0:
                self.segments.append(self._seal_builder())
                self._builder = None
            views = [SegmentView(seg, self.deleted_rows.get(seg.seg_id))
                     for seg in self.segments]
            self._reader = ShardReader(views)
            for listener in self._refresh_listeners:
                listener(self._reader)
            return self._reader

    def acquire_searcher(self) -> ShardReader:
        with self._lock:
            if self._reader is None:
                self.refresh()
            return self._reader

    def add_refresh_listener(self, listener: Callable[[ShardReader], None]) -> None:
        self._refresh_listeners.append(listener)

    # ------------------------------------------------------------- durability
    def flush(self) -> None:
        """Commit: persist segments + metadata, trim translog (Lucene commit)."""
        with self._lock:
            self.refresh()
            commit = {
                "local_checkpoint": self.tracker.checkpoint,
                "max_seq_no": self.tracker.max_seq_no,
                "primary_term": self.primary_term,
                "next_row": self._next_row,
                "next_seg_id": self._next_seg_id,
            }
            tmp = os.path.join(self.path, "commit.tmp")
            with open(tmp, "wb") as f:
                pickle.dump({
                    "segments": self.segments,
                    "deleted_rows": self.deleted_rows,
                    "version_map": self.version_map,
                    "meta": commit,
                }, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.path, "commit.bin"))
            with open(os.path.join(self.path, "commit.json"), "w") as f:
                json.dump(commit, f)
            self.last_commit_checkpoint = commit["local_checkpoint"]
            self.translog.roll_generation()
            # retention-lease-aware trimming (ReplicationTracker.java:308):
            # a recovering copy's lease pins history the commit would drop
            retained = (self.retained_seq_no_provider()
                        if self.retained_seq_no_provider else
                        commit["local_checkpoint"] + 1)
            if retained > commit["local_checkpoint"]:
                self.translog.trim_below(
                    self.translog.generation,
                    min_retained_seq_no=commit["local_checkpoint"] + 1)

    def _load_commit(self) -> None:
        path = os.path.join(self.path, "commit.bin")
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = pickle.load(f)
        self.segments = data["segments"]
        self.deleted_rows = data["deleted_rows"]
        self.version_map = data["version_map"]
        meta = data["meta"]
        self._next_row = meta["next_row"]
        self._next_seg_id = meta["next_seg_id"]
        self.tracker = LocalCheckpointTracker(meta["max_seq_no"], meta["local_checkpoint"])
        self.last_commit_checkpoint = meta["local_checkpoint"]

    def _recover_from_translog(self) -> None:
        """Replay translog ops above the last commit's checkpoint."""
        from_seq = self.tracker.checkpoint + 1
        for op in self.translog.read_ops(from_seq):
            kind = op.get("op")
            if kind == OP_INDEX:
                self.index(op["id"], op["source"], seq_no=op["seq_no"],
                           primary_term=op.get("primary_term"),
                           version=op.get("version"), origin="replica",
                           routing=op.get("routing"))
            elif kind == OP_DELETE:
                try:
                    self.delete(op["id"], seq_no=op["seq_no"],
                                primary_term=op.get("primary_term"),
                                version=op.get("version"), origin="replica")
                except DocumentMissingError:
                    pass
            elif kind == OP_NOOP:
                self.tracker.mark_processed(op["seq_no"])

    # ---------------------------------------------------------------- merging
    def can_replay_from(self, from_seq_no: int) -> bool:
        """True when the translog still holds every op >= from_seq_no, so an
        ops-only peer recovery is safe. Once a flush has trimmed history,
        ops below the trim point live only in the commit files and the
        recovering copy needs phase 1 (file copy) first."""
        return from_seq_no >= self.translog.min_retained_seq_no

    def merge(self) -> None:
        """Compact all sealed segments into one, dropping tombstoned docs.

        The analog of a Lucene force-merge; rows are reassigned, so the
        device vector store must re-ingest after a merge (same contract as
        the reference rebuilding doc ids on merge).
        """
        with self._lock:
            self.refresh()
            if len(self.segments) <= 1 and not any(self.deleted_rows.values()):
                return
            builder = SegmentBuilder(self._next_seg_id, self._next_row)
            self._next_seg_id += 1
            reader = self._reader
            new_map: Dict[str, VersionValue] = {}
            for view in reader.views:
                seg = view.segment
                for local in range(seg.num_docs):
                    if not view.live[local]:
                        continue
                    doc_id = seg.ids[local]
                    vv = self.version_map.get(doc_id)
                    if vv is None or vv.deleted or vv.row != seg.base + local:
                        continue
                    parsed = self.mapper_service.parse_document(doc_id, seg.sources[local])
                    new_local = builder.add(parsed, int(seg.seq_nos[local]))
                    new_map[doc_id] = vv._replace(row=builder.base + new_local)
            self._next_row = builder.base + builder.num_docs
            if builder.num_docs:
                saved = self._builder
                self._builder = builder
                try:
                    merged = self._seal_builder()
                finally:
                    self._builder = saved
                self.segments = [merged]
                # the seal may have physically re-sorted: rows come from the
                # sealed segment's id order, not the pre-sort builder locals
                for local, doc_id in enumerate(merged.ids):
                    if doc_id in new_map:
                        new_map[doc_id] = new_map[doc_id]._replace(
                            row=merged.base + local)
            else:
                self.segments = []
            self.deleted_rows = {}
            for doc_id, vv in self.version_map.items():
                if vv.deleted:
                    new_map.setdefault(doc_id, vv)
            self.version_map = new_map
            self.refresh()

    # ------------------------------------------------------------------ stats
    @property
    def local_checkpoint(self) -> int:
        return self.tracker.checkpoint

    @property
    def max_seq_no(self) -> int:
        return self.tracker.max_seq_no

    def doc_count(self) -> int:
        return sum(1 for v in self.version_map.values() if not v.deleted)

    def close(self) -> None:
        with self._lock:
            self.translog.close()
