"""Per-shard write-ahead log with generations and checkpoints.

Re-design of the reference translog (`index/translog/Translog.java:85-106`):
append-only generation files plus a checkpoint file recording the current
generation and the durability horizon. Every operation is length-prefixed,
CRC-checked, and carries (seq_no, primary_term); recovery replays operations
above the last commit's local checkpoint, exactly like
`InternalEngine.recoverFromTranslog`.

Format per record:  vint(length) | payload | crc32(payload) as 4 bytes BE
Payload: StreamOutput generic dict {op, id, source?, seq_no, primary_term,
version}. Checkpoint file: JSON {generation, min_translog_generation,
global_checkpoint, max_seq_no}.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.common.serialization import StreamInput, StreamOutput

CHECKPOINT_FILE = "translog.ckp"

OP_INDEX = "index"
OP_DELETE = "delete"
OP_NOOP = "noop"


class TranslogCorruptedError(SearchEngineError):
    status = 500


class Translog:
    def __init__(self, directory: str, sync_policy: str = "request"):
        """sync_policy: 'request' fsyncs every add; 'async' leaves it to sync()."""
        self.directory = directory
        self.sync_policy = sync_policy
        os.makedirs(directory, exist_ok=True)
        ckp = self._read_checkpoint()
        self.generation = ckp.get("generation", 1)
        self.min_generation = ckp.get("min_translog_generation", self.generation)
        self.global_checkpoint = ckp.get("global_checkpoint", -1)
        self.max_seq_no = ckp.get("max_seq_no", -1)
        # lowest seq_no this translog still guarantees to hold; raised (and
        # persisted) only when a trim actually discards history
        self.min_retained_seq_no = ckp.get("min_retained_seq_no", 0)
        self._file = open(self._gen_path(self.generation), "ab")

    # -- paths / checkpoint ---------------------------------------------------
    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"translog-{gen}.tlog")

    def _read_checkpoint(self) -> dict:
        path = os.path.join(self.directory, CHECKPOINT_FILE)
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    def _write_checkpoint(self) -> None:
        path = os.path.join(self.directory, CHECKPOINT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "generation": self.generation,
                "min_translog_generation": self.min_generation,
                "global_checkpoint": self.global_checkpoint,
                "max_seq_no": self.max_seq_no,
                "min_retained_seq_no": self.min_retained_seq_no,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- write path -----------------------------------------------------------
    def add(self, op: Dict[str, Any]) -> None:
        """Append one operation (dict with op/id/seq_no/primary_term/...)."""
        out = StreamOutput()
        out.write_generic(op)
        payload = out.bytes()
        rec = StreamOutput()
        rec.write_vint(len(payload))
        rec.write_bytes(payload)
        rec.write_bytes(struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF))
        self._file.write(rec.bytes())
        self.max_seq_no = max(self.max_seq_no, op.get("seq_no", -1))
        if self.sync_policy == "request":
            self.sync()

    def sync(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())
        self._write_checkpoint()

    def update_global_checkpoint(self, value: int) -> None:
        if value > self.global_checkpoint:
            self.global_checkpoint = value

    def roll_generation(self) -> None:
        """Start a new generation file (reference: Translog.rollGeneration)."""
        self.sync()
        self._file.close()
        self.generation += 1
        self._file = open(self._gen_path(self.generation), "ab")
        self._write_checkpoint()

    def trim_below(self, generation: int,
                   min_retained_seq_no: Optional[int] = None) -> None:
        """Delete generations below `generation` (after a commit persists them).

        min_retained_seq_no: the lowest seq_no still guaranteed retained
        after the trim (the committing caller's checkpoint + 1)."""
        for gen in range(self.min_generation, generation):
            path = self._gen_path(gen)
            if os.path.exists(path):
                os.remove(path)
        self.min_generation = max(self.min_generation, generation)
        if min_retained_seq_no is not None:
            self.min_retained_seq_no = max(self.min_retained_seq_no,
                                           min_retained_seq_no)
        self._write_checkpoint()

    # -- read path ------------------------------------------------------------
    def _read_gen(self, gen: int) -> Iterator[Dict[str, Any]]:
        path = self._gen_path(gen)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        inp = StreamInput(data)
        while inp.remaining() > 0:
            try:
                length = inp.read_vint()
                payload = inp.read_bytes(length)
                crc = struct.unpack(">I", inp.read_bytes(4))[0]
            except SearchEngineError:
                raise TranslogCorruptedError(f"truncated translog record in generation {gen}")
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise TranslogCorruptedError(f"translog CRC mismatch in generation {gen}")
            yield StreamInput(payload).read_generic()

    def read_ops(self, from_seq_no: int = 0) -> List[Dict[str, Any]]:
        """All operations with seq_no >= from_seq_no, in log order.

        Serves both startup recovery (replay past the last commit) and
        ops-based peer recovery / CCR shard-changes
        (`RecoverySourceHandler.java:290`, `ShardChangesAction.java:59`).
        """
        # async-durability shards buffer appends; flush (no fsync needed for a
        # same-process read) so recovery sees every operation
        self._file.flush()
        ops = []
        for gen in range(self.min_generation, self.generation + 1):
            for op in self._read_gen(gen):
                if op.get("seq_no", -1) >= from_seq_no:
                    ops.append(op)
        return ops

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._file.close()
