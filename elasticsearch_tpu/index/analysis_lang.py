"""Language analysis: the analyzers the reference ships in
`modules/analysis-common` (language analyzers built from stopwords +
snowball stemmers) and the `plugins/analysis-{icu,phonetic,kuromoji,nori,
smartcn,...}` plugins (SURVEY.md §2.12).

Design notes, not ports:
- Language analyzers are stopword set + light suffix stemmer per language
  (the reference composes Lucene's stop + SnowballFilter the same way);
  stemmer rules here are compact light-stemming variants, not full
  snowball — BM25 ranking only needs consistent conflation.
- `cjk` does Han/Kana/Hangul bigramming, which is also the
  dictionary-free behavior the CJK plugins degrade to; kuromoji/nori/
  smartcn register as aliases of it so mappings written for the plugins
  resolve.
- `icu_folding` = NFKC + accent strip + case fold (the common 99% of
  ICU folding); `phonetic` provides soundex and metaphone-lite encoders.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Iterable, List

from elasticsearch_tpu.index.analysis import (
    Analyzer,
    Token,
    letter_tokenizer,
    lowercase_filter,
    standard_tokenizer,
    stop_filter,
)

# ---------------------------------------------------------------------------
# stopword sets (standard public lists, abbreviated to the high-frequency
# core — enough for scoring parity on common text)
# ---------------------------------------------------------------------------

STOPWORDS = {
    "french": frozenset(
        "au aux avec ce ces dans de des du elle en et eux il ils je la le les "
        "leur lui ma mais me même mes moi mon ne nos notre nous on ou par pas "
        "pour qu que qui sa se ses son sur ta te tes toi ton tu un une vos "
        "votre vous c d j l à m n s t y été être".split()),
    "german": frozenset(
        "aber alle als also am an auch auf aus bei bin bis bist da damit das "
        "dass dein der den des dem die dies doch dort du durch ein eine einem "
        "einen einer eines er es für hatte hier ich ihr im in ist ja kann "
        "mein mit muss nach nicht noch nun nur oder sehr sich sie sind so "
        "um und uns unter vom von vor war was wie wir zu zum zur über".split()),
    "spanish": frozenset(
        "a al algo como con de del desde donde el ella ellas ellos en entre "
        "era es esa ese eso esta este esto fue ha hay la las le les lo los "
        "me mi mis muy más ni no nos o os para pero por que se ser si sin "
        "sobre su sus te tu un una uno y ya él".split()),
    "italian": frozenset(
        "a ad al alla alle anche che chi ci come con da dal dalla de dei del "
        "della delle di e ed era fra gli ha ho i il in io la le lei lo loro "
        "lui ma mi ne nei nel nella no noi non nostro o per piú più quella "
        "quello questa questo se si sono su sua sue sui sul sulla suo tra tu "
        "un una uno voi è".split()),
    "portuguese": frozenset(
        "a ao aos as até com como da das de dela dele deles do dos e ela elas "
        "ele eles em entre era essa esse esta este eu foi há isso isto já la "
        "lhe mais mas me mesmo meu minha muito na nas no nos nossa nosso não "
        "o os ou para pela pelo por qual quando que se sem ser seu sua são "
        "também te tem um uma você à às é".split()),
    "dutch": frozenset(
        "aan al alles als altijd andere ben bij daar dan dat de der deze die "
        "dit doch doen door dus een en er ge geen geweest haar had heb hebben "
        "heeft hem het hier hij hoe hun iemand iets ik in is ja je kan kon "
        "kunnen maar me meer men met mij mijn moet na naar niet niets nog nu "
        "of om omdat onder ons ook op over reeds te tegen toch toen tot u uit "
        "uw van veel voor want waren was wat werd wezen wie wil worden wordt "
        "zal ze zelf zich zij zijn zo zonder zou".split()),
    "russian": frozenset(
        "а без более бы был была были было быть в вам вас весь во вот все "
        "всего всех вы где да даже для до его ее если есть еще же за здесь и "
        "из или им их к как ко когда кто ли либо мне может мы на надо наш не "
        "него нее нет ни них но ну о об однако он она они оно от очень по "
        "под при с со так также такой там те тем то того тоже той только том "
        "ты у уже хотя чего чей чем что чтобы чье чья эта эти это я".split()),
    "swedish": frozenset(
        "alla att av blev bli blir de dem den denna deras dess det detta dig "
        "din dina ditt du där då efter ej eller en er era ett från för ha "
        "hade han hans har hon hos hur här i icke ingen inom inte jag ju kan "
        "kunde man med mellan men mig min mina mitt mot mycket ni nu när "
        "någon något några och om oss på samma sedan sig sin sina sitta "
        "själv skulle som så sådan till under upp ut utan vad var vara varför "
        "varit varje vars vem vi vid vilken än är åt över".split()),
    "norwegian": frozenset(
        "alle at av bare begge ble da de dem den denne der deres det dette "
        "din disse du eller en enn er et for fra få ha hadde han hans har "
        "hennes her hun hva hvem hver hvilken hvis hvor hvordan hvorfor i "
        "ikke ingen inn jeg kan kom kun kunne man mange med meg mellom men "
        "mer min mitt mot noe noen nå når og også om opp oss over på samme "
        "seg selv sin sine sitt skal skulle slik som store så til um under "
        "ut uten var ved vi vil ville vår være vært".split()),
    "danish": frozenset(
        "af alle alt anden at blev blive bliver da de dem den denne der deres "
        "det dette dig din disse dog du efter eller en end er et for fra ham "
        "han hans har havde have hende hendes her hos hun hvad hvis hvor i "
        "ikke ind jeg jer jo kunne man mange med meget men mig min mine mit "
        "mod ned noget nogle nu når og også om op os over på selv sig sin "
        "sine sit skal skulle som sådan thi til ud under var vi vil ville "
        "vor være været".split()),
    "finnish": frozenset(
        "ei eivät emme en et ette että he hän häneen hänellä hänelle häneltä "
        "hänen hänessä hänestä hänet ja jos joka jotka kanssa keiden ketkä "
        "koska kuin kuinka kun me minkä minua minulla minulle minulta minun "
        "minussa minusta minut minä mitkä mukaan mutta ne niin nyt näiden "
        "nämä ole olemme olen olet olette oli olimme olin olisi olit olitte "
        "olivat olla olleet ollut on ovat poikki se sekä sen siinä siitä "
        "sille sillä silti sinua sinulla sinulle sinulta sinun sinussa "
        "sinusta sinut sinä tai te tämä tässä tästä tähän vaan vai vaikka yli "
        "ylös".split()),
}

# light suffix-stripping rules per language: longest match wins, applied to
# lowercase terms above a minimum stem length
_STEM_RULES = {
    "french": ["issements", "issement", "atrices", "atrice", "ateurs",
               "ations", "ateur", "ation", "ements", "ement", "euses",
               "ences", "ance", "ence", "euse", "eurs", "eaux", "ives",
               "eur", "ive", "aux", "ées", "és", "ée", "es", "er", "ez",
               "s", "e"],
    "german": ["erinnen", "erin", "ern", "em", "er", "en", "es", "e", "s"],
    "spanish": ["amientos", "imientos", "amiento", "imiento", "aciones",
                "uciones", "adoras", "adores", "ancias", "acion", "ucion",
                "adora", "ador", "ante", "anza", "ible", "able", "ista",
                "oso", "osa", "es", "os", "as", "o", "a", "e"],
    "italian": ["azioni", "azione", "amenti", "imenti", "amento", "imento",
                "atrice", "atori", "anza", "enza", "ante", "ibili", "abili",
                "ista", "oso", "osa", "i", "e", "o", "a"],
    "portuguese": ["amentos", "imentos", "amento", "imento", "adoras",
                   "adores", "aço~es", "ações", "ismos", "istas", "adora",
                   "ação", "ador", "ante", "ável", "ível", "eza", "ico",
                   "ica", "oso", "osa", "es", "os", "as", "o", "a", "e"],
    "dutch": ["heden", "ingen", "eren", "end", "ing", "en", "se", "s", "e"],
    "russian": ["иями", "ями", "ами", "иях", "ях", "ах", "ией", "ей", "ой",
                "ий", "ия", "ие", "ые", "ое", "ая", "яя", "ет", "ют", "ит",
                "ат", "ть", "ы", "и", "а", "я", "о", "е", "у", "ю", "ь"],
    "swedish": ["heterna", "heten", "arna", "erna", "orna", "ande", "ende",
                "aste", "arne", "or", "ar", "er", "en", "et", "a", "e"],
    "norwegian": ["hetene", "heten", "ande", "ende", "edes", "ene", "ane",
                  "ete", "et", "en", "ar", "er", "as", "es", "a", "e", "s"],
    "danish": ["erendes", "erende", "hedens", "ernes", "erens", "heden",
               "erne", "eren", "erer", "heds", "enes", "eres", "ens", "ene",
               "ere", "en", "er", "es", "et", "e", "s"],
    "finnish": ["issa", "issä", "ista", "istä", "iksi", "illa", "illä",
                "ilta", "iltä", "ille", "ssa", "ssä", "sta", "stä", "lla",
                "llä", "lta", "ltä", "lle", "ksi", "in", "en", "an", "än",
                "on", "a", "ä", "n", "t"],
}


def light_stemmer(language: str, min_stem: int = 3):
    rules = sorted(_STEM_RULES[language], key=len, reverse=True)

    def stem(word: str) -> str:
        for suf in rules:
            if word.endswith(suf) and len(word) - len(suf) >= min_stem:
                return word[: -len(suf)]
        return word

    def apply(tokens: Iterable[Token]) -> List[Token]:
        return [t._replace(term=stem(t.term)) for t in tokens]

    return apply


def elision_filter(tokens: Iterable[Token]) -> List[Token]:
    """French/Italian articles: l'avion -> avion (reference: ElisionFilter)."""
    out = []
    for t in tokens:
        term = t.term
        for apo in ("'", "’"):
            if apo in term:
                head, _, tail = term.partition(apo)
                if len(head) <= 2 and tail:
                    term = tail
                break
        out.append(t._replace(term=term))
    return out


# ---------------------------------------------------------------------------
# CJK bigrams (reference: `cjk` analyzer; the dictionary plugins
# kuromoji/nori/smartcn alias onto it here)
# ---------------------------------------------------------------------------

def _is_cjk(ch: str) -> bool:
    code = ord(ch)
    return (0x4E00 <= code <= 0x9FFF      # CJK unified
            or 0x3400 <= code <= 0x4DBF   # ext A
            or 0x3040 <= code <= 0x30FF   # hiragana + katakana
            or 0xAC00 <= code <= 0xD7AF   # hangul
            or 0xF900 <= code <= 0xFAFF)  # compatibility ideographs


def cjk_tokenizer(text: str) -> List[Token]:
    """Bigrams over CJK runs; non-CJK words tokenize like standard."""
    out: List[Token] = []
    pos = 0
    i = 0
    n = len(text)
    while i < n:
        ch = text[i]
        if _is_cjk(ch):
            j = i
            while j < n and _is_cjk(text[j]):
                j += 1
            run = text[i:j]
            if len(run) == 1:
                out.append(Token(run, pos, i, j))
                pos += 1
            else:
                for kk in range(len(run) - 1):
                    out.append(Token(run[kk:kk + 2], pos, i + kk, i + kk + 2))
                    pos += 1
            i = j
        elif ch.isalnum():
            j = i
            while j < n and text[j].isalnum() and not _is_cjk(text[j]):
                j += 1
            out.append(Token(text[i:j].lower(), pos, i, j))
            pos += 1
            i = j
        else:
            i += 1
    return out


# ---------------------------------------------------------------------------
# ICU folding (reference: plugins/analysis-icu ICUFoldingFilter)
# ---------------------------------------------------------------------------

def icu_folding_filter(tokens: Iterable[Token]) -> List[Token]:
    def fold(s: str) -> str:
        s = unicodedata.normalize("NFKC", s)
        s = "".join(c for c in unicodedata.normalize("NFKD", s)
                    if not unicodedata.combining(c))
        return s.casefold()

    return [t._replace(term=fold(t.term)) for t in tokens]


# ---------------------------------------------------------------------------
# Phonetic (reference: plugins/analysis-phonetic)
# ---------------------------------------------------------------------------

_SOUNDEX_CODES = {**{c: "1" for c in "bfpv"}, **{c: "2" for c in "cgjkqsxz"},
                  **{c: "3" for c in "dt"}, "l": "4",
                  **{c: "5" for c in "mn"}, "r": "6"}


def soundex(word: str) -> str:
    word = re.sub(r"[^a-z]", "", word.lower())
    if not word:
        return ""
    out = word[0].upper()
    prev = _SOUNDEX_CODES.get(word[0], "")
    for ch in word[1:]:
        code = _SOUNDEX_CODES.get(ch, "")
        if code and code != prev:
            out += code
            if len(out) == 4:
                break
        if ch not in "hw":
            prev = code
    return (out + "000")[:4]


def metaphone(word: str) -> str:
    """Compact metaphone variant: consonant-class folding."""
    w = re.sub(r"[^a-z]", "", word.lower())
    if not w:
        return ""
    subs = [("ph", "f"), ("gh", "g"), ("ck", "k"), ("sch", "sk"),
            ("th", "0"), ("sh", "x"), ("ch", "x"), ("dg", "j"),
            ("qu", "kw"), ("wh", "w")]
    for a, b in subs:
        w = w.replace(a, b)
    w = re.sub(r"(.)\1+", r"\1", w)          # dedupe doubles
    head, rest = w[0], w[1:]
    rest = re.sub(r"[aeiouy]", "", rest)     # drop interior vowels
    w = head + rest
    w = w.replace("c", "k").replace("q", "k").replace("z", "s")
    return w[:6].upper()


def phonetic_filter(encoder: str = "metaphone", replace: bool = True):
    enc = soundex if encoder == "soundex" else metaphone

    def apply(tokens: Iterable[Token]) -> List[Token]:
        out = []
        for t in tokens:
            code = enc(t.term)
            if not code:
                out.append(t)
                continue
            out.append(t._replace(term=code))
            if not replace:
                out.append(t)
        return out

    return apply


# ---------------------------------------------------------------------------
# generic filters the reference ships in analysis-common
# ---------------------------------------------------------------------------

def shingle_filter(min_size: int = 2, max_size: int = 2,
                   output_unigrams: bool = True):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        toks = list(tokens)
        out = list(toks) if output_unigrams else []
        for n in range(min_size, max_size + 1):
            for i in range(len(toks) - n + 1):
                grp = toks[i:i + n]
                out.append(Token(" ".join(t.term for t in grp),
                                 grp[0].position, grp[0].start_offset,
                                 grp[-1].end_offset))
        return out

    return apply


def edge_ngram_filter(min_gram: int = 1, max_gram: int = 10):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, min(max_gram, len(t.term)) + 1):
                out.append(t._replace(term=t.term[:n]))
        return out

    return apply


def ngram_filter(min_gram: int = 1, max_gram: int = 2):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, max_gram + 1):
                for i in range(max(0, len(t.term) - n + 1)):
                    out.append(t._replace(term=t.term[i:i + n]))
        return out

    return apply


def synonym_filter(synonyms: List[str]):
    """Solr-format rules: "a, b => c" (rewrite) or "a, b, c" (expand)."""
    rewrite = {}
    expand = {}
    for rule in synonyms:
        if "=>" in rule:
            lhs, _, rhs = rule.partition("=>")
            target = rhs.strip().split(",")[0].strip()
            for term in lhs.split(","):
                rewrite[term.strip()] = target
        else:
            group = [t.strip() for t in rule.split(",") if t.strip()]
            for term in group:
                expand.setdefault(term, group)

    def apply(tokens: Iterable[Token]) -> List[Token]:
        out = []
        for t in tokens:
            if t.term in rewrite:
                out.append(t._replace(term=rewrite[t.term]))
            elif t.term in expand:
                for alt in expand[t.term]:
                    out.append(t._replace(term=alt))
            else:
                out.append(t)
        return out

    return apply


def trim_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=t.term.strip()) for t in tokens]


def truncate_filter(length: int = 10):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        return [t._replace(term=t.term[:length]) for t in tokens]

    return apply


def unique_filter(tokens: Iterable[Token]) -> List[Token]:
    seen = set()
    out = []
    for t in tokens:
        if t.term not in seen:
            seen.add(t.term)
            out.append(t)
    return out


def reverse_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=t.term[::-1]) for t in tokens]


def length_filter(min_len: int = 0, max_len: int = 255):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        return [t for t in tokens if min_len <= len(t.term) <= max_len]

    return apply


def stemmer_filter(language: str = "english"):
    if language in ("english", "porter", "porter2", "light_english"):
        from elasticsearch_tpu.index.analysis import porter_stem_filter
        return porter_stem_filter
    base = language.replace("light_", "")
    if base in _STEM_RULES:
        return light_stemmer(base)
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    raise IllegalArgumentError(f"unknown stemmer language [{language}]")


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

def language_analyzers() -> List[Analyzer]:
    out = []
    for lang, stops in STOPWORDS.items():
        filters = [lowercase_filter]
        if lang in ("french", "italian"):
            filters.append(elision_filter)
        filters.append(stop_filter(stops))
        filters.append(light_stemmer(lang))
        out.append(Analyzer(lang, standard_tokenizer, filters))
    cjk = Analyzer("cjk", cjk_tokenizer, [])
    out.append(cjk)
    # dictionary-analyzer plugins resolve to the bigram analyzer
    for alias in ("kuromoji", "nori", "smartcn"):
        out.append(Analyzer(alias, cjk_tokenizer, []))
    out.append(Analyzer("icu_analyzer", standard_tokenizer,
                        [icu_folding_filter]))
    out.append(Analyzer("arabic", standard_tokenizer,
                        [lowercase_filter]))
    out.append(Analyzer("fingerprint", letter_tokenizer,
                        [lowercase_filter, unique_filter]))
    return out
