"""Sequence numbers: local checkpoints, global checkpoints, retention leases.

Re-design of `index/seqno/` (SURVEY.md §2.4):

- `LocalCheckpointTracker` (`LocalCheckpointTracker.java`): tracks which
  seq_nos have been processed and advances the contiguous-acknowledgement
  checkpoint.
- `ReplicationTracker` (`ReplicationTracker.java:79`): primary-side view of
  all copies — in-sync set, per-copy local checkpoints, the global
  checkpoint (min over in-sync copies, `:996`), and retention leases
  (`:308,390`) pinning operation history for ops-based recovery.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from elasticsearch_tpu.common.errors import IllegalArgumentError, SearchEngineError

NO_OPS_PERFORMED = -1
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED,
                 local_checkpoint: int = NO_OPS_PERFORMED):
        self._processed: Set[int] = set()
        self.checkpoint = local_checkpoint
        self.max_seq_no = max_seq_no
        self._next_seq_no = max_seq_no + 1

    def generate_seq_no(self) -> int:
        s = self._next_seq_no
        self._next_seq_no += 1
        return s

    def advance_max_seq_no(self, seq_no: int) -> None:
        if seq_no > self.max_seq_no:
            self.max_seq_no = seq_no
        if seq_no >= self._next_seq_no:
            self._next_seq_no = seq_no + 1

    def mark_processed(self, seq_no: int) -> None:
        self.advance_max_seq_no(seq_no)
        if seq_no <= self.checkpoint:
            return
        self._processed.add(seq_no)
        while self.checkpoint + 1 in self._processed:
            self.checkpoint += 1
            self._processed.remove(self.checkpoint)

    def pending_gaps(self) -> int:
        return len(self._processed)


class RetentionLease:
    __slots__ = ("lease_id", "retaining_seq_no", "timestamp_ms", "source")

    def __init__(self, lease_id: str, retaining_seq_no: int, source: str,
                 timestamp_ms: Optional[int] = None):
        self.lease_id = lease_id
        self.retaining_seq_no = retaining_seq_no
        self.source = source
        self.timestamp_ms = timestamp_ms if timestamp_ms is not None else int(time.time() * 1000)

    def to_dict(self) -> dict:
        return {"id": self.lease_id, "retaining_seq_no": self.retaining_seq_no,
                "timestamp": self.timestamp_ms, "source": self.source}


class CheckpointState:
    __slots__ = ("local_checkpoint", "global_checkpoint", "in_sync", "tracked")

    def __init__(self, local_checkpoint: int = UNASSIGNED_SEQ_NO,
                 in_sync: bool = False, tracked: bool = False):
        self.local_checkpoint = local_checkpoint
        self.global_checkpoint = UNASSIGNED_SEQ_NO
        self.in_sync = in_sync
        self.tracked = tracked


class ReplicationTracker:
    """Primary-mode tracker of replication progress across shard copies."""

    def __init__(self, allocation_id: str, retention_lease_expiry_ms: int = 12 * 3600 * 1000):
        self.allocation_id = allocation_id
        self.primary_mode = False
        self.checkpoints: Dict[str, CheckpointState] = {
            allocation_id: CheckpointState(in_sync=True, tracked=True)
        }
        self.global_checkpoint = NO_OPS_PERFORMED
        self.retention_leases: Dict[str, RetentionLease] = {}
        self.retention_lease_expiry_ms = retention_lease_expiry_ms

    # -- membership -----------------------------------------------------------
    def activate_primary_mode(self, local_checkpoint: int) -> None:
        self.primary_mode = True
        self.checkpoints[self.allocation_id].local_checkpoint = local_checkpoint
        self._recompute_global_checkpoint()

    def init_tracking(self, allocation_id: str) -> None:
        """A new copy starts recovery: tracked but not yet in-sync."""
        self._assert_primary()
        if allocation_id not in self.checkpoints:
            self.checkpoints[allocation_id] = CheckpointState(tracked=True)

    def mark_in_sync(self, allocation_id: str, local_checkpoint: int) -> None:
        """Recovery finished and the copy caught up (`markAllocationIdAsInSync:119`)."""
        self._assert_primary()
        state = self.checkpoints.get(allocation_id)
        if state is None:
            raise SearchEngineError(f"unknown allocation [{allocation_id}]")
        state.local_checkpoint = max(state.local_checkpoint, local_checkpoint)
        state.in_sync = True
        self._recompute_global_checkpoint()

    def remove_copy(self, allocation_id: str) -> None:
        self._assert_primary()
        if allocation_id == self.allocation_id:
            raise IllegalArgumentError("cannot remove the primary's own tracking")
        self.checkpoints.pop(allocation_id, None)
        self._recompute_global_checkpoint()

    def in_sync_ids(self) -> Set[str]:
        return {aid for aid, s in self.checkpoints.items() if s.in_sync}

    # -- checkpoints ----------------------------------------------------------
    def update_local_checkpoint(self, allocation_id: str, checkpoint: int) -> None:
        state = self.checkpoints.get(allocation_id)
        if state is None:
            return
        if checkpoint > state.local_checkpoint:
            state.local_checkpoint = checkpoint
            self._recompute_global_checkpoint()

    def update_global_checkpoint_on_replica(self, checkpoint: int) -> None:
        if checkpoint > self.global_checkpoint:
            self.global_checkpoint = checkpoint

    def _recompute_global_checkpoint(self) -> None:
        in_sync = [s.local_checkpoint for s in self.checkpoints.values() if s.in_sync]
        if not in_sync or any(c == UNASSIGNED_SEQ_NO for c in in_sync):
            return
        new_ckpt = min(in_sync)
        if new_ckpt > self.global_checkpoint:
            self.global_checkpoint = new_ckpt

    def _assert_primary(self) -> None:
        if not self.primary_mode:
            raise SearchEngineError("tracker is not in primary mode")

    # -- retention leases -----------------------------------------------------
    def add_retention_lease(self, lease_id: str, retaining_seq_no: int, source: str) -> RetentionLease:
        self._assert_primary()
        if lease_id in self.retention_leases:
            raise IllegalArgumentError(f"retention lease [{lease_id}] already exists")
        lease = RetentionLease(lease_id, retaining_seq_no, source)
        self.retention_leases[lease_id] = lease
        return lease

    def renew_retention_lease(self, lease_id: str, retaining_seq_no: int) -> RetentionLease:
        self._assert_primary()
        lease = self.retention_leases.get(lease_id)
        if lease is None:
            raise IllegalArgumentError(f"retention lease [{lease_id}] not found")
        lease.retaining_seq_no = max(lease.retaining_seq_no, retaining_seq_no)
        lease.timestamp_ms = int(time.time() * 1000)
        return lease

    def remove_retention_lease(self, lease_id: str) -> None:
        self._assert_primary()
        self.retention_leases.pop(lease_id, None)

    def expire_leases(self, now_ms: Optional[int] = None) -> List[str]:
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        expired = [lid for lid, l in self.retention_leases.items()
                   if now_ms - l.timestamp_ms > self.retention_lease_expiry_ms]
        for lid in expired:
            self.retention_leases.pop(lid)
        return expired

    def min_retained_seq_no(self) -> int:
        """History below this may be discarded (trim translog / compact)."""
        if self.retention_leases:
            return min(l.retaining_seq_no for l in self.retention_leases.values())
        return self.global_checkpoint + 1
