"""Text analysis: analyzers, tokenizers, token filters.

Re-design of the reference's analysis registry (`index/analysis/`,
`modules/analysis-common/` — SURVEY.md §2.4): a small pluggable registry of
named analyzers built from tokenizer + filter chains. Covers the built-in
analyzers the core API surface needs (standard, simple, whitespace, keyword,
stop, english) — language plugins can register more.

Analysis is host-side by design: it feeds the inverted index, which stays on
host; only scoring-relevant statistics cross to the device.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError


class Token(NamedTuple):
    term: str
    position: int
    start_offset: int
    end_offset: int


# ---------------------------------------------------------------------------
# Tokenizers
# ---------------------------------------------------------------------------

_WORD_RE = re.compile(r"[^\W_]+(?:['’][^\W_]+)?", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def _regex_tokenizer(pattern: re.Pattern) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        return [Token(m.group(0), i, m.start(), m.end())
                for i, m in enumerate(pattern.finditer(text))]

    return tokenize


standard_tokenizer = _regex_tokenizer(_WORD_RE)     # unicode word segmentation (approx UAX#29)
whitespace_tokenizer = _regex_tokenizer(_WHITESPACE_RE)
letter_tokenizer = _regex_tokenizer(_LETTER_RE)


def keyword_tokenizer(text: str) -> List[Token]:
    return [Token(text, 0, 0, len(text))] if text else []


def ngram_tokenizer(min_gram: int = 1, max_gram: int = 2) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        out = []
        pos = 0
        for n in range(min_gram, max_gram + 1):
            for i in range(0, max(0, len(text) - n + 1)):
                out.append(Token(text[i:i + n], pos, i, i + n))
                pos += 1
        return out

    return tokenize


def edge_ngram_tokenizer(min_gram: int = 1, max_gram: int = 10) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        return [Token(text[:n], 0, 0, n)
                for n in range(min_gram, min(max_gram, len(text)) + 1)]

    return tokenize


# ---------------------------------------------------------------------------
# Token filters
# ---------------------------------------------------------------------------

ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=t.term.lower()) for t in tokens]


def asciifolding_filter(tokens: Iterable[Token]) -> List[Token]:
    def fold(s: str) -> str:
        return "".join(c for c in unicodedata.normalize("NFKD", s)
                       if not unicodedata.combining(c))

    return [t._replace(term=fold(t.term)) for t in tokens]


def stop_filter(stopwords: frozenset = ENGLISH_STOPWORDS):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        return [t for t in tokens if t.term not in stopwords]

    return apply


def _porter_stem(word: str) -> str:
    """Porter stemmer (reference uses Lucene's PorterStemFilter for 'english').

    Compact implementation of the classic algorithm, steps 1-5.
    """
    if len(word) <= 2:
        return word

    vowels = "aeiou"

    def is_cons(w, i):
        c = w[i]
        if c in vowels:
            return False
        if c == "y":
            return i == 0 or not is_cons(w, i - 1)
        return True

    def measure(w):
        m, prev_v = 0, False
        for i in range(len(w)):
            v = not is_cons(w, i)
            if prev_v and not v:
                m += 1
            prev_v = v
        return m

    def has_vowel(w):
        return any(not is_cons(w, i) for i in range(len(w)))

    def ends_double_cons(w):
        return len(w) >= 2 and w[-1] == w[-2] and is_cons(w, len(w) - 1)

    def cvc(w):
        if len(w) < 3:
            return False
        return (is_cons(w, len(w) - 3) and not is_cons(w, len(w) - 2)
                and is_cons(w, len(w) - 1) and w[-1] not in "wxy")

    w = word

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and has_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and has_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif ends_double_cons(w) and not w.endswith(("l", "s", "z")):
                w = w[:-1]
            elif measure(w) == 1 and cvc(w):
                w += "e"

    # step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    step2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
             ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
             ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
             ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
             ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble")]
    for suf, rep in step2:
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 3
    step3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
             ("ical", "ic"), ("ful", ""), ("ness", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 4
    step4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
             "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]
    for suf in step4:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if suf == "ent" and w.endswith(("sion", "tion")):
                # 'ion' handled below
                pass
            if measure(stem) > 1:
                if suf in ("ate",) or True:
                    w = stem
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and measure(w[:-3]) > 1:
            w = w[:-3]

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = measure(stem)
        if m > 1 or (m == 1 and not cvc(stem)):
            w = stem
    # step 5b
    if measure(w) > 1 and ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w


def porter_stem_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=_porter_stem(t.term)) for t in tokens]


# ---------------------------------------------------------------------------
# Analyzer
# ---------------------------------------------------------------------------

class Analyzer:
    def __init__(self, name: str, tokenizer: Callable[[str], List[Token]],
                 filters: Iterable[Callable[[Iterable[Token]], List[Token]]] = ()):
        self.name = name
        self.tokenizer = tokenizer
        self.filters = list(filters)

    def analyze(self, text: str) -> List[Token]:
        tokens = self.tokenizer(str(text))
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def terms(self, text: str) -> List[str]:
        return [t.term for t in self.analyze(text)]


class AnalysisRegistry:
    """Named analyzers per index (reference: AnalysisRegistry.java)."""

    def __init__(self):
        self._analyzers: Dict[str, Analyzer] = {}
        for a in built_in_analyzers():
            self._analyzers[a.name] = a
        from elasticsearch_tpu.index.analysis_lang import language_analyzers
        for a in language_analyzers():
            self._analyzers.setdefault(a.name, a)

    def register(self, analyzer: Analyzer) -> None:
        self._analyzers[analyzer.name] = analyzer

    def get(self, name: str) -> Analyzer:
        if name == "default" and "default" not in self._analyzers:
            name = "standard"  # index.analysis.analyzer.default fallback
        a = self._analyzers.get(name)
        if a is None:
            raise IllegalArgumentError(f"failed to find analyzer [{name}]")
        return a

    def names(self):
        return sorted(self._analyzers)

    @classmethod
    def from_index_settings(cls, flat_settings: Dict) -> "AnalysisRegistry":
        """Per-index registry with custom analyzers/tokenizers/filters from
        `index.analysis.*` settings (reference: AnalysisRegistry builds
        per-index components from IndexSettings)."""
        reg = cls()
        analysis = _nest_analysis_settings(flat_settings)
        if not analysis:
            return reg

        custom_tokenizers = {}
        for name, spec in (analysis.get("tokenizer") or {}).items():
            custom_tokenizers[name] = _build_tokenizer(spec)
        custom_filters = {}
        for name, spec in (analysis.get("filter") or {}).items():
            custom_filters[name] = _build_filter(spec)

        for name, spec in (analysis.get("analyzer") or {}).items():
            atype = spec.get("type", "custom")
            if atype != "custom":
                # e.g. {"type": "standard", "stopwords": [...]}: start from
                # the named built-in, override stopwords when given
                base = reg.get(atype)
                filters = list(base.filters)
                if "stopwords" in spec:
                    filters = list(filters) + [
                        stop_filter(_resolve_stopwords(spec["stopwords"]))]
                reg.register(Analyzer(name, base.tokenizer, filters))
                continue
            tok_name = spec.get("tokenizer", "standard")
            tokenizer = custom_tokenizers.get(tok_name) \
                or _builtin_tokenizer(tok_name)
            filters = []
            for f in _as_list(spec.get("filter", [])):
                if f in custom_filters:
                    filters.append(custom_filters[f])
                else:
                    filters.append(_builtin_filter(f))
            reg.register(Analyzer(name, tokenizer, filters))
        return reg


def _as_list(v):
    if isinstance(v, str):
        return [p.strip() for p in v.split(",") if p.strip()]
    return list(v or [])


def _resolve_stopwords(value) -> frozenset:
    """Stopword spec → set; "_lang_" macros resolve to the language list,
    "_none_" disables, unknown macros error (a typo silently becoming the
    English list is invisible data corruption)."""
    if isinstance(value, str) and value.startswith("_") and value.endswith("_"):
        name = value.strip("_")
        if name == "none":
            return frozenset()
        if name == "english":
            return ENGLISH_STOPWORDS
        from elasticsearch_tpu.index.analysis_lang import STOPWORDS
        if name in STOPWORDS:
            return STOPWORDS[name]
        raise IllegalArgumentError(f"failed to find stopwords set [{value}]")
    return frozenset(_as_list(value))


def _nest_analysis_settings(flat: Dict) -> Dict:
    """{"index.analysis.analyzer.my.type": "custom", ...} →
    {"analyzer": {"my": {"type": "custom", ...}}}; list-valued leaves pass
    through (filter: [...])."""
    out: Dict = {}
    for key, value in (flat or {}).items():
        if not key.startswith("index.analysis."):
            continue
        parts = key[len("index.analysis."):].split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


def _builtin_tokenizer(name: str):
    table = {
        "standard": standard_tokenizer,
        "whitespace": whitespace_tokenizer,
        "letter": letter_tokenizer,
        "keyword": keyword_tokenizer,
        "lowercase": lambda text: lowercase_filter(letter_tokenizer(text)),
    }
    if name in table:
        return table[name]
    if name == "ngram":
        return ngram_tokenizer()
    if name == "edge_ngram":
        return edge_ngram_tokenizer()
    from elasticsearch_tpu.index.analysis_lang import cjk_tokenizer
    if name in ("cjk", "kuromoji_tokenizer", "nori_tokenizer", "smartcn_tokenizer"):
        return cjk_tokenizer
    raise IllegalArgumentError(f"failed to find tokenizer [{name}]")


def _build_tokenizer(spec: Dict):
    ttype = spec.get("type", "standard")
    if ttype == "ngram":
        return ngram_tokenizer(int(spec.get("min_gram", 1)),
                               int(spec.get("max_gram", 2)))
    if ttype == "edge_ngram":
        return edge_ngram_tokenizer(int(spec.get("min_gram", 1)),
                                    int(spec.get("max_gram", 10)))
    if ttype == "pattern":
        pat = re.compile(spec.get("pattern", r"\W+"))

        def tokenize(text: str, pat=pat):
            # tokens are the gaps between separator matches, with real
            # offsets (highlighting depends on them)
            out = []
            pos = 0
            start = 0
            for m in pat.finditer(text):
                if m.start() > start:
                    out.append(Token(text[start:m.start()], pos, start,
                                     m.start()))
                    pos += 1
                start = max(m.end(), start + 1 if m.end() == m.start()
                            else m.end())
            if start < len(text):
                out.append(Token(text[start:], pos, start, len(text)))
            return out

        return tokenize
    return _builtin_tokenizer(ttype)


def _builtin_filter(name: str):
    from elasticsearch_tpu.index import analysis_lang as lang
    table = {
        "lowercase": lowercase_filter,
        "asciifolding": asciifolding_filter,
        "stop": stop_filter(),
        "porter_stem": porter_stem_filter,
        "stemmer": porter_stem_filter,
        "kstem": porter_stem_filter,
        "snowball": porter_stem_filter,
        "elision": lang.elision_filter,
        "icu_folding": lang.icu_folding_filter,
        "icu_normalizer": lang.icu_folding_filter,
        "trim": lang.trim_filter,
        "unique": lang.unique_filter,
        "reverse": lang.reverse_filter,
        "shingle": lang.shingle_filter(),
        "edge_ngram": lang.edge_ngram_filter(),
        "ngram": lang.ngram_filter(),
        "phonetic": lang.phonetic_filter(),
        "truncate": lang.truncate_filter(),
        "length": lang.length_filter(),
        "classic": lowercase_filter,
        "uppercase": lambda toks: [t._replace(term=t.term.upper())
                                   for t in toks],
        "decimal_digit": lambda toks: [
            t._replace(term="".join(
                str(unicodedata.digit(c)) if c.isdigit() else c
                for c in t.term)) for t in toks],
    }
    if name in table:
        return table[name]
    raise IllegalArgumentError(f"failed to find token filter [{name}]")


def _build_filter(spec: Dict):
    from elasticsearch_tpu.index import analysis_lang as lang
    ftype = spec.get("type")
    if ftype == "stop":
        return stop_filter(_resolve_stopwords(spec.get("stopwords",
                                                       "_english_")))
    if ftype == "stemmer":
        return lang.stemmer_filter(spec.get("language", "english"))
    if ftype == "synonym" or ftype == "synonym_graph":
        return lang.synonym_filter(_as_list(spec.get("synonyms", [])))
    if ftype == "shingle":
        return lang.shingle_filter(
            int(spec.get("min_shingle_size", 2)),
            int(spec.get("max_shingle_size", 2)),
            bool(spec.get("output_unigrams", True)))
    if ftype == "edge_ngram":
        return lang.edge_ngram_filter(int(spec.get("min_gram", 1)),
                                      int(spec.get("max_gram", 10)))
    if ftype == "ngram":
        return lang.ngram_filter(int(spec.get("min_gram", 1)),
                                 int(spec.get("max_gram", 2)))
    if ftype == "phonetic":
        return lang.phonetic_filter(spec.get("encoder", "metaphone"),
                                    bool(spec.get("replace", True)))
    if ftype == "truncate":
        return lang.truncate_filter(int(spec.get("length", 10)))
    if ftype == "length":
        return lang.length_filter(int(spec.get("min", 0)),
                                  int(spec.get("max", 255)))
    if ftype == "pattern_replace":
        pat = re.compile(spec.get("pattern", ""))
        repl = spec.get("replacement", "")
        return lambda toks: [t._replace(term=pat.sub(repl, t.term))
                             for t in toks]
    if ftype:
        return _builtin_filter(ftype)
    raise IllegalArgumentError("token filter definition requires [type]")


def built_in_analyzers() -> List[Analyzer]:
    return [
        Analyzer("standard", standard_tokenizer, [lowercase_filter]),
        Analyzer("simple", letter_tokenizer, [lowercase_filter]),
        Analyzer("whitespace", whitespace_tokenizer),
        Analyzer("keyword", keyword_tokenizer),
        Analyzer("stop", letter_tokenizer, [lowercase_filter, stop_filter()]),
        Analyzer("english", standard_tokenizer,
                 [lowercase_filter, stop_filter(), porter_stem_filter]),
    ]


# DEFAULT_REGISTRY is constructed lazily (PEP 562 module __getattr__):
# building it at import time would re-enter analysis_lang while that module
# is still initializing whenever analysis_lang is imported first.
_default_registry: Optional[AnalysisRegistry] = None


def __getattr__(name: str):
    if name == "DEFAULT_REGISTRY":
        global _default_registry
        if _default_registry is None:
            _default_registry = AnalysisRegistry()
        return _default_registry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
