"""Text analysis: analyzers, tokenizers, token filters.

Re-design of the reference's analysis registry (`index/analysis/`,
`modules/analysis-common/` — SURVEY.md §2.4): a small pluggable registry of
named analyzers built from tokenizer + filter chains. Covers the built-in
analyzers the core API surface needs (standard, simple, whitespace, keyword,
stop, english) — language plugins can register more.

Analysis is host-side by design: it feeds the inverted index, which stays on
host; only scoring-relevant statistics cross to the device.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError


class Token(NamedTuple):
    term: str
    position: int
    start_offset: int
    end_offset: int


# ---------------------------------------------------------------------------
# Tokenizers
# ---------------------------------------------------------------------------

_WORD_RE = re.compile(r"[^\W_]+(?:['’][^\W_]+)?", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def _regex_tokenizer(pattern: re.Pattern) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        return [Token(m.group(0), i, m.start(), m.end())
                for i, m in enumerate(pattern.finditer(text))]

    return tokenize


standard_tokenizer = _regex_tokenizer(_WORD_RE)     # unicode word segmentation (approx UAX#29)
whitespace_tokenizer = _regex_tokenizer(_WHITESPACE_RE)
letter_tokenizer = _regex_tokenizer(_LETTER_RE)


def keyword_tokenizer(text: str) -> List[Token]:
    return [Token(text, 0, 0, len(text))] if text else []


def ngram_tokenizer(min_gram: int = 1, max_gram: int = 2) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        out = []
        pos = 0
        for n in range(min_gram, max_gram + 1):
            for i in range(0, max(0, len(text) - n + 1)):
                out.append(Token(text[i:i + n], pos, i, i + n))
                pos += 1
        return out

    return tokenize


def edge_ngram_tokenizer(min_gram: int = 1, max_gram: int = 10) -> Callable[[str], List[Token]]:
    def tokenize(text: str) -> List[Token]:
        return [Token(text[:n], 0, 0, n)
                for n in range(min_gram, min(max_gram, len(text)) + 1)]

    return tokenize


# ---------------------------------------------------------------------------
# Token filters
# ---------------------------------------------------------------------------

ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=t.term.lower()) for t in tokens]


def asciifolding_filter(tokens: Iterable[Token]) -> List[Token]:
    def fold(s: str) -> str:
        return "".join(c for c in unicodedata.normalize("NFKD", s)
                       if not unicodedata.combining(c))

    return [t._replace(term=fold(t.term)) for t in tokens]


def stop_filter(stopwords: frozenset = ENGLISH_STOPWORDS):
    def apply(tokens: Iterable[Token]) -> List[Token]:
        return [t for t in tokens if t.term not in stopwords]

    return apply


def _porter_stem(word: str) -> str:
    """Porter stemmer (reference uses Lucene's PorterStemFilter for 'english').

    Compact implementation of the classic algorithm, steps 1-5.
    """
    if len(word) <= 2:
        return word

    vowels = "aeiou"

    def is_cons(w, i):
        c = w[i]
        if c in vowels:
            return False
        if c == "y":
            return i == 0 or not is_cons(w, i - 1)
        return True

    def measure(w):
        m, prev_v = 0, False
        for i in range(len(w)):
            v = not is_cons(w, i)
            if prev_v and not v:
                m += 1
            prev_v = v
        return m

    def has_vowel(w):
        return any(not is_cons(w, i) for i in range(len(w)))

    def ends_double_cons(w):
        return len(w) >= 2 and w[-1] == w[-2] and is_cons(w, len(w) - 1)

    def cvc(w):
        if len(w) < 3:
            return False
        return (is_cons(w, len(w) - 3) and not is_cons(w, len(w) - 2)
                and is_cons(w, len(w) - 1) and w[-1] not in "wxy")

    w = word

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and has_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and has_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif ends_double_cons(w) and not w.endswith(("l", "s", "z")):
                w = w[:-1]
            elif measure(w) == 1 and cvc(w):
                w += "e"

    # step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    step2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
             ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
             ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
             ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
             ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble")]
    for suf, rep in step2:
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 3
    step3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
             ("ical", "ic"), ("ful", ""), ("ness", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 4
    step4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
             "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]
    for suf in step4:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if suf == "ent" and w.endswith(("sion", "tion")):
                # 'ion' handled below
                pass
            if measure(stem) > 1:
                if suf in ("ate",) or True:
                    w = stem
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and measure(w[:-3]) > 1:
            w = w[:-3]

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = measure(stem)
        if m > 1 or (m == 1 and not cvc(stem)):
            w = stem
    # step 5b
    if measure(w) > 1 and ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w


def porter_stem_filter(tokens: Iterable[Token]) -> List[Token]:
    return [t._replace(term=_porter_stem(t.term)) for t in tokens]


# ---------------------------------------------------------------------------
# Analyzer
# ---------------------------------------------------------------------------

class Analyzer:
    def __init__(self, name: str, tokenizer: Callable[[str], List[Token]],
                 filters: Iterable[Callable[[Iterable[Token]], List[Token]]] = ()):
        self.name = name
        self.tokenizer = tokenizer
        self.filters = list(filters)

    def analyze(self, text: str) -> List[Token]:
        tokens = self.tokenizer(str(text))
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def terms(self, text: str) -> List[str]:
        return [t.term for t in self.analyze(text)]


class AnalysisRegistry:
    """Named analyzers per index (reference: AnalysisRegistry.java)."""

    def __init__(self):
        self._analyzers: Dict[str, Analyzer] = {}
        for a in built_in_analyzers():
            self._analyzers[a.name] = a

    def register(self, analyzer: Analyzer) -> None:
        self._analyzers[analyzer.name] = analyzer

    def get(self, name: str) -> Analyzer:
        a = self._analyzers.get(name)
        if a is None:
            raise IllegalArgumentError(f"failed to find analyzer [{name}]")
        return a

    def names(self):
        return sorted(self._analyzers)


def built_in_analyzers() -> List[Analyzer]:
    return [
        Analyzer("standard", standard_tokenizer, [lowercase_filter]),
        Analyzer("simple", letter_tokenizer, [lowercase_filter]),
        Analyzer("whitespace", whitespace_tokenizer),
        Analyzer("keyword", keyword_tokenizer),
        Analyzer("stop", letter_tokenizer, [lowercase_filter, stop_filter()]),
        Analyzer("english", standard_tokenizer,
                 [lowercase_filter, stop_filter(), porter_stem_filter]),
    ]


DEFAULT_REGISTRY = AnalysisRegistry()
