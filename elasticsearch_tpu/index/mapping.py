"""Mappings: field types, document parsing, dynamic mapping.

Re-design of the reference's mapper layer (`index/mapper/` — MapperService,
DocumentMapper, DocumentParser, FieldMapper subclasses; SURVEY.md §2.4).
A mapping is a tree of typed field definitions; parsing a JSON document
produces a `ParsedDocument`: analyzed terms for the inverted index, typed
values for doc-values columns, dense vectors for the device matrix, and the
stored `_source`.

Field types covered: text, keyword, long/integer/short/byte, double/float/
half_float, boolean, date, ip, geo_point, dense_vector, object, nested
(stored flattened with nested paths), plus dynamic inference for unmapped
fields (reference `DynamicTemplates`/`DocumentParser.parseDynamicValue`).

dense_vector follows `x-pack/plugin/vectors/.../DenseVectorFieldMapper.java:45`
semantics: fixed `dims`, float array values, one vector per doc — but the
2048-dim cap is lifted (the TPU path has no BinaryDocValues encoding limit)
and a `similarity` parameter selects the device metric at index time.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import math
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError, MapperParsingError
from elasticsearch_tpu.index.analysis import AnalysisRegistry, DEFAULT_REGISTRY

# ---------------------------------------------------------------------------
# Parsed output containers
# ---------------------------------------------------------------------------


class ParsedDocument:
    """Everything the engine needs to index one document."""

    __slots__ = ("doc_id", "source", "terms", "term_positions", "doc_values",
                 "vectors", "field_lengths", "dynamic_updates")

    def __init__(self, doc_id: str, source: dict):
        self.doc_id = doc_id
        self.source = source
        # field -> list of terms (with duplicates, for tf)
        self.terms: Dict[str, List[str]] = {}
        # field -> term -> positions list
        self.term_positions: Dict[str, Dict[str, List[int]]] = {}
        # field -> scalar or list (kept typed: int/float/str/bool)
        self.doc_values: Dict[str, Any] = {}
        # field -> np.ndarray[dims] f32
        self.vectors: Dict[str, np.ndarray] = {}
        # field -> token count (for BM25 norms)
        self.field_lengths: Dict[str, int] = {}
        # mapping updates triggered by dynamic fields (field path -> mapper def)
        self.dynamic_updates: Dict[str, dict] = {}


# ---------------------------------------------------------------------------
# Field mappers
# ---------------------------------------------------------------------------

EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
_DATE_PATTERNS = (
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d",
)


_DATE_MATH_TOKEN = re.compile(r"([+\-/])(\d*)([yMwdhHms])")
_ROUND_SPAN_MS = {"s": 1000, "m": 60_000, "h": 3_600_000, "H": 3_600_000,
                  "d": 86_400_000, "w": 7 * 86_400_000}


def _apply_date_math(millis: int, expr: str, round_up: bool = False) -> int:
    """Date-math suffix (`||+1d/d`, `now-1h`): +/- offsets and /unit
    rounding (reference: JavaDateMathParser). `round_up` rounds to the END
    of the unit (the reference rounds up for gt/lte bounds). Malformed
    expressions raise — a typo must fail loudly, not query the wrong
    window."""
    tokens = _DATE_MATH_TOKEN.findall(expr)
    if "".join(op + num + unit for op, num, unit in tokens) \
            != expr.replace(" ", ""):
        raise MapperParsingError(
            f"failed to parse date math expression [{expr}]")
    for op, num, unit in tokens:
        if op == "/":
            if num:
                raise MapperParsingError(
                    f"rounding does not take a number [{op}{num}{unit}]")
            d = _dt.datetime.fromtimestamp(millis / 1000.0,
                                           tz=_dt.timezone.utc)
            if unit == "d":
                d = d.replace(hour=0, minute=0, second=0, microsecond=0)
            elif unit in ("h", "H"):
                d = d.replace(minute=0, second=0, microsecond=0)
            elif unit == "m":
                d = d.replace(second=0, microsecond=0)
            elif unit == "s":
                d = d.replace(microsecond=0)
            elif unit == "M":
                d = d.replace(day=1, hour=0, minute=0, second=0,
                              microsecond=0)
            elif unit == "y":
                d = d.replace(month=1, day=1, hour=0, minute=0, second=0,
                              microsecond=0)
            elif unit == "w":
                d = (d - _dt.timedelta(days=d.weekday())).replace(
                    hour=0, minute=0, second=0, microsecond=0)
            millis = int(d.timestamp() * 1000)
            if round_up:
                if unit in _ROUND_SPAN_MS:
                    millis += _ROUND_SPAN_MS[unit] - 1
                else:  # month/year: start of NEXT unit minus 1ms
                    months = 12 if unit == "y" else 1
                    millis = _shift_months(millis, months) - 1
            continue
        n = int(num or 1)
        if unit in _ROUND_SPAN_MS:
            delta = n * _ROUND_SPAN_MS[unit]
            millis += delta if op == "+" else -delta
        else:  # calendar months/years, day-clamped like the reference
            months = n * (12 if unit == "y" else 1)
            millis = _shift_months(millis, months if op == "+" else -months)
    return millis


def _shift_months(millis: int, months: int) -> int:
    import calendar
    d = _dt.datetime.fromtimestamp(millis / 1000.0, tz=_dt.timezone.utc)
    total = d.month - 1 + months
    year = d.year + total // 12
    month = total % 12 + 1
    day = min(d.day, calendar.monthrange(year, month)[1])
    return int(d.replace(year=year, month=month, day=day).timestamp() * 1000)


def parse_date_millis(value: Any, round_up: bool = False) -> int:
    """Parse a date into epoch millis (reference: DateFieldMapper,
    strict_date_optional_time||epoch_millis + date math). `round_up`
    applies to /unit rounding (gt/lte query bounds round to unit end)."""
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if s.startswith("now"):
        import time as _time
        return _apply_date_math(int(_time.time() * 1000), s[3:], round_up)
    if "||" in s:
        base, _, math_expr = s.partition("||")
        return _apply_date_math(parse_date_millis(base), math_expr, round_up)
    if round_up and re.fullmatch(r"\d{4}-\d{2}-\d{2}", s):
        # partial date on a gt/lte bound fills missing fields to unit END
        # (DateMathParser roundUpProperty): "2014-11-18" -> 23:59:59.999
        return parse_date_millis(s) + 86_400_000 - 1
    if re.fullmatch(r"-?\d{5,}", s):
        # epoch_millis claims any numeric string except bare 4-digit
        # years, which strict_date_optional_time parses as yyyy
        return int(s)
    norm = s.replace("Z", "+0000")
    if re.search(r"[+-]\d{2}:\d{2}$", norm):
        norm = norm[:-3] + norm[-2:]
    for pat in _DATE_PATTERNS:
        try:
            dt = _dt.datetime.strptime(norm, pat)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date field [{value}]")


class FieldMapper:
    type_name = "base"

    def __init__(self, name: str, params: Optional[dict] = None):
        self.name = name
        self.params = dict(params or {})

    # returns list of index terms; default: none
    def index_terms(self, value: Any) -> List[str]:
        return []

    # returns the doc-values representation (comparable/sortable), or None
    def doc_value(self, value: Any) -> Any:
        return None

    def to_def(self) -> dict:
        d = {"type": self.type_name}
        d.update(self.params)
        return d


class KeywordFieldMapper(FieldMapper):
    type_name = "keyword"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.ignore_above = self.params.get("ignore_above")

    def index_terms(self, value):
        s = str(value)
        if self.ignore_above is not None and len(s) > self.ignore_above:
            return []
        return [s]

    def doc_value(self, value):
        return str(value)


class TextFieldMapper(FieldMapper):
    type_name = "text"

    def __init__(self, name, params=None, registry: AnalysisRegistry = DEFAULT_REGISTRY):
        super().__init__(name, params)
        self.analyzer = registry.get(self.params.get("analyzer", "standard"))
        self.search_analyzer = registry.get(
            self.params.get("search_analyzer", self.params.get("analyzer", "standard")))

    def analyze(self, value) -> List[str]:
        return self.analyzer.terms(str(value))

    def analyze_positions(self, value):
        return self.analyzer.analyze(str(value))

    def index_terms(self, value):
        return self.analyze(value)

    def doc_value(self, value):
        return None  # text has no doc_values (reference: fielddata disabled by default)


class _NumericMapper(FieldMapper):
    py_type = float

    def coerce(self, value: Any):
        if isinstance(value, bool):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type_name}]: boolean")
        try:
            v = self.py_type(value)
        except (TypeError, ValueError):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type_name}] value [{value}]")
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            raise MapperParsingError(f"[{self.name}] non-finite value [{value}]")
        return v

    def index_terms(self, value):
        return [repr(self.coerce(value))]

    def doc_value(self, value):
        return self.coerce(value)


class LongFieldMapper(_NumericMapper):
    type_name = "long"
    py_type = int


class IntegerFieldMapper(LongFieldMapper):
    type_name = "integer"


class ShortFieldMapper(LongFieldMapper):
    type_name = "short"


class ByteFieldMapper(LongFieldMapper):
    type_name = "byte"


class DoubleFieldMapper(_NumericMapper):
    type_name = "double"
    py_type = float


class FloatFieldMapper(DoubleFieldMapper):
    type_name = "float"


class HalfFloatFieldMapper(DoubleFieldMapper):
    type_name = "half_float"


class ScaledFloatFieldMapper(_NumericMapper):
    type_name = "scaled_float"
    py_type = float

    def doc_value(self, value):
        factor = self.params.get("scaling_factor", 100)
        return round(self.coerce(value) * factor) / factor


class BooleanFieldMapper(FieldMapper):
    type_name = "boolean"

    def coerce(self, value):
        if isinstance(value, bool):
            return value
        if value in ("true", "True"):
            return True
        if value in ("false", "False", ""):
            return False
        raise MapperParsingError(f"failed to parse boolean field [{self.name}] value [{value}]")

    def index_terms(self, value):
        return ["T" if self.coerce(value) else "F"]

    def doc_value(self, value):
        return self.coerce(value)


_LOCALE_NAMES = {
    # localized day/month tokens normalize to English before strptime
    # (the reference delegates to java.time locale-aware formatters)
    "de": {"Mo": "Mon", "Di": "Tue", "Mi": "Wed", "Do": "Thu", "Fr": "Fri",
           "Sa": "Sat", "So": "Sun",
           "Jan": "Jan", "Feb": "Feb", "Mär": "Mar", "Mrz": "Mar",
           "Apr": "Apr", "Mai": "May", "Jun": "Jun", "Jul": "Jul",
           "Aug": "Aug", "Sep": "Sep", "Okt": "Oct", "Nov": "Nov",
           "Dez": "Dec"},
}


def parse_custom_date(value: str, fmt: str, locale: str = "") -> int:
    """Parse with a joda-style custom pattern (E, d MMM yyyy HH:mm:ss Z)
    honoring the mapping's locale for day/month names."""
    import datetime as _dt

    s = str(value).strip()
    names = _LOCALE_NAMES.get(str(locale or "").split("_")[0].lower())
    if names:
        for loc, eng in names.items():
            s = re.sub(rf"\b{loc}\b", eng, s)
    py = fmt
    for joda, strp in (("yyyy", "%Y"), ("yy", "%y"), ("MMMM", "%B"),
                       ("MMM", "%b"), ("MM", "%m"), ("dd", "%d"),
                       ("EEEE", "%A"), ("E", "%a"), ("HH", "%H"),
                       ("mm", "%M"), ("ss", "%S"), ("Z", "%z")):
        py = py.replace(joda, strp)
    py = re.sub(r"(?<!%)\bd\b", "%d", py)
    py = re.sub(r"(?<!%)\bM\b", "%m", py)
    d = _dt.datetime.strptime(s, py)
    if d.tzinfo is None:
        d = d.replace(tzinfo=_dt.timezone.utc)
    return int(d.timestamp() * 1000)


class DateFieldMapper(FieldMapper):
    type_name = "date"

    _CUSTOM_PATTERN_RE = re.compile(r"[EM]{1,4}|,")

    def _parse(self, value):
        # an explicit epoch_second format scales numeric inputs
        # (DateFormatters EpochSecond); everything else rides the default
        # strict_date_optional_time||epoch_millis chain
        fmt = str(self.params.get("format", ""))
        if "epoch_second" in fmt:
            try:
                return int(float(value) * 1000)
            except (TypeError, ValueError):
                pass
        if isinstance(value, str) and fmt and ("E" in fmt or "MMM" in fmt):
            try:
                return parse_custom_date(value, fmt,
                                         self.params.get("locale", ""))
            except (ValueError, MapperParsingError):
                pass
        return parse_date_millis(value)

    def index_terms(self, value):
        return [str(self._parse(value))]

    def doc_value(self, value):
        return self._parse(value)


def parse_date_nanos(value: Any) -> int:
    """Epoch NANOS (DateFieldMapper.Resolution.NANOSECONDS): numbers are
    epoch millis; strings keep up to 9 fractional-second digits."""
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, (int, float)):
        return int(value) * 1_000_000
    s = str(value).strip()
    if re.fullmatch(r"-?\d{10,}", s):
        return int(s) * 1_000_000
    m = re.match(r"^(.*?)(?:\.(\d{1,9}))?((?:Z|[+-]\d{2}:?\d{2})?)$", s)
    base, frac, tz = m.groups()
    millis = parse_date_millis(base + (tz or ""))
    return millis * 1_000_000 + int((frac or "0").ljust(9, "0")[:9])


class DateNanosFieldMapper(DateFieldMapper):
    """date_nanos: nanosecond-resolution dates (the reference stores nanos
    since epoch; `DateFieldMapper` with Resolution.NANOSECONDS)."""

    type_name = "date_nanos"

    def index_terms(self, value):
        return [str(parse_date_nanos(value))]

    def doc_value(self, value):
        nanos = parse_date_nanos(value)
        if nanos < 0:
            raise MapperParsingError(
                f"failed to parse field [{self.name}]",
                caused_by={"reason": f"date[{value}] is before the epoch in "
                           "1970 and cannot be stored in nanosecond "
                           "resolution"})
        if nanos > 9223372036854775807:  # int64 max = 2262-04-11
            raise MapperParsingError(
                f"failed to parse field [{self.name}]",
                caused_by={"reason": f"date[{value}] is after "
                           "2262-04-11T23:47:16.854775807 and cannot be "
                           "stored in nanosecond resolution"})
        return nanos


class IpFieldMapper(FieldMapper):
    type_name = "ip"

    @staticmethod
    def parse_ip(value) -> int:
        """IPs order/store in the 16-byte IPv6 space; IPv4 maps to
        ::ffff:a.b.c.d (the reference stores InetAddressPoint's 16-byte
        form), so '::1' and '0.0.0.1' remain distinct values. Every
        consumer (doc values, query bounds, agg ranges) MUST use this one
        transform or comparisons cross number spaces."""
        ip = ipaddress.ip_address(str(value))
        if isinstance(ip, ipaddress.IPv4Address):
            ip = ipaddress.IPv6Address(b"\x00" * 10 + b"\xff\xff" + ip.packed)
        return int(ip)

    def coerce(self, value) -> int:
        try:
            return self.parse_ip(value)
        except ValueError:
            raise MapperParsingError(f"failed to parse IP [{value}] for field [{self.name}]")

    @staticmethod
    def format_value(stored: int) -> str:
        addr = ipaddress.IPv6Address(int(stored))
        return str(addr.ipv4_mapped or addr)

    def index_terms(self, value):
        return [str(self.coerce(value))]

    def doc_value(self, value):
        return self.coerce(value)


def _geohash_decode(gh: str):
    """Geohash -> (lat, lon) cell center (Lucene GeoHashUtils)."""
    bits = "0123456789bcdefghjkmnpqrstuvwxyz"
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    even = True
    for ch in gh:
        cd = bits.index(ch)
        for mask in (16, 8, 4, 2, 1):
            if even:
                mid = (lon_lo + lon_hi) / 2
                if cd & mask:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if cd & mask:
                    lat_lo = mid
                else:
                    lat_hi = mid
            even = not even
    return ((lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2)


class GeoPointFieldMapper(FieldMapper):
    type_name = "geo_point"

    def coerce(self, value) -> Tuple[float, float]:
        """Returns (lat, lon)."""
        if isinstance(value, dict):
            try:
                return float(value["lat"]), float(value["lon"])
            except (KeyError, TypeError, ValueError):
                raise MapperParsingError(f"failed to parse geo_point [{value}]")
        if isinstance(value, (list, tuple)) and len(value) == 2:
            return float(value[1]), float(value[0])  # [lon, lat] GeoJSON order
        if isinstance(value, str):
            parts = value.split(",")
            if len(parts) == 2:
                return float(parts[0]), float(parts[1])
            import re as _re
            if _re.fullmatch(r"[0123456789bcdefghjkmnpqrstuvwxyz]{1,12}",
                             value.lower()):
                return _geohash_decode(value.lower())
        raise MapperParsingError(f"failed to parse geo_point [{value}]")

    def doc_value(self, value):
        return self.coerce(value)


class DenseVectorFieldMapper(FieldMapper):
    """`dense_vector` (reference: DenseVectorFieldMapper.java:45).

    params: dims (required), similarity (cosine|dot_product|l2_norm,
    default cosine), index_options.type — the quantization-ladder rung
    (flat|int8_flat|int4_flat|binary_flat: storage encoding of the
    device matrix, see `elasticsearch_tpu/quant/codec.py`;
    ivf|int8_ivf|int4_ivf|binary_ivf: same rung on the partitioned
    `tpu_ivf` engine, overriding `index.knn.engine`),
    index_options.nlist / nprobe (per-field IVF overrides),
    index_options.rescore / rescore_oversample (two-phase exact rescore:
    packed rungs default rescore on; oversample sizes the coarse
    window — k·oversample candidates re-ranked exactly).
    """

    type_name = "dense_vector"

    INDEX_OPTIONS_TYPES = ("flat", "int8_flat", "int4_flat", "binary_flat",
                           "ivf", "int8_ivf", "int4_ivf", "binary_ivf")

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.dims = self.params.get("dims")
        if self.dims is None:
            raise MapperParsingError(f"[{name}] dense_vector requires [dims]")
        self.dims = int(self.dims)
        self.similarity = self.params.get("similarity", "cosine")
        if self.similarity not in ("cosine", "dot_product", "l2_norm", "max_inner_product"):
            raise MapperParsingError(f"[{name}] unknown similarity [{self.similarity}]")
        opts = self.params.get("index_options") or {}
        otype = opts.get("type")
        if otype is not None and otype not in self.INDEX_OPTIONS_TYPES:
            raise MapperParsingError(
                f"[{name}] unknown index_options type [{otype}]; expected "
                f"one of {list(self.INDEX_OPTIONS_TYPES)}")
        self.index_options_type = otype
        # packed rungs constrain dims by their bit layout; reject at
        # mapping time, not at first refresh
        if otype in ("int4_flat", "int4_ivf") and self.dims % 2:
            raise MapperParsingError(
                f"[{name}] index_options type [{otype}] requires even "
                f"[dims], got [{self.dims}]")
        if otype in ("binary_flat", "binary_ivf"):
            if self.dims % 32:
                raise MapperParsingError(
                    f"[{name}] index_options type [{otype}] requires "
                    f"[dims] divisible by 32, got [{self.dims}]")
            if self.similarity in ("l2_norm", "max_inner_product"):
                # the sign-bit coarse phase discards magnitudes, which
                # l2 and MIP rankings depend on — the true top-k would
                # never enter the rescore window
                raise MapperParsingError(
                    f"[{name}] index_options type [{otype}] scores "
                    "sign-bit Hamming — incompatible with "
                    f"[{self.similarity}] similarity (use cosine or "
                    "unit-normalized dot_product)")
        for opt_key in ("nlist", "nprobe", "rescore_oversample"):
            v = opts.get(opt_key)
            if v is None or (opt_key == "nprobe" and v == "auto"):
                continue  # "auto" is meaningful only for nprobe
            try:
                ok = int(v) >= 1
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise MapperParsingError(
                    f"[{name}] index_options [{opt_key}] must be an "
                    f"integer >= 1, got [{v}]")

    def coerce(self, value) -> np.ndarray:
        if not isinstance(value, (list, tuple)):
            raise MapperParsingError(f"[{self.name}] dense_vector value must be an array")
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1 or arr.shape[0] != self.dims:
            raise MapperParsingError(
                f"[{self.name}] vector has [{arr.shape[0] if arr.ndim == 1 else '?'}] "
                f"dimensions, mapping requires [{self.dims}]")
        if not np.isfinite(arr).all():
            raise MapperParsingError(f"[{self.name}] vector contains non-finite values")
        return arr


class ObjectMapper(FieldMapper):
    type_name = "object"


class NestedMapper(FieldMapper):
    type_name = "nested"


class RankFeatureFieldMapper(FieldMapper):
    """`rank_feature` (reference: modules/mapper-extras
    RankFeatureFieldMapper) — positive float consumed by rank_feature
    queries."""

    type_name = "rank_feature"

    def coerce(self, value) -> float:
        v = float(value)
        if v <= 0 and not self.params.get("positive_score_impact", True) is False:
            if v < 0:
                raise MapperParsingError(
                    f"[{self.name}] rank_feature fields only support positive "
                    f"values, got [{value}]")
        return v

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class RankFeaturesFieldMapper(FieldMapper):
    """`rank_features`: a sparse map feature→weight."""

    type_name = "rank_features"

    def coerce(self, value) -> dict:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] rank_features value must be an object")
        return {str(k): float(v) for k, v in value.items()}

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class RankVectorsFieldMapper(FieldMapper):
    """`rank_vectors` (reference: x-pack rank-vectors
    RankVectorsFieldMapper, the late-interaction field): each doc holds
    a ragged LIST of token vectors, scored by MaxSim against a multi-
    token query.

    params: dims (required), similarity (cosine|dot_product, default
    cosine — l2/MIP have no max-sum decomposition on the dot kernel),
    index_options.encoding — the token-block storage rung in the device
    columnar store (f32|bf16|int8|int4, default int8; binary has no
    MaxSim kernel), index_options.oversample — the coarse pooled-
    centroid window multiplier (k·oversample candidates rescored by the
    fused MaxSim kernel, default 4), index_options.coarse — storage
    rung of the pooled centroid matrix (any `dense_vector` flat rung,
    default f32)."""

    type_name = "rank_vectors"

    ENCODINGS = ("f32", "bf16", "int8", "int4")
    COARSE = ("f32", "bf16", "int8", "int4", "binary")

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.dims = self.params.get("dims")
        if self.dims is None:
            raise MapperParsingError(f"[{name}] rank_vectors requires [dims]")
        self.dims = int(self.dims)
        self.similarity = self.params.get("similarity", "cosine")
        if self.similarity not in ("cosine", "dot_product"):
            raise MapperParsingError(
                f"[{name}] unknown similarity [{self.similarity}] for "
                "rank_vectors; expected cosine or dot_product")
        # storage knobs read from index_options with top-level fallback
        # (the REST mapping surface accepts either placement)
        opts = self.params.get("index_options") or {}
        self.encoding = opts.get("encoding",
                                 self.params.get("encoding", "int8"))
        if self.encoding not in self.ENCODINGS:
            raise MapperParsingError(
                f"[{name}] unknown index_options encoding "
                f"[{self.encoding}]; expected one of {list(self.ENCODINGS)}")
        if self.encoding == "int4" and self.dims % 2:
            raise MapperParsingError(
                f"[{name}] index_options encoding [int4] requires even "
                f"[dims], got [{self.dims}]")
        self.coarse = opts.get("coarse", self.params.get("coarse", "f32"))
        if self.coarse not in self.COARSE:
            raise MapperParsingError(
                f"[{name}] unknown index_options coarse [{self.coarse}]; "
                f"expected one of {list(self.COARSE)}")
        oversample = opts.get("oversample",
                              self.params.get("oversample", 4))
        try:
            ok = int(oversample) >= 1
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise MapperParsingError(
                f"[{name}] index_options [oversample] must be an integer "
                f">= 1, got [{oversample}]")
        self.oversample = int(oversample)

    def coerce(self, value) -> np.ndarray:
        if not isinstance(value, (list, tuple)) or not value:
            raise MapperParsingError(
                f"[{self.name}] rank_vectors value must be a non-empty "
                "array of vectors")
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        if arr.ndim != 2 or arr.shape[1] != self.dims:
            raise MapperParsingError(
                f"[{self.name}] rank_vectors rows must have [{self.dims}] "
                "dimensions")
        if not np.isfinite(arr).all():
            raise MapperParsingError(
                f"[{self.name}] rank_vectors contains non-finite values")
        return arr

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class JoinFieldMapper(FieldMapper):
    """`join` (reference: modules/parent-join ParentJoinFieldMapper):
    relations define parent→children; doc value keeps {name, parent}."""

    type_name = "join"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.relations: Dict[str, List[str]] = {}
        for parent, children in (self.params.get("relations") or {}).items():
            self.relations[parent] = (children if isinstance(children, list)
                                      else [children])

    def coerce(self, value):
        if isinstance(value, str):
            return {"name": value}
        if isinstance(value, dict) and "name" in value:
            return value
        raise MapperParsingError(f"[{self.name}] join value must be a "
                                 f"relation name or {{name, parent}}")

    def index_terms(self, value):
        return [self.coerce(value)["name"]]

    def doc_value(self, value):
        return self.coerce(value)


class PercolatorFieldMapper(FieldMapper):
    """`percolator` (reference: modules/percolator PercolatorFieldMapper):
    stores a query to run in reverse against candidate documents."""

    type_name = "percolator"

    def coerce(self, value):
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] percolator field must hold a query object")
        # validate eagerly like the reference (parse at index time)
        from elasticsearch_tpu.search.queries import parse_query
        parse_query(value)
        return value

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class BinaryFieldMapper(FieldMapper):
    """`binary` (reference: index/mapper/BinaryFieldMapper.java): base64
    value, stored but not searchable."""

    type_name = "binary"

    def coerce(self, value) -> str:
        import base64
        s = str(value)
        try:
            base64.b64decode(s, validate=True)
        except Exception:
            raise MapperParsingError(
                f"[{self.name}] failed to parse base64 binary value")
        return s

    def doc_value(self, value):
        return self.coerce(value) if self.params.get("doc_values", True) else None


class RangeFieldMapperBase(FieldMapper):
    """Range family (reference: index/mapper/RangeFieldMapper.java —
    integer/long/float/double/date/ip ranges). A value is an object of
    gt/gte/lt/lte bounds; stored normalized to inclusive numeric [lo, hi]
    so membership (term) and overlap (range query relations) are interval
    arithmetic over doc values."""

    discrete = True  # exclusive bounds shift by 1; floats use nextafter

    def _bound(self, value) -> float:
        return float(value)

    def coerce(self, value) -> dict:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] range field value must be an object of bounds")
        lo, hi = -math.inf, math.inf
        for k, v in value.items():
            if k == "gte":
                lo = self._bound(v)
            elif k == "gt":
                b = self._bound(v)
                lo = b + 1 if self.discrete else float(np.nextafter(b, math.inf))
            elif k == "lte":
                hi = self._bound(v)
            elif k == "lt":
                b = self._bound(v)
                hi = b - 1 if self.discrete else float(np.nextafter(b, -math.inf))
            else:
                raise MapperParsingError(
                    f"[{self.name}] unknown range bound [{k}]")
        if lo > hi:
            raise MapperParsingError(
                f"[{self.name}] range lower bound greater than upper bound")
        return {"gte": lo, "lte": hi}

    def doc_value(self, value):
        return self.coerce(value)

    def query_bound(self, value, round_up: bool = False) -> float:
        """Bound coercion for query-side values (same units as storage);
        `round_up` only matters for date ranges (date-math rounding)."""
        return self._bound(value)


class IntegerRangeFieldMapper(RangeFieldMapperBase):
    type_name = "integer_range"

    def _bound(self, value):
        return float(int(value))


class LongRangeFieldMapper(IntegerRangeFieldMapper):
    type_name = "long_range"


class FloatRangeFieldMapper(RangeFieldMapperBase):
    type_name = "float_range"
    discrete = False


class DoubleRangeFieldMapper(FloatRangeFieldMapper):
    type_name = "double_range"


class DateRangeFieldMapper(RangeFieldMapperBase):
    type_name = "date_range"

    def _bound(self, value):
        return float(parse_date_millis(value))

    def query_bound(self, value, round_up: bool = False) -> float:
        return float(parse_date_millis(value, round_up=round_up))


class IpRangeFieldMapper(RangeFieldMapperBase):
    type_name = "ip_range"

    def _bound(self, value):
        return float(int(ipaddress.ip_address(str(value))))

    def coerce(self, value):
        if isinstance(value, str):  # CIDR form "10.0.0.0/8"
            try:
                net = ipaddress.ip_network(value, strict=False)
            except ValueError:
                raise MapperParsingError(
                    f"[{self.name}] failed to parse ip range [{value}]")
            return {"gte": float(int(net.network_address)),
                    "lte": float(int(net.broadcast_address))}
        return super().coerce(value)


class CompletionFieldMapper(FieldMapper):
    """`completion` (reference: index/mapper/CompletionFieldMapper.java —
    FST-backed suggester field). Inputs index as exact terms; the completion
    suggester prefix-scans them (search/extras.py)."""

    type_name = "completion"

    def _inputs(self, value) -> Tuple[List[str], int, dict]:
        if isinstance(value, str):
            return [value], 1, {}
        if isinstance(value, list):
            return [str(v) for v in value], 1, {}
        if isinstance(value, dict):
            inp = value.get("input", [])
            inputs = [inp] if isinstance(inp, str) else [str(v) for v in inp]
            return (inputs, int(value.get("weight", 1)),
                    value.get("contexts") or {})
        raise MapperParsingError(
            f"[{self.name}] completion value must be string, array or object")

    def index_terms(self, value):
        return self._inputs(value)[0]

    def doc_value(self, value):
        inputs, weight, contexts = self._inputs(value)
        # context-enabled fields REQUIRE contexts at index time unless the
        # context resolves from a document path (ContextMappings.addField)
        defs = self.params.get("contexts") or []
        needs = [d for d in defs if not d.get("path")]
        if needs and not contexts:
            raise MapperParsingError(
                f"Contexts are mandatory in context enabled completion "
                f"field [{self.name}]")
        return {"input": inputs, "weight": weight, "contexts": contexts}


class _ShingleAnalyzer:
    """Analyzer adapter producing word shingles of size N over a base
    analyzer (both index- and search-side for the SAYT subfields)."""

    def __init__(self, base, n: int):
        self.base = base
        self.n = n

    def terms(self, text: str) -> List[str]:
        base = self.base.terms(text)
        return [" ".join(base[i:i + self.n])
                for i in range(len(base) - self.n + 1)]

    def analyze(self, text: str):
        from elasticsearch_tpu.index.analysis import Token
        return [Token(t, i, 0, 0) for i, t in enumerate(self.terms(text))]


class _ShingleTextMapper(TextFieldMapper):
    """Auto subfield of search_as_you_type: word shingles of size N."""

    type_name = "text"

    def __init__(self, name, params=None, shingle_size=2):
        super().__init__(name, params)
        self.shingle_size = shingle_size
        self.analyzer = _ShingleAnalyzer(self.analyzer, shingle_size)
        self.search_analyzer = _ShingleAnalyzer(self.search_analyzer,
                                                shingle_size)


class _PrefixTextMapper(TextFieldMapper):
    """Auto subfield of search_as_you_type: edge n-grams over 1..3-shingles
    (reference's `._index_prefix`)."""

    type_name = "text"

    def analyze(self, value):
        base = super().analyze(value)
        out = []
        for n in (1, 2, 3):
            for i in range(max(0, len(base) - n + 1)):
                shingle = " ".join(base[i:i + n])
                out.extend(shingle[:j] for j in range(1, min(len(shingle), 19) + 1))
        return sorted(set(out))

    def analyze_positions(self, value):
        from elasticsearch_tpu.index.analysis import Token
        return [Token(t, i, 0, 0) for i, t in enumerate(self.analyze(value))]


class SearchAsYouTypeFieldMapper(TextFieldMapper):
    """`search_as_you_type` (reference: modules/mapper-extras
    SearchAsYouTypeFieldMapper.java): a text field with auto `._2gram`,
    `._3gram` shingle subfields and an `._index_prefix` edge-ngram subfield,
    targeted by multi_match bool_prefix queries."""

    type_name = "search_as_you_type"


class TokenCountFieldMapper(FieldMapper):
    """`token_count` (reference: modules/mapper-extras
    TokenCountFieldMapper.java): indexes the number of analyzed tokens."""

    type_name = "token_count"

    def __init__(self, name, params=None,
                 registry: AnalysisRegistry = DEFAULT_REGISTRY):
        super().__init__(name, params)
        self.analyzer = registry.get(self.params.get("analyzer", "standard"))

    def count(self, value) -> int:
        # numeric input IS the count (query-side values, pre-counted docs);
        # strings get analyzed (index-side text values)
        if isinstance(value, int) and not isinstance(value, bool):
            return value
        return len(self.analyzer.terms(str(value)))

    def index_terms(self, value):
        return [repr(self.count(value))]

    def doc_value(self, value):
        return self.count(value)


class WildcardFieldMapper(KeywordFieldMapper):
    """`wildcard` (reference: x-pack/plugin/wildcard): keyword-like field
    optimized for leading-wildcard matching. The term-scan execution here
    already handles arbitrary patterns, so this shares keyword indexing."""

    type_name = "wildcard"


class ConstantKeywordFieldMapper(FieldMapper):
    """`constant_keyword` (reference: x-pack/plugin/mapper-constant-keyword):
    one value for every document in the index; documents may omit it, and a
    conflicting value is a parse error. First seen value fixes it if the
    mapping didn't."""

    type_name = "constant_keyword"

    def coerce(self, value, fix: bool = False) -> str:
        s = str(value)
        const = self.params.get("value")
        if const is None:
            if fix:  # only the write path fixes the constant
                self.params["value"] = s
            return s
        if s != const:
            raise MapperParsingError(
                f"[{self.name}] constant_keyword field is already set to "
                f"[{const}], cannot index [{s}]")
        return s

    def index_terms(self, value):
        # query-side coercion must not mutate the mapping
        return [self.coerce(value)]

    def doc_value(self, value):
        return self.coerce(value, fix=True)


class Murmur3FieldMapper(FieldMapper):
    """`murmur3` (reference: plugins/mapper-murmur3): stores the murmur3
    hash of the value for cheap cardinality estimation."""

    type_name = "murmur3"

    def doc_value(self, value):
        from elasticsearch_tpu.cluster.routing import murmur3_x86_32
        h = murmur3_x86_32(str(value).encode("utf-8"))
        return h - (1 << 32) if h >= (1 << 31) else h  # signed like the ref


class HistogramFieldMapper(FieldMapper):
    """`histogram` (reference: x-pack/plugin/analytics histogram field):
    pre-aggregated {values[], counts[]} consumed by percentile aggs."""

    type_name = "histogram"

    def coerce(self, value) -> dict:
        if not isinstance(value, dict) or "values" not in value \
                or "counts" not in value:
            raise MapperParsingError(
                f"[{self.name}] histogram must be {{values, counts}}")
        values = [float(v) for v in value["values"]]
        counts = [int(c) for c in value["counts"]]
        if len(values) != len(counts):
            raise MapperParsingError(
                f"[{self.name}] expected same length for values and counts")
        if any(c < 0 for c in counts):
            raise MapperParsingError(f"[{self.name}] counts must be >= 0")
        if values != sorted(values):
            raise MapperParsingError(
                f"[{self.name}] values must be in increasing order")
        return {"values": values, "counts": counts}

    def doc_value(self, value):
        return self.coerce(value)


class FlattenedFieldMapper(FieldMapper):
    """`flattened` (reference: x-pack/plugin/mapper-flattened
    FlatObjectFieldMapper.java): an entire JSON object indexed as keywords —
    root-field queries match any leaf value, `field.key` queries match that
    key's value. Keyed terms are materialized in MapperService._index_one."""

    type_name = "flattened"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.depth_limit = int(self.params.get("depth_limit", 20))

    def coerce(self, value) -> dict:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] flattened field value must be an object")
        return value

    def index_terms(self, value):
        # query-side coercion: scalar query values look up leaf terms
        # (document dicts never reach here — _index_one intercepts them)
        if isinstance(value, dict):
            return []
        return [_flat_str(value)]

    def leaves(self, value, prefix: str = "", depth: int = 0):
        """Yields (key_path, leaf_string)."""
        if depth > self.depth_limit:
            raise MapperParsingError(
                f"[{self.name}] object depth exceeds depth_limit "
                f"[{self.depth_limit}]")
        for k, v in value.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                yield from self.leaves(v, path + ".", depth + 1)
            elif isinstance(v, list):
                for item in v:
                    if isinstance(item, dict):
                        yield from self.leaves(item, path + ".", depth + 1)
                    elif item is not None:
                        yield path, _flat_str(item)
            elif v is not None:
                yield path, _flat_str(v)

    def doc_value(self, value):
        return self.coerce(value)


def _flat_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class AnnotatedTextFieldMapper(TextFieldMapper):
    """`annotated_text` (reference: plugins/mapper-annotated-text):
    markdown-style `[text](annotation)` spans index both the visible text
    and the annotation value as terms."""

    type_name = "annotated_text"

    _ANNOTATION = re.compile(r"\[([^\]]+)\]\(([^)]+)\)")

    def _expand(self, value: str) -> str:
        annotations = []

        def sub(m):
            for part in m.group(2).split("&"):
                import urllib.parse
                annotations.append(urllib.parse.unquote(part))
            return m.group(1)

        text = self._ANNOTATION.sub(sub, str(value))
        return text + ("\n" + "\n".join(annotations) if annotations else "")

    def analyze(self, value):
        return super().analyze(self._expand(str(value)))

    def analyze_positions(self, value):
        return super().analyze_positions(self._expand(str(value)))


class SparseVectorFieldMapper(FieldMapper):
    """`sparse_vector` (reference: x-pack/plugin/vectors
    SparseVectorFieldMapper.java, deprecated in the snapshot): map of
    dimension→weight, stored for script access."""

    type_name = "sparse_vector"

    def coerce(self, value) -> dict:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] sparse_vector value must be an object")
        return {str(k): float(v) for k, v in value.items()}

    def doc_value(self, value):
        return self.coerce(value)


class GeoShapeFieldMapper(FieldMapper):
    """`geo_shape` (reference: index/mapper/GeoShapeFieldMapper.java +
    libs/geo): GeoJSON (or WKT envelope/point) geometries. Indexed as the
    shape's bounding envelope; geo_shape queries run envelope relations —
    a documented approximation of the reference's triangulated BKD index."""

    type_name = "geo_shape"

    def coerce(self, value) -> dict:
        shape = self._parse_shape(value)
        env = shape_envelope(shape)
        return {"shape": shape, "envelope": env}

    def _parse_shape(self, value) -> dict:
        if isinstance(value, dict) and "type" in value:
            t = str(value["type"]).lower()
            if t == "geometrycollection":
                geoms = [self._parse_shape(g)
                         for g in value.get("geometries", [])]
                return {"type": "geometrycollection", "geometries": geoms}
            if "coordinates" not in value:
                raise MapperParsingError(
                    f"[{self.name}] geo_shape requires [coordinates]")
            return {"type": t, "coordinates": value["coordinates"]}
        if isinstance(value, str):
            return parse_wkt(value, self.name)
        raise MapperParsingError(
            f"[{self.name}] failed to parse geo_shape value [{value}]")

    def doc_value(self, value):
        return self.coerce(value)


def shape_envelope(shape: dict) -> Tuple[float, float, float, float]:
    """(min_lon, min_lat, max_lon, max_lat) of a normalized shape dict."""
    if shape["type"] == "geometrycollection":
        envs = [shape_envelope(g) for g in shape["geometries"]]
        return (min(e[0] for e in envs), min(e[1] for e in envs),
                max(e[2] for e in envs), max(e[3] for e in envs))
    coords = shape["coordinates"]
    if shape["type"] == "envelope":
        # [[min_lon, max_lat], [max_lon, min_lat]] — ES envelope order
        (min_lon, max_lat), (max_lon, min_lat) = coords
        return (float(min_lon), float(min_lat), float(max_lon), float(max_lat))
    pts = list(_iter_positions(coords))
    if not pts:
        raise MapperParsingError("geo_shape has no coordinates")
    lons = [p[0] for p in pts]
    lats = [p[1] for p in pts]
    return (min(lons), min(lats), max(lons), max(lats))


def _iter_positions(coords):
    if isinstance(coords, (list, tuple)):
        if len(coords) >= 2 and all(
                isinstance(c, (int, float)) for c in coords[:2]):
            yield float(coords[0]), float(coords[1])
        else:
            for c in coords:
                yield from _iter_positions(c)


def parse_wkt(s: str, field: str = "") -> dict:
    """Minimal WKT: POINT, ENVELOPE (ES extension), POLYGON, LINESTRING."""
    m = re.match(r"\s*(\w+)\s*\((.*)\)\s*$", s, re.DOTALL)
    if not m:
        raise MapperParsingError(f"[{field}] failed to parse WKT [{s}]")
    kind = m.group(1).upper()
    body = m.group(2)

    def pts(text):
        out = []
        for pair in text.split(","):
            xy = pair.split()
            out.append([float(xy[0]), float(xy[1])])
        return out

    if kind == "POINT":
        return {"type": "point", "coordinates": pts(body)[0]}
    if kind == "ENVELOPE":
        # ENVELOPE(min_lon, max_lon, max_lat, min_lat) — WKT/ES order
        v = [float(x) for x in body.split(",")]
        return {"type": "envelope",
                "coordinates": [[v[0], v[2]], [v[1], v[3]]]}
    if kind == "LINESTRING":
        return {"type": "linestring", "coordinates": pts(body)}
    if kind == "POLYGON":
        rings = re.findall(r"\(([^()]*)\)", body)
        return {"type": "polygon", "coordinates": [pts(r) for r in rings]}
    raise MapperParsingError(f"[{field}] unsupported WKT type [{kind}]")


class AliasFieldMapper(FieldMapper):
    """`alias` (reference: index/mapper/FieldAliasMapper.java): query-time
    alternate name for a concrete field. Resolved in MapperService.get /
    resolve_field; writes through an alias are rejected."""

    type_name = "alias"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        if not self.params.get("path"):
            raise MapperParsingError(f"[{name}] alias requires [path]")
        self.path = self.params["path"]


FIELD_TYPES = {
    m.type_name: m
    for m in (KeywordFieldMapper, TextFieldMapper, LongFieldMapper, IntegerFieldMapper,
              ShortFieldMapper, ByteFieldMapper, DoubleFieldMapper, FloatFieldMapper,
              HalfFloatFieldMapper, ScaledFloatFieldMapper, BooleanFieldMapper,
              DateFieldMapper, DateNanosFieldMapper, IpFieldMapper,
              GeoPointFieldMapper,
              DenseVectorFieldMapper, ObjectMapper, NestedMapper,
              RankFeatureFieldMapper, RankFeaturesFieldMapper,
              RankVectorsFieldMapper,
              JoinFieldMapper, PercolatorFieldMapper,
              BinaryFieldMapper, IntegerRangeFieldMapper, LongRangeFieldMapper,
              FloatRangeFieldMapper, DoubleRangeFieldMapper,
              DateRangeFieldMapper, IpRangeFieldMapper,
              CompletionFieldMapper, SearchAsYouTypeFieldMapper,
              TokenCountFieldMapper, WildcardFieldMapper,
              ConstantKeywordFieldMapper, Murmur3FieldMapper,
              HistogramFieldMapper, FlattenedFieldMapper,
              AnnotatedTextFieldMapper, SparseVectorFieldMapper,
              GeoShapeFieldMapper, AliasFieldMapper)
}


def build_mapper(name: str, definition: dict,
                 registry: Optional[AnalysisRegistry] = None) -> FieldMapper:
    leaf = name.rpartition(".")[2]
    if leaf == "":
        # ObjectMapper.Builder rejects empty field names
        raise IllegalArgumentError("field name cannot be an empty string")
    t = definition.get("type", "object" if "properties" in definition else None)
    if t is None:
        raise MapperParsingError(f"no type specified for field [{name}]")
    cls = FIELD_TYPES.get(t)
    if cls is None:
        raise MapperParsingError(f"No handler for type [{t}] declared on field [{name}]")
    params = {k: v for k, v in definition.items() if k not in ("type", "properties", "fields")}
    if registry is not None and issubclass(cls, (TextFieldMapper,
                                                 TokenCountFieldMapper)):
        return cls(name, params, registry=registry)
    return cls(name, params)


# ---------------------------------------------------------------------------
# MapperService / DocumentMapper
# ---------------------------------------------------------------------------

class MapperService:
    """Holds the (mutable, additive-only) mapping for one index.

    Reference: MapperService.java — mappings merge additively; changing an
    existing field's type is rejected.
    """

    def __init__(self, mapping: Optional[dict] = None, dynamic: bool = True,
                 registry: Optional[AnalysisRegistry] = None):
        # flat map "a.b.c" -> FieldMapper
        self._mappers: Dict[str, FieldMapper] = {}
        # fields with subfields (multi-fields), e.g. text with .keyword
        self._multi_fields: Dict[str, Dict[str, FieldMapper]] = {}
        self.registry = registry or DEFAULT_REGISTRY
        # fields whose fielddata/global-ordinals were materialized by a
        # search (stats report bytes only for loaded fields)
        self.loaded_fielddata: set = set()
        self.dynamic = dynamic
        self._meta: dict = {}
        # set on any mapping mutation; cleared by whoever persists the mapping
        self.dirty = False
        self.source_enabled = True
        if mapping:
            self.merge(mapping)

    # -- mapping CRUD --------------------------------------------------------
    def merge(self, mapping: dict) -> None:
        if "properties" in mapping:
            props = mapping["properties"]
        else:
            # a bare props dict; strip metadata sections (_source/_meta/
            # dynamic/_routing) which are NOT field definitions
            props = {k: v for k, v in mapping.items()
                     if not k.startswith("_") and k != "dynamic"}
        if "dynamic" in mapping:
            dyn = mapping["dynamic"]
            self.dynamic = dyn if isinstance(dyn, bool) else dyn == "true"
        if "_meta" in mapping:
            self._meta = mapping["_meta"]
        if isinstance(mapping.get("_source"), dict) \
                and mapping["_source"].get("enabled") is False:
            # _source disabled: stored internally (the engine needs it),
            # but never rendered and GET /_source 404s
            self.source_enabled = False
        self._merge_props(props, prefix="")

    def _merge_props(self, props: dict, prefix: str) -> None:
        for name, definition in props.items():
            if not isinstance(definition, dict):
                raise MapperParsingError(f"invalid mapping definition for [{prefix}{name}]")
            path = f"{prefix}{name}"
            if "properties" in definition:
                self._merge_props(definition["properties"], prefix=path + ".")
                if definition.get("type") == "nested":
                    self._put(path, NestedMapper(path, {}))
                continue
            mapper = build_mapper(path, definition, self.registry)
            self._put(path, mapper)
            for sub_name, sub_def in (definition.get("fields") or {}).items():
                sub_path = f"{path}.{sub_name}"
                sub = build_mapper(sub_path, sub_def, self.registry)
                self._multi_fields.setdefault(path, {})[sub_name] = sub
                self._put(sub_path, sub)
            if isinstance(mapper, SearchAsYouTypeFieldMapper):
                # auto shingle/prefix subfields (reference:
                # SearchAsYouTypeFieldMapper.java builds them in the builder)
                analyzer_params = {k: v for k, v in mapper.params.items()
                                   if k in ("analyzer", "search_analyzer")}
                subs = {
                    "_2gram": _ShingleTextMapper(f"{path}._2gram",
                                                 analyzer_params, 2),
                    "_3gram": _ShingleTextMapper(f"{path}._3gram",
                                                 analyzer_params, 3),
                    "_index_prefix": _PrefixTextMapper(f"{path}._index_prefix",
                                                       analyzer_params),
                }
                for sub_name, sub in subs.items():
                    self._multi_fields.setdefault(path, {})[sub_name] = sub
                    self._mappers[f"{path}.{sub_name}"] = sub

    def _put(self, path: str, mapper: FieldMapper) -> None:
        existing = self._mappers.get(path)
        if existing is not None and existing.type_name != mapper.type_name:
            raise IllegalArgumentError(
                f"mapper [{path}] cannot be changed from type [{existing.type_name}] "
                f"to [{mapper.type_name}]")
        if existing is None:
            self.dirty = True
        self._mappers[path] = mapper

    def get(self, path: str) -> Optional[FieldMapper]:
        """Mapper for a path; `alias` fields resolve to their target
        (reference: FieldAliasMapper — aliases are query-time only)."""
        mapper = self._mappers.get(path)
        if isinstance(mapper, AliasFieldMapper):
            target = self._mappers.get(mapper.path)
            return target if not isinstance(target, AliasFieldMapper) else None
        return mapper

    def get_raw(self, path: str) -> Optional[FieldMapper]:
        return self._mappers.get(path)

    def mark_fielddata_loaded(self, field: str) -> None:
        self.loaded_fielddata.add(field)

    def resolve_field(self, path: str) -> str:
        """Follow an alias to its concrete field name (one hop)."""
        mapper = self._mappers.get(path)
        if isinstance(mapper, AliasFieldMapper):
            return mapper.path
        return path

    def all_mappers(self):
        return list(self._mappers.items())

    def field_names(self) -> List[str]:
        return sorted(self._mappers)

    def vector_fields(self) -> Dict[str, DenseVectorFieldMapper]:
        return {p: m for p, m in self._mappers.items()
                if isinstance(m, DenseVectorFieldMapper)}

    def to_dict(self) -> dict:
        """Render back to the API mapping shape (GET /index/_mapping)."""
        root: dict = {}
        for path in sorted(self._mappers):
            mapper = self._mappers[path]
            if isinstance(mapper, (ObjectMapper,)):
                continue
            parts = path.split(".")
            # multi-fields render under "fields", not "properties"
            parent = ".".join(parts[:-1])
            if parent in self._multi_fields and parts[-1] in self._multi_fields[parent]:
                continue
            node = root
            for p in parts[:-1]:
                node = node.setdefault("properties", {}).setdefault(p, {})
            leaf = node.setdefault("properties", {}).setdefault(parts[-1], {})
            leaf.update(mapper.to_def())
            if path in self._multi_fields:
                leaf["fields"] = {sub: m.to_def()
                                  for sub, m in self._multi_fields[path].items()}
        out = {"properties": root.get("properties", {})}
        if self._meta:
            out["_meta"] = self._meta
        return out

    # -- document parsing ----------------------------------------------------
    def parse_document(self, doc_id: str, source: dict) -> ParsedDocument:
        """Parse a source document (reference: DocumentParser.parseDocument)."""
        if not isinstance(source, dict):
            raise MapperParsingError("document source must be an object")
        limit = getattr(self, "nested_objects_limit", None)
        if limit is not None:
            n_nested = self._count_nested_docs(source, "")
            if n_nested > limit:
                raise IllegalArgumentError(
                    f"The number of nested documents has exceeded the "
                    f"allowed limit of [{limit}]. This limit can be set by "
                    f"changing the [index.mapping.nested_objects.limit] "
                    f"index level setting.")
        parsed = ParsedDocument(doc_id, source)
        self._parse_object(source, "", parsed)
        return parsed

    def _count_nested_docs(self, obj: dict, prefix: str) -> int:
        """Count the Lucene sub-documents nested arrays expand into
        (DocumentParser nested-doc accounting)."""
        total = 0
        for k, v in obj.items():
            path = prefix + k
            mapper = self.get(path)
            is_nested = mapper is not None and \
                getattr(mapper, "type_name", "") == "nested"
            if isinstance(v, list):
                dict_items = [i for i in v if isinstance(i, dict)]
                if is_nested:
                    total += len(dict_items)
                for item in dict_items:
                    total += self._count_nested_docs(item, path + ".")
            elif isinstance(v, dict):
                if is_nested:
                    total += 1
                total += self._count_nested_docs(v, path + ".")
        return total

    def _parse_object(self, obj: dict, prefix: str, parsed: ParsedDocument) -> None:
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if isinstance(value, dict) and self.get(path) is None or (
                    isinstance(value, dict) and isinstance(self.get(path), (ObjectMapper, NestedMapper))):
                self._parse_object(value, path + ".", parsed)
                continue
            if isinstance(value, dict) and isinstance(self.get(path), (
                    GeoPointFieldMapper, FlattenedFieldMapper,
                    HistogramFieldMapper, GeoShapeFieldMapper,
                    SparseVectorFieldMapper, RangeFieldMapperBase,
                    CompletionFieldMapper, JoinFieldMapper,
                    PercolatorFieldMapper, RankFeaturesFieldMapper)):
                self._parse_field(path, value, parsed)
                continue
            if isinstance(value, list) and value and isinstance(value[0], dict) \
                    and isinstance(self.get(path), (
                        GeoPointFieldMapper, FlattenedFieldMapper,
                        HistogramFieldMapper, GeoShapeFieldMapper,
                        SparseVectorFieldMapper, RangeFieldMapperBase,
                        CompletionFieldMapper, RankFeaturesFieldMapper)):
                # arrays of dict-valued field values (multi-valued ranges,
                # shapes, …) — each element is one field value, not an object
                self._parse_field(path, value, parsed)
                continue
            if isinstance(value, list) and value and isinstance(value[0], dict):
                # array of objects (nested docs stored flattened)
                for item in value:
                    if isinstance(item, dict):
                        self._parse_object(item, path + ".", parsed)
                continue
            self._parse_field(path, value, parsed)

    def _parse_field(self, path: str, value: Any, parsed: ParsedDocument) -> None:
        if isinstance(self.get_raw(path), AliasFieldMapper):
            raise MapperParsingError(f"Cannot write to a field alias [{path}].")
        mapper = self.get(path)
        if mapper is None:
            if value is None:
                return
            if not self.dynamic:
                return  # dynamic:false — unmapped fields not indexed, still in _source
            mapper = self._dynamic_mapper(path, value)
            if mapper is None:
                return
            self._put(path, mapper)
            parsed.dynamic_updates[path] = mapper.to_def()
            # dynamic strings get the reference's default text + .keyword multi-field
            if isinstance(mapper, TextFieldMapper):
                kw = KeywordFieldMapper(f"{path}.keyword", {"ignore_above": 256})
                self._multi_fields.setdefault(path, {})["keyword"] = kw
                self._put(f"{path}.keyword", kw)

        # dense_vector: the array IS the single value, not multi-values;
        # geo_point [lon, lat] numeric pairs too (GeoJSON order —
        # GeoPointFieldMapper parse() array form)
        if isinstance(mapper, DenseVectorFieldMapper):
            values = [value]
        elif (isinstance(mapper, GeoPointFieldMapper)
              and isinstance(value, (list, tuple)) and len(value) == 2
              and all(isinstance(v, (int, float)) and
                      not isinstance(v, bool) for v in value)):
            values = [value]
        else:
            values = value if isinstance(value, list) else [value]
        for v in values:
            if v is None:
                continue
            try:
                self._index_one(path, mapper, v, parsed)
            except MapperParsingError:
                # ignore_malformed: drop the unparseable VALUE, keep the doc
                # (IgnoreMalformedStoredValues; the field lands in _ignored)
                if not mapper.params.get("ignore_malformed"):
                    raise
                parsed.doc_values.setdefault("_ignored", [])
                if path not in parsed.doc_values["_ignored"]:
                    parsed.doc_values["_ignored"].append(path)
                    # _ignored is searchable (term/terms/exists) like the
                    # reference's IgnoredFieldMapper metadata field
                    parsed.terms.setdefault("_ignored", []).append(path)
                continue
            for sub_name, sub in self._multi_fields.get(path, {}).items():
                self._index_one(f"{path}.{sub_name}", sub, v, parsed)

    def _index_one(self, path: str, mapper: FieldMapper, v: Any, parsed: ParsedDocument) -> None:
        if isinstance(mapper, AliasFieldMapper):
            raise MapperParsingError(
                f"Cannot write to a field alias [{path}].")
        if isinstance(mapper, FlattenedFieldMapper):
            obj = mapper.coerce(v)
            root_terms = parsed.terms.setdefault(path, [])
            for key_path, leaf in mapper.leaves(obj):
                root_terms.append(leaf)
                parsed.terms.setdefault(f"{path}.{key_path}", []).append(leaf)
            parsed.doc_values[path] = obj
            return
        if isinstance(mapper, DenseVectorFieldMapper):
            if path in parsed.vectors:
                raise MapperParsingError(f"[{path}] only one vector per document")
            parsed.vectors[path] = mapper.coerce(v)
            return
        if isinstance(mapper, TextFieldMapper):
            tokens = mapper.analyze_positions(v)
            bucket = parsed.terms.setdefault(path, [])
            pos_map = parsed.term_positions.setdefault(path, {})
            base = parsed.field_lengths.get(path, 0)
            for t in tokens:
                bucket.append(t.term)
                pos_map.setdefault(t.term, []).append(base + t.position)
            parsed.field_lengths[path] = base + len(tokens)
            return
        terms = mapper.index_terms(v)
        if terms:
            parsed.terms.setdefault(path, []).extend(terms)
        dv = mapper.doc_value(v)
        if dv is not None:
            existing = parsed.doc_values.get(path)
            if existing is None:
                parsed.doc_values[path] = dv
            elif isinstance(existing, list):
                existing.append(dv)
            else:
                parsed.doc_values[path] = [existing, dv]

    def _dynamic_mapper(self, path: str, value: Any) -> Optional[FieldMapper]:
        probe = value[0] if isinstance(value, list) and value else value
        if isinstance(probe, bool):
            return BooleanFieldMapper(path, {})
        if isinstance(probe, int):
            return LongFieldMapper(path, {})
        if isinstance(probe, float):
            return FloatFieldMapper(path, {})
        if isinstance(probe, str):
            try:
                parse_date_millis(probe) if re.match(r"\d{4}-\d{2}-\d{2}", probe) else None
            except MapperParsingError:
                pass
            else:
                if re.match(r"\d{4}-\d{2}-\d{2}", probe):
                    return DateFieldMapper(path, {})
            return TextFieldMapper(path, {}, registry=self.registry)
        return None
