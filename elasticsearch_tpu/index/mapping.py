"""Mappings: field types, document parsing, dynamic mapping.

Re-design of the reference's mapper layer (`index/mapper/` — MapperService,
DocumentMapper, DocumentParser, FieldMapper subclasses; SURVEY.md §2.4).
A mapping is a tree of typed field definitions; parsing a JSON document
produces a `ParsedDocument`: analyzed terms for the inverted index, typed
values for doc-values columns, dense vectors for the device matrix, and the
stored `_source`.

Field types covered: text, keyword, long/integer/short/byte, double/float/
half_float, boolean, date, ip, geo_point, dense_vector, object, nested
(stored flattened with nested paths), plus dynamic inference for unmapped
fields (reference `DynamicTemplates`/`DocumentParser.parseDynamicValue`).

dense_vector follows `x-pack/plugin/vectors/.../DenseVectorFieldMapper.java:45`
semantics: fixed `dims`, float array values, one vector per doc — but the
2048-dim cap is lifted (the TPU path has no BinaryDocValues encoding limit)
and a `similarity` parameter selects the device metric at index time.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import math
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError, MapperParsingError
from elasticsearch_tpu.index.analysis import AnalysisRegistry, DEFAULT_REGISTRY

# ---------------------------------------------------------------------------
# Parsed output containers
# ---------------------------------------------------------------------------


class ParsedDocument:
    """Everything the engine needs to index one document."""

    __slots__ = ("doc_id", "source", "terms", "term_positions", "doc_values",
                 "vectors", "field_lengths", "dynamic_updates")

    def __init__(self, doc_id: str, source: dict):
        self.doc_id = doc_id
        self.source = source
        # field -> list of terms (with duplicates, for tf)
        self.terms: Dict[str, List[str]] = {}
        # field -> term -> positions list
        self.term_positions: Dict[str, Dict[str, List[int]]] = {}
        # field -> scalar or list (kept typed: int/float/str/bool)
        self.doc_values: Dict[str, Any] = {}
        # field -> np.ndarray[dims] f32
        self.vectors: Dict[str, np.ndarray] = {}
        # field -> token count (for BM25 norms)
        self.field_lengths: Dict[str, int] = {}
        # mapping updates triggered by dynamic fields (field path -> mapper def)
        self.dynamic_updates: Dict[str, dict] = {}


# ---------------------------------------------------------------------------
# Field mappers
# ---------------------------------------------------------------------------

EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
_DATE_PATTERNS = (
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d",
)


def parse_date_millis(value: Any) -> int:
    """Parse a date into epoch millis (reference: DateFieldMapper, strict_date_optional_time||epoch_millis)."""
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if re.fullmatch(r"-?\d{10,}", s):
        return int(s)
    norm = s.replace("Z", "+0000")
    if re.search(r"[+-]\d{2}:\d{2}$", norm):
        norm = norm[:-3] + norm[-2:]
    for pat in _DATE_PATTERNS:
        try:
            dt = _dt.datetime.strptime(norm, pat)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date field [{value}]")


class FieldMapper:
    type_name = "base"

    def __init__(self, name: str, params: Optional[dict] = None):
        self.name = name
        self.params = dict(params or {})

    # returns list of index terms; default: none
    def index_terms(self, value: Any) -> List[str]:
        return []

    # returns the doc-values representation (comparable/sortable), or None
    def doc_value(self, value: Any) -> Any:
        return None

    def to_def(self) -> dict:
        d = {"type": self.type_name}
        d.update(self.params)
        return d


class KeywordFieldMapper(FieldMapper):
    type_name = "keyword"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.ignore_above = self.params.get("ignore_above")

    def index_terms(self, value):
        s = str(value)
        if self.ignore_above is not None and len(s) > self.ignore_above:
            return []
        return [s]

    def doc_value(self, value):
        return str(value)


class TextFieldMapper(FieldMapper):
    type_name = "text"

    def __init__(self, name, params=None, registry: AnalysisRegistry = DEFAULT_REGISTRY):
        super().__init__(name, params)
        self.analyzer = registry.get(self.params.get("analyzer", "standard"))
        self.search_analyzer = registry.get(
            self.params.get("search_analyzer", self.params.get("analyzer", "standard")))

    def analyze(self, value) -> List[str]:
        return self.analyzer.terms(str(value))

    def analyze_positions(self, value):
        return self.analyzer.analyze(str(value))

    def index_terms(self, value):
        return self.analyze(value)

    def doc_value(self, value):
        return None  # text has no doc_values (reference: fielddata disabled by default)


class _NumericMapper(FieldMapper):
    py_type = float

    def coerce(self, value: Any):
        if isinstance(value, bool):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type_name}]: boolean")
        try:
            v = self.py_type(value)
        except (TypeError, ValueError):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.type_name}] value [{value}]")
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            raise MapperParsingError(f"[{self.name}] non-finite value [{value}]")
        return v

    def index_terms(self, value):
        return [repr(self.coerce(value))]

    def doc_value(self, value):
        return self.coerce(value)


class LongFieldMapper(_NumericMapper):
    type_name = "long"
    py_type = int


class IntegerFieldMapper(LongFieldMapper):
    type_name = "integer"


class ShortFieldMapper(LongFieldMapper):
    type_name = "short"


class ByteFieldMapper(LongFieldMapper):
    type_name = "byte"


class DoubleFieldMapper(_NumericMapper):
    type_name = "double"
    py_type = float


class FloatFieldMapper(DoubleFieldMapper):
    type_name = "float"


class HalfFloatFieldMapper(DoubleFieldMapper):
    type_name = "half_float"


class ScaledFloatFieldMapper(_NumericMapper):
    type_name = "scaled_float"
    py_type = float

    def doc_value(self, value):
        factor = self.params.get("scaling_factor", 100)
        return round(self.coerce(value) * factor) / factor


class BooleanFieldMapper(FieldMapper):
    type_name = "boolean"

    def coerce(self, value):
        if isinstance(value, bool):
            return value
        if value in ("true", "True"):
            return True
        if value in ("false", "False", ""):
            return False
        raise MapperParsingError(f"failed to parse boolean field [{self.name}] value [{value}]")

    def index_terms(self, value):
        return ["T" if self.coerce(value) else "F"]

    def doc_value(self, value):
        return self.coerce(value)


class DateFieldMapper(FieldMapper):
    type_name = "date"

    def index_terms(self, value):
        return [str(parse_date_millis(value))]

    def doc_value(self, value):
        return parse_date_millis(value)


class IpFieldMapper(FieldMapper):
    type_name = "ip"

    def coerce(self, value) -> int:
        try:
            return int(ipaddress.ip_address(str(value)))
        except ValueError:
            raise MapperParsingError(f"failed to parse IP [{value}] for field [{self.name}]")

    def index_terms(self, value):
        return [str(self.coerce(value))]

    def doc_value(self, value):
        return self.coerce(value)


class GeoPointFieldMapper(FieldMapper):
    type_name = "geo_point"

    def coerce(self, value) -> Tuple[float, float]:
        """Returns (lat, lon)."""
        if isinstance(value, dict):
            try:
                return float(value["lat"]), float(value["lon"])
            except (KeyError, TypeError, ValueError):
                raise MapperParsingError(f"failed to parse geo_point [{value}]")
        if isinstance(value, (list, tuple)) and len(value) == 2:
            return float(value[1]), float(value[0])  # [lon, lat] GeoJSON order
        if isinstance(value, str):
            parts = value.split(",")
            if len(parts) == 2:
                return float(parts[0]), float(parts[1])
        raise MapperParsingError(f"failed to parse geo_point [{value}]")

    def doc_value(self, value):
        return self.coerce(value)


class DenseVectorFieldMapper(FieldMapper):
    """`dense_vector` (reference: DenseVectorFieldMapper.java:45).

    params: dims (required), similarity (cosine|dot_product|l2_norm,
    default cosine), index_options.type (flat|int8_flat — storage dtype of
    the device matrix).
    """

    type_name = "dense_vector"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.dims = self.params.get("dims")
        if self.dims is None:
            raise MapperParsingError(f"[{name}] dense_vector requires [dims]")
        self.dims = int(self.dims)
        self.similarity = self.params.get("similarity", "cosine")
        if self.similarity not in ("cosine", "dot_product", "l2_norm", "max_inner_product"):
            raise MapperParsingError(f"[{name}] unknown similarity [{self.similarity}]")

    def coerce(self, value) -> np.ndarray:
        if not isinstance(value, (list, tuple)):
            raise MapperParsingError(f"[{self.name}] dense_vector value must be an array")
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1 or arr.shape[0] != self.dims:
            raise MapperParsingError(
                f"[{self.name}] vector has [{arr.shape[0] if arr.ndim == 1 else '?'}] "
                f"dimensions, mapping requires [{self.dims}]")
        if not np.isfinite(arr).all():
            raise MapperParsingError(f"[{self.name}] vector contains non-finite values")
        return arr


class ObjectMapper(FieldMapper):
    type_name = "object"


class NestedMapper(FieldMapper):
    type_name = "nested"


class RankFeatureFieldMapper(FieldMapper):
    """`rank_feature` (reference: modules/mapper-extras
    RankFeatureFieldMapper) — positive float consumed by rank_feature
    queries."""

    type_name = "rank_feature"

    def coerce(self, value) -> float:
        v = float(value)
        if v <= 0 and not self.params.get("positive_score_impact", True) is False:
            if v < 0:
                raise MapperParsingError(
                    f"[{self.name}] rank_feature fields only support positive "
                    f"values, got [{value}]")
        return v

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class RankFeaturesFieldMapper(FieldMapper):
    """`rank_features`: a sparse map feature→weight."""

    type_name = "rank_features"

    def coerce(self, value) -> dict:
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] rank_features value must be an object")
        return {str(k): float(v) for k, v in value.items()}

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


class JoinFieldMapper(FieldMapper):
    """`join` (reference: modules/parent-join ParentJoinFieldMapper):
    relations define parent→children; doc value keeps {name, parent}."""

    type_name = "join"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.relations: Dict[str, List[str]] = {}
        for parent, children in (self.params.get("relations") or {}).items():
            self.relations[parent] = (children if isinstance(children, list)
                                      else [children])

    def coerce(self, value):
        if isinstance(value, str):
            return {"name": value}
        if isinstance(value, dict) and "name" in value:
            return value
        raise MapperParsingError(f"[{self.name}] join value must be a "
                                 f"relation name or {{name, parent}}")

    def index_terms(self, value):
        return [self.coerce(value)["name"]]

    def doc_value(self, value):
        return self.coerce(value)


class PercolatorFieldMapper(FieldMapper):
    """`percolator` (reference: modules/percolator PercolatorFieldMapper):
    stores a query to run in reverse against candidate documents."""

    type_name = "percolator"

    def coerce(self, value):
        if not isinstance(value, dict):
            raise MapperParsingError(
                f"[{self.name}] percolator field must hold a query object")
        # validate eagerly like the reference (parse at index time)
        from elasticsearch_tpu.search.queries import parse_query
        parse_query(value)
        return value

    def index_terms(self, value):
        return []

    def doc_value(self, value):
        return self.coerce(value)


FIELD_TYPES = {
    m.type_name: m
    for m in (KeywordFieldMapper, TextFieldMapper, LongFieldMapper, IntegerFieldMapper,
              ShortFieldMapper, ByteFieldMapper, DoubleFieldMapper, FloatFieldMapper,
              HalfFloatFieldMapper, ScaledFloatFieldMapper, BooleanFieldMapper,
              DateFieldMapper, IpFieldMapper, GeoPointFieldMapper,
              DenseVectorFieldMapper, ObjectMapper, NestedMapper,
              RankFeatureFieldMapper, RankFeaturesFieldMapper,
              JoinFieldMapper, PercolatorFieldMapper)
}


def build_mapper(name: str, definition: dict) -> FieldMapper:
    t = definition.get("type", "object" if "properties" in definition else None)
    if t is None:
        raise MapperParsingError(f"no type specified for field [{name}]")
    cls = FIELD_TYPES.get(t)
    if cls is None:
        raise MapperParsingError(f"No handler for type [{t}] declared on field [{name}]")
    params = {k: v for k, v in definition.items() if k not in ("type", "properties", "fields")}
    return cls(name, params)


# ---------------------------------------------------------------------------
# MapperService / DocumentMapper
# ---------------------------------------------------------------------------

class MapperService:
    """Holds the (mutable, additive-only) mapping for one index.

    Reference: MapperService.java — mappings merge additively; changing an
    existing field's type is rejected.
    """

    def __init__(self, mapping: Optional[dict] = None, dynamic: bool = True):
        # flat map "a.b.c" -> FieldMapper
        self._mappers: Dict[str, FieldMapper] = {}
        # fields with subfields (multi-fields), e.g. text with .keyword
        self._multi_fields: Dict[str, Dict[str, FieldMapper]] = {}
        self.dynamic = dynamic
        self._meta: dict = {}
        # set on any mapping mutation; cleared by whoever persists the mapping
        self.dirty = False
        if mapping:
            self.merge(mapping)

    # -- mapping CRUD --------------------------------------------------------
    def merge(self, mapping: dict) -> None:
        props = mapping.get("properties", mapping if "properties" not in mapping else {})
        if "dynamic" in mapping:
            dyn = mapping["dynamic"]
            self.dynamic = dyn if isinstance(dyn, bool) else dyn == "true"
        if "_meta" in mapping:
            self._meta = mapping["_meta"]
        self._merge_props(props, prefix="")

    def _merge_props(self, props: dict, prefix: str) -> None:
        for name, definition in props.items():
            if not isinstance(definition, dict):
                raise MapperParsingError(f"invalid mapping definition for [{prefix}{name}]")
            path = f"{prefix}{name}"
            if "properties" in definition:
                self._merge_props(definition["properties"], prefix=path + ".")
                if definition.get("type") == "nested":
                    self._put(path, NestedMapper(path, {}))
                continue
            mapper = build_mapper(path, definition)
            self._put(path, mapper)
            for sub_name, sub_def in (definition.get("fields") or {}).items():
                sub_path = f"{path}.{sub_name}"
                sub = build_mapper(sub_path, sub_def)
                self._multi_fields.setdefault(path, {})[sub_name] = sub
                self._put(sub_path, sub)

    def _put(self, path: str, mapper: FieldMapper) -> None:
        existing = self._mappers.get(path)
        if existing is not None and existing.type_name != mapper.type_name:
            raise IllegalArgumentError(
                f"mapper [{path}] cannot be changed from type [{existing.type_name}] "
                f"to [{mapper.type_name}]")
        if existing is None:
            self.dirty = True
        self._mappers[path] = mapper

    def get(self, path: str) -> Optional[FieldMapper]:
        return self._mappers.get(path)

    def all_mappers(self):
        return list(self._mappers.items())

    def field_names(self) -> List[str]:
        return sorted(self._mappers)

    def vector_fields(self) -> Dict[str, DenseVectorFieldMapper]:
        return {p: m for p, m in self._mappers.items()
                if isinstance(m, DenseVectorFieldMapper)}

    def to_dict(self) -> dict:
        """Render back to the API mapping shape (GET /index/_mapping)."""
        root: dict = {}
        for path in sorted(self._mappers):
            mapper = self._mappers[path]
            if isinstance(mapper, (ObjectMapper,)):
                continue
            parts = path.split(".")
            # multi-fields render under "fields", not "properties"
            parent = ".".join(parts[:-1])
            if parent in self._multi_fields and parts[-1] in self._multi_fields[parent]:
                continue
            node = root
            for p in parts[:-1]:
                node = node.setdefault("properties", {}).setdefault(p, {})
            leaf = node.setdefault("properties", {}).setdefault(parts[-1], {})
            leaf.update(mapper.to_def())
            if path in self._multi_fields:
                leaf["fields"] = {sub: m.to_def()
                                  for sub, m in self._multi_fields[path].items()}
        out = {"properties": root.get("properties", {})}
        if self._meta:
            out["_meta"] = self._meta
        return out

    # -- document parsing ----------------------------------------------------
    def parse_document(self, doc_id: str, source: dict) -> ParsedDocument:
        """Parse a source document (reference: DocumentParser.parseDocument)."""
        if not isinstance(source, dict):
            raise MapperParsingError("document source must be an object")
        parsed = ParsedDocument(doc_id, source)
        self._parse_object(source, "", parsed)
        return parsed

    def _parse_object(self, obj: dict, prefix: str, parsed: ParsedDocument) -> None:
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if isinstance(value, dict) and self.get(path) is None or (
                    isinstance(value, dict) and isinstance(self.get(path), (ObjectMapper, NestedMapper))):
                self._parse_object(value, path + ".", parsed)
                continue
            if isinstance(value, dict) and isinstance(self.get(path), GeoPointFieldMapper):
                self._parse_field(path, value, parsed)
                continue
            if isinstance(value, list) and value and isinstance(value[0], dict):
                # array of objects (nested docs stored flattened)
                for item in value:
                    if isinstance(item, dict):
                        self._parse_object(item, path + ".", parsed)
                continue
            self._parse_field(path, value, parsed)

    def _parse_field(self, path: str, value: Any, parsed: ParsedDocument) -> None:
        mapper = self.get(path)
        if mapper is None:
            if value is None:
                return
            if not self.dynamic:
                return  # dynamic:false — unmapped fields not indexed, still in _source
            mapper = self._dynamic_mapper(path, value)
            if mapper is None:
                return
            self._put(path, mapper)
            parsed.dynamic_updates[path] = mapper.to_def()
            # dynamic strings get the reference's default text + .keyword multi-field
            if isinstance(mapper, TextFieldMapper):
                kw = KeywordFieldMapper(f"{path}.keyword", {"ignore_above": 256})
                self._multi_fields.setdefault(path, {})["keyword"] = kw
                self._put(f"{path}.keyword", kw)

        # dense_vector: the array IS the single value, not multi-values
        if isinstance(mapper, DenseVectorFieldMapper):
            values = [value]
        else:
            values = value if isinstance(value, list) else [value]
        for v in values:
            if v is None:
                continue
            self._index_one(path, mapper, v, parsed)
            for sub_name, sub in self._multi_fields.get(path, {}).items():
                self._index_one(f"{path}.{sub_name}", sub, v, parsed)

    def _index_one(self, path: str, mapper: FieldMapper, v: Any, parsed: ParsedDocument) -> None:
        if isinstance(mapper, DenseVectorFieldMapper):
            if path in parsed.vectors:
                raise MapperParsingError(f"[{path}] only one vector per document")
            parsed.vectors[path] = mapper.coerce(v)
            return
        if isinstance(mapper, TextFieldMapper):
            tokens = mapper.analyze_positions(v)
            bucket = parsed.terms.setdefault(path, [])
            pos_map = parsed.term_positions.setdefault(path, {})
            base = parsed.field_lengths.get(path, 0)
            for t in tokens:
                bucket.append(t.term)
                pos_map.setdefault(t.term, []).append(base + t.position)
            parsed.field_lengths[path] = base + len(tokens)
            return
        terms = mapper.index_terms(v)
        if terms:
            parsed.terms.setdefault(path, []).extend(terms)
        dv = mapper.doc_value(v)
        if dv is not None:
            existing = parsed.doc_values.get(path)
            if existing is None:
                parsed.doc_values[path] = dv
            elif isinstance(existing, list):
                existing.append(dv)
            else:
                parsed.doc_values[path] = [existing, dv]

    def _dynamic_mapper(self, path: str, value: Any) -> Optional[FieldMapper]:
        probe = value[0] if isinstance(value, list) and value else value
        if isinstance(probe, bool):
            return BooleanFieldMapper(path, {})
        if isinstance(probe, int):
            return LongFieldMapper(path, {})
        if isinstance(probe, float):
            return FloatFieldMapper(path, {})
        if isinstance(probe, str):
            try:
                parse_date_millis(probe) if re.match(r"\d{4}-\d{2}-\d{2}", probe) else None
            except MapperParsingError:
                pass
            else:
                if re.match(r"\d{4}-\d{2}-\d{2}", probe):
                    return DateFieldMapper(path, {})
            return TextFieldMapper(path, {})
        return None
