"""Segment storage: inverted index, doc values, stored fields, vectors.

Plays the role Lucene's segment files play under the reference's engine
(`index/engine/InternalEngine.java` writes via IndexWriter; segments are
immutable, deletes are tombstones, merges compact). Re-designed for the TPU
stack:

- postings are numpy arrays (doc ids ascending, freqs parallel) so BM25
  scoring vectorizes on host and can batch to the device;
- doc values are columnar numpy arrays (numerics) / ordinal-encoded string
  columns, feeding sorts and aggregations;
- each segment's dense-vector columns are contiguous [num_docs, dims] f32
  blocks — exactly the shape the device corpus ingests at refresh.

A `SegmentBuilder` accumulates the in-memory indexing buffer; `seal()`
freezes it into an immutable `Segment` (the analog of a Lucene flush making
an NRT reader visible). `ShardReader` is a point-in-time view over sealed
segments + tombstone bitmaps (the analog of acquiring an IndexSearcher).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np


class DocValuesColumn:
    """Columnar per-doc values for one field within one segment.

    values: object array (None = missing); for numerics additionally a
    float64 view + presence mask for vectorized math.
    """

    __slots__ = ("values", "numeric", "present")

    def __init__(self, values: List[Any]):
        self.values = values
        first = next((v for v in values if v is not None), None)
        if isinstance(first, (int, float)) and not isinstance(first, bool):
            arr = np.zeros(len(values), dtype=np.float64)
            present = np.zeros(len(values), dtype=bool)
            for i, v in enumerate(values):
                if v is None:
                    continue
                if isinstance(v, list):
                    arr[i] = float(v[0]) if v else 0.0
                    present[i] = bool(v)
                else:
                    arr[i] = float(v)
                    present[i] = True
            self.numeric = arr
            self.present = present
        else:
            self.numeric = None
            self.present = np.asarray([v is not None for v in values], dtype=bool)

    def get(self, local_doc: int) -> Any:
        return self.values[local_doc]


class Postings:
    """Term postings within one segment: ascending local doc ids + freqs."""

    __slots__ = ("doc_ids", "freqs", "positions")

    def __init__(self, doc_ids: np.ndarray, freqs: np.ndarray,
                 positions: Optional[List[List[int]]] = None):
        self.doc_ids = doc_ids
        self.freqs = freqs
        self.positions = positions

    @property
    def doc_freq(self) -> int:
        return len(self.doc_ids)


class Segment:
    """Immutable sealed segment.

    Weak-referenceable on purpose: the columnar segment block store
    (`elasticsearch_tpu/columnar/`) caches per-(segment, field) column
    extractions against the segment OBJECT, so dropping a segment (an
    engine merge/rewrite) releases its blocks automatically."""

    __slots__ = ("seg_id", "base", "num_docs", "postings", "field_lengths",
                 "total_terms", "doc_values", "vectors", "ids", "sources",
                 "seq_nos", "__weakref__")

    def __init__(self, seg_id: int, base: int, num_docs: int,
                 postings: Dict[str, Dict[str, Postings]],
                 field_lengths: Dict[str, np.ndarray],
                 total_terms: Dict[str, int],
                 doc_values: Dict[str, DocValuesColumn],
                 vectors: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 ids: List[str], sources: List[dict], seq_nos: np.ndarray):
        self.seg_id = seg_id
        self.base = base          # global row id of local doc 0
        self.num_docs = num_docs
        self.postings = postings  # field -> term -> Postings
        self.field_lengths = field_lengths  # field -> int32[num_docs]
        self.total_terms = total_terms      # field -> sum of lengths
        self.doc_values = doc_values        # field -> DocValuesColumn
        self.vectors = vectors              # field -> (matrix [n,d] f32, present bool[n])
        self.ids = ids                      # local doc -> _id
        self.sources = sources              # local doc -> source dict
        self.seq_nos = seq_nos              # local doc -> seq_no

    def get_postings(self, field: str, term: str) -> Optional[Postings]:
        f = self.postings.get(field)
        return f.get(term) if f else None

    def terms_of(self, field: str) -> Iterable[str]:
        return self.postings.get(field, {}).keys()


class SegmentBuilder:
    """In-memory indexing buffer (the analog of Lucene's IndexWriter RAM buffer)."""

    def __init__(self, seg_id: int, base: int):
        self.seg_id = seg_id
        self.base = base
        self._postings: Dict[str, Dict[str, List[Tuple[int, int, Optional[List[int]]]]]] = {}
        self._field_lengths: Dict[str, Dict[int, int]] = {}
        self._doc_values: Dict[str, Dict[int, Any]] = {}
        self._vectors: Dict[str, Dict[int, np.ndarray]] = {}
        self._ids: List[str] = []
        self._sources: List[dict] = []
        self._seq_nos: List[int] = []

    @property
    def num_docs(self) -> int:
        return len(self._ids)

    def add(self, parsed, seq_no: int) -> int:
        """Add a parsed document; returns its local doc id."""
        local = len(self._ids)
        self._ids.append(parsed.doc_id)
        self._sources.append(parsed.source)
        self._seq_nos.append(seq_no)

        for field, terms in parsed.terms.items():
            fp = self._postings.setdefault(field, {})
            counts: Dict[str, int] = {}
            for t in terms:
                counts[t] = counts.get(t, 0) + 1
            pos_map = parsed.term_positions.get(field, {})
            for term, freq in counts.items():
                fp.setdefault(term, []).append((local, freq, pos_map.get(term)))

        for field, length in parsed.field_lengths.items():
            self._field_lengths.setdefault(field, {})[local] = length

        for field, value in parsed.doc_values.items():
            self._doc_values.setdefault(field, {})[local] = value

        for field, vec in parsed.vectors.items():
            self._vectors.setdefault(field, {})[local] = vec

        return local

    def seal(self, order: Optional[List[int]] = None) -> Segment:
        """Seal the buffer into an immutable Segment. `order` (index sort,
        IndexWriterConfig#setIndexSort analog): order[new_local] =
        old_local — documents are physically reordered so `_doc` iteration
        follows the index sort."""
        if order is not None:
            inv = {old: new for new, old in enumerate(order)}
            self._ids = [self._ids[o] for o in order]
            self._sources = [self._sources[o] for o in order]
            self._seq_nos = [self._seq_nos[o] for o in order]
            self._postings = {
                f: {t: [(inv[l], fr, pos) for (l, fr, pos) in entries]
                    for t, entries in terms.items()}
                for f, terms in self._postings.items()}
            self._field_lengths = {
                f: {inv[l]: v for l, v in m.items()}
                for f, m in self._field_lengths.items()}
            self._doc_values = {
                f: {inv[l]: v for l, v in m.items()}
                for f, m in self._doc_values.items()}
            self._vectors = {
                f: {inv[l]: v for l, v in m.items()}
                for f, m in self._vectors.items()}
        n = self.num_docs
        postings: Dict[str, Dict[str, Postings]] = {}
        for field, terms in self._postings.items():
            out: Dict[str, Postings] = {}
            for term, entries in terms.items():
                entries.sort(key=lambda e: e[0])
                doc_ids = np.asarray([e[0] for e in entries], dtype=np.int32)
                freqs = np.asarray([e[1] for e in entries], dtype=np.int32)
                positions = [e[2] for e in entries] if any(e[2] for e in entries) else None
                out[term] = Postings(doc_ids, freqs, positions)
            postings[field] = out

        field_lengths = {}
        total_terms = {}
        for field, lengths in self._field_lengths.items():
            arr = np.zeros(n, dtype=np.int32)
            for local, length in lengths.items():
                arr[local] = length
            field_lengths[field] = arr
            total_terms[field] = int(arr.sum())

        doc_values = {}
        for field, vals in self._doc_values.items():
            col = [vals.get(i) for i in range(n)]
            doc_values[field] = DocValuesColumn(col)

        vectors = {}
        for field, vecs in self._vectors.items():
            dims = len(next(iter(vecs.values())))
            mat = np.zeros((n, dims), dtype=np.float32)
            present = np.zeros(n, dtype=bool)
            for local, v in vecs.items():
                mat[local] = v
                present[local] = True
            vectors[field] = (mat, present)

        return Segment(self.seg_id, self.base, n, postings, field_lengths,
                       total_terms, doc_values, vectors, list(self._ids),
                       list(self._sources), np.asarray(self._seq_nos, dtype=np.int64))


class SegmentView:
    """One segment + its tombstone bitmap inside a point-in-time reader."""

    __slots__ = ("segment", "live")

    def __init__(self, segment: Segment, deleted_locals: Optional[set] = None):
        self.segment = segment
        live = np.ones(segment.num_docs, dtype=bool)
        if deleted_locals:
            live[list(deleted_locals)] = False
        self.live = live

    @property
    def live_count(self) -> int:
        return int(self.live.sum())

    def live_postings(self, field: str
                      ) -> Tuple[Dict[str, Tuple[np.ndarray, np.ndarray]],
                                 np.ndarray, int]:
        """Tombstone-filtered postings of one field in dense live-slot
        space: ({term: (live slots ascending, freqs)}, field lengths per
        live slot, live count).

        Live docs renumber 0..n_live-1 in ascending local order — the
        columnar extraction the device lexical engine (`ops/bm25.py`)
        ingests at refresh through the segment block store
        (`columnar/blocks.extract_postings_block`), owned here because
        the slot/tombstone layout is this layer's contract (the vector
        twin is `columnar/blocks.extract_vector_block`)."""
        seg = self.segment
        n_live = self.live_count
        slot_of = np.cumsum(self.live) - 1  # local doc -> dense live slot
        fl = seg.field_lengths.get(field)
        lengths = np.zeros(n_live, dtype=np.float32)
        if fl is not None and n_live:
            lengths[:] = fl[self.live].astype(np.float32)
        terms: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for term, p in seg.postings.get(field, {}).items():
            keep = self.live[p.doc_ids]
            ids = p.doc_ids[keep]
            if len(ids):
                terms[term] = (slot_of[ids].astype(np.int32),
                               p.freqs[keep])
        return terms, lengths, n_live


_reader_gen = itertools.count(1)


class ShardReader:
    """Point-in-time searcher view over sealed segments.

    The analog of the reference engine's `acquireSearcher`
    (`InternalEngine.java` / `ContextIndexSearcher.java:73`): immutable
    snapshot; concurrent writes/deletes after acquisition are invisible.
    `gen` identifies the view for cache keys (request/query caches key on
    it, so a refresh that produced a new reader invalidates implicitly).
    """

    def __init__(self, views: List[SegmentView]):
        self.views = views
        self.gen = next(_reader_gen)

    @property
    def num_docs(self) -> int:
        return sum(v.live_count for v in self.views)

    @property
    def max_doc(self) -> int:
        return sum(v.segment.num_docs for v in self.views)

    def doc_freq(self, field: str, term: str) -> int:
        total = 0
        for v in self.views:
            p = v.segment.get_postings(field, term)
            if p is not None:
                # count only live postings
                total += int(v.live[p.doc_ids].sum())
        return total

    def total_term_count(self, field: str) -> int:
        return sum(v.segment.total_terms.get(field, 0) for v in self.views)

    def docs_with_field_count(self, field: str) -> int:
        total = 0
        for v in self.views:
            fl = v.segment.field_lengths.get(field)
            if fl is not None:
                total += int((v.live & (fl > 0)).sum())
            else:
                dv = v.segment.doc_values.get(field)
                if dv is not None:
                    total += int((v.live & dv.present).sum())
        return total

    def avg_field_length(self, field: str) -> float:
        docs = self.docs_with_field_count(field)
        if docs == 0:
            return 0.0
        return self.total_term_count(field) / docs

    # -- global row helpers ---------------------------------------------------
    def resolve(self, global_row: int) -> Optional[Tuple[SegmentView, int]]:
        for v in self.views:
            if v.segment.base <= global_row < v.segment.base + v.segment.num_docs:
                return v, global_row - v.segment.base
        return None

    def get_id(self, global_row: int) -> Optional[str]:
        hit = self.resolve(global_row)
        return hit[0].segment.ids[hit[1]] if hit else None

    def get_source(self, global_row: int) -> Optional[dict]:
        hit = self.resolve(global_row)
        return hit[0].segment.sources[hit[1]] if hit else None

    def get_seq_no(self, global_row: int) -> Optional[int]:
        hit = self.resolve(global_row)
        return int(hit[0].segment.seq_nos[hit[1]]) if hit else None

    def get_doc_value(self, field: str, global_row: int) -> Any:
        hit = self.resolve(global_row)
        if hit is None:
            return None
        view, local = hit
        col = view.segment.doc_values.get(field)
        return col.get(local) if col else None

    def live_global_rows(self) -> np.ndarray:
        parts = []
        for v in self.views:
            rows = np.nonzero(v.live)[0] + v.segment.base
            parts.append(rows)
        if not parts:
            return np.zeros(0, dtype=np.int64)
        return np.concatenate(parts)
