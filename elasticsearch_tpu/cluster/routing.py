"""Document → shard routing.

Re-design of `cluster/routing/OperationRouting.java`: shard = murmur3_32(
routing_key) mod num_shards, where routing key defaults to the document id.
The murmur3 implementation matches the x86 32-bit variant the reference uses
(Lucene StringHelper.murmurhash3_x86_32, seed 0) over the SAME byte
encoding — each Java char as two little-endian bytes (UTF-16LE,
`Murmur3HashFunction.java:34-41`) — so document placement is bit-compatible
with the reference for the same ids (validated against the known values in
`Murmur3HashFunctionTests.java`).
"""

from __future__ import annotations


def murmur3_x86_32(data: bytes, seed: int = 0) -> int:
    """32-bit MurmurHash3 (x86 variant), returns signed-style int in [0, 2^32)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    length = len(data)
    rounded = length & ~0x3
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = length & 0x3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def hash_routing(routing: str) -> int:
    """Murmur3HashFunction.hash(String): murmur3 over UTF-16LE char bytes,
    returned as a Java signed 32-bit int."""
    h = murmur3_x86_32(routing.encode("utf-16-le"))
    return h - (1 << 32) if h >= (1 << 31) else h


def shard_id_for(routing: str, num_shards: int, routing_partition_size: int = 1) -> int:
    """OperationRouting.generateShardId: murmur3(routing) floorMod num_shards."""
    return hash_routing(routing) % num_shards
