"""Seed-hosts providers: dynamic discovery of transport addresses.

Reference: `discovery.seed_providers` — `plugins/discovery-ec2`
(DescribeInstances over the EC2 Query API), `plugins/discovery-gce`
(instances list over the compute JSON API), and the built-in `file`
provider (`config/unicast_hosts.txt`, one host:port per line, reloaded
every resolution). Providers APPEND to any statically configured
`discovery.seed_hosts`; failures return an empty list and log — a cloud
API outage must never crash node boot (SeedHostsResolver swallows
per-provider errors the same way).

Settings:
  discovery.seed_providers: comma list of file | ec2 | gce
  discovery.ec2.endpoint:   EC2-compatible Query API endpoint
  discovery.ec2.tag.<k>:    instance tag filters (value may be a list)
  discovery.ec2.host_type:  private_ip (default) | public_ip
  discovery.gce.endpoint:   GCE-compatible API endpoint
  discovery.gce.project / discovery.gce.zone
  transport.default_port:   port appended to bare discovered IPs (9300)
"""

from __future__ import annotations

import logging
import os
import re
import urllib.parse
import urllib.request
from typing import Any, Dict, List

log = logging.getLogger("elasticsearch_tpu.discovery")

DEFAULT_TRANSPORT_PORT = 9300


def _with_port(host: str, settings: Dict[str, Any]) -> str:
    port = int(settings.get("transport.default_port",
                            DEFAULT_TRANSPORT_PORT))
    if host.startswith("["):
        # bracketed IPv6, with or without an explicit port
        return host if re.match(r"^\[.*\]:\d+$", host) else f"{host}:{port}"
    if host.count(":") >= 2:
        # bare IPv6: ':' membership would misread its separators as a port
        return f"[{host}]:{port}"
    if ":" in host:
        return host
    return f"{host}:{port}"


def _file_hosts(settings: Dict[str, Any], data_path: str) -> List[str]:
    """The built-in file provider: config/unicast_hosts.txt, re-read on
    every resolution so operators can edit it live (FileBasedSeedHostsProvider)."""
    path = str(settings.get(
        "discovery.file.path",
        os.path.join(data_path, "config", "unicast_hosts.txt")))
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(_with_port(line, settings))
    return out


def _ec2_hosts(settings: Dict[str, Any]) -> List[str]:
    """EC2 Query API DescribeInstances against a configurable endpoint
    (localstack / an in-process fixture / real EC2). Tag filters via
    `discovery.ec2.tag.<name>`; running instances only, like the
    reference's AwsEc2SeedHostsProvider."""
    endpoint = str(settings.get("discovery.ec2.endpoint", ""))
    if not endpoint:
        return []
    if not endpoint.startswith(("http://", "https://")):
        endpoint = "http://" + endpoint
    params = [("Action", "DescribeInstances"), ("Version", "2013-10-15"),
              ("Filter.1.Name", "instance-state-name"),
              ("Filter.1.Value.1", "running")]
    fidx = 2
    for key, value in sorted(settings.items()):
        if not str(key).startswith("discovery.ec2.tag."):
            continue
        tag = str(key)[len("discovery.ec2.tag."):]
        values = value if isinstance(value, (list, tuple)) else [value]
        params.append((f"Filter.{fidx}.Name", f"tag:{tag}"))
        for vi, v in enumerate(values, 1):
            params.append((f"Filter.{fidx}.Value.{vi}", str(v)))
        fidx += 1
    url = endpoint + "/?" + urllib.parse.urlencode(params)
    with urllib.request.urlopen(url, timeout=10) as resp:
        xml = resp.read().decode("utf-8", errors="replace")
    field = ("ipAddress"
             if settings.get("discovery.ec2.host_type") == "public_ip"
             else "privateIpAddress")
    hosts = re.findall(rf"<{field}>([^<]+)</{field}>", xml)
    return [_with_port(h, settings) for h in hosts]


def _gce_hosts(settings: Dict[str, Any]) -> List[str]:
    """GCE compute instances list (JSON) against a configurable endpoint
    (the reference's GceSeedHostsProvider reads networkInterfaces[0]
    .networkIP of RUNNING instances)."""
    import json
    endpoint = str(settings.get("discovery.gce.endpoint", ""))
    if not endpoint:
        return []
    if not endpoint.startswith(("http://", "https://")):
        endpoint = "http://" + endpoint
    project = str(settings.get("discovery.gce.project", "default"))
    zone = str(settings.get("discovery.gce.zone", "default"))
    url = (f"{endpoint}/compute/v1/projects/{project}/zones/{zone}"
           f"/instances")
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = json.loads(resp.read())
    hosts = []
    for item in body.get("items", []):
        if item.get("status") not in (None, "RUNNING"):
            continue
        nics = item.get("networkInterfaces") or []
        if nics and nics[0].get("networkIP"):
            hosts.append(nics[0]["networkIP"])
    return [_with_port(h, settings) for h in hosts]


def resolve_seed_hosts(settings: Dict[str, Any],
                       data_path: str = ".") -> List[str]:
    """All provider-discovered seed addresses for this node, deduplicated,
    order-preserving. Per-provider failures log and contribute nothing."""
    providers = settings.get("discovery.seed_providers", "")
    if isinstance(providers, str):
        providers = [p.strip() for p in providers.split(",") if p.strip()]
    out: List[str] = []
    for name in providers:
        try:
            if name == "file":
                out.extend(_file_hosts(settings, data_path))
            elif name == "ec2":
                out.extend(_ec2_hosts(settings))
            elif name == "gce":
                out.extend(_gce_hosts(settings))
            else:
                log.warning("unknown seed provider [%s]", name)
        except Exception:  # noqa: BLE001 — discovery outage ≠ boot failure
            log.warning("seed provider [%s] failed", name, exc_info=True)
    return list(dict.fromkeys(out))
