"""ClusterAwareNode: ONE feature surface for both deployment shapes.

The reference has a single execution path — every REST handler drives a
TransportAction, and a one-node cluster is just a cluster (`node/Node.java`
wires the same ActionModule either way). Round 1 here grew two worlds: the
full-featured single-node `Node` and a CRUD+search-only `ClusterNode`
(VERDICT "two worlds, one brain").

This class collapses them for the REST surface: it IS a `Node` (every
registered handler — templates, ingest pipelines, analyze, scripts, cat
APIs, xpack features — keeps working), but the DATA PATH overrides
delegate to the cluster layer:

- document writes/deletes route to the shard's primary and replicate
  (`ClusterNode.client_write`)
- GETs route to the primary (realtime)
- searches/counts/msearch run the distributed two-phase scatter-gather
  with streaming reduce and partial-agg merging (`client_search`)
- index create/delete/refresh and cluster settings go through the master

Registries (ingest pipelines, templates, stored scripts) replicate
through cluster state (`_wire_replicated_registries`), so a PUT on any
node is visible cluster-wide after publication.
"""

from __future__ import annotations

import functools
import threading
import time as _time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, IndexNotFoundError, SearchEngineError,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.telemetry import metrics as _telemetrics
from elasticsearch_tpu.telemetry import trace as _teletrace


def _parse_keepalive_s(value: Optional[str]) -> float:
    """'1m' / '30s' -> seconds (TimeValue parsing)."""
    if not value:
        return 300.0
    from elasticsearch_tpu.common.settings import parse_time_value
    return float(parse_time_value(str(value), "scroll"))


def _empty_search_response() -> dict:
    return {"took": 0, "timed_out": False,
            "_shards": {"total": 0, "successful": 0, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": 0, "relation": "eq"},
                     "max_score": None, "hits": []}}


class ClusterCallError(SearchEngineError):
    status = 503


class ClusterAwareNode(Node):
    def __init__(self, data_path: str, cluster_node, loop,
                 node_name: str = "node-0", cluster_name: str = "tpu-search",
                 settings: Optional[dict] = None):
        super().__init__(data_path, node_name=node_name,
                         cluster_name=cluster_name, settings=settings)
        self.cluster = cluster_node
        self.loop = loop
        # one identity: the REST layer, task manager, and cluster layer must
        # agree on this node's id (task ids embed it; fan-out responses key
        # on it)
        self.node_id = cluster_node.node_id
        self.tasks.node_id = cluster_node.node_id
        self._wire_replicated_registries()
        self._wire_persistent_features()
        self._wire_node_dispatch()
        self._wire_cluster_snapshots()
        self._wire_replicated_jobs()

    def _wire_persistent_features(self) -> None:
        """Background features run as cluster-assigned persistent tasks
        (PersistentTasksClusterService): the master picks exactly ONE node
        to tick ILM / SLM / watcher, with reassignment on node-leave —
        instead of every node ticking its own copy."""
        from elasticsearch_tpu.xpack.watcher import WatcherService

        def _bg(fn):
            # ticks fire on the event loop; the feature work itself (which
            # may write through the cluster and block on the loop) runs on
            # the generic pool — running it inline would deadlock
            def tick():
                try:
                    self.thread_pool.submit("generic", fn)
                except Exception:
                    pass
            return tick

        self.cluster.persistent_task_executors.update({
            "watcher": _bg(lambda: self.watcher.run_once()),
            "ilm": _bg(lambda: self.ilm.run_once()),
            "slm": _bg(lambda: self.slm.run_once()),
            "rollup": _bg(lambda: self.rollup.run_once()),
            "transform": _bg(lambda: self.transform.run_once()),
        })

        # watches replicate through cluster state like the other
        # registries, so the assigned executor node sees every watch
        watcher = self.watcher
        orig_put_watch = WatcherService.put_watch.__get__(watcher)
        orig_del_watch = WatcherService.delete_watch.__get__(watcher)
        node = self

        record = functools.partial(self._record_registry, "watches")

        def put_watch(watch_id, body, active=True):
            WatcherService.validate_watch(body)
            created = watch_id not in watcher.watches
            value = {"body": body, "active": active}
            node._call(node.cluster.client_put_registry,
                       "watches", watch_id, value)
            out = orig_put_watch(watch_id, body, active=active)
            record(watch_id, value)
            # the registry sync may have applied the watch an instant
            # before the local call: report created from the pre-call view
            out["created"] = created
            return out

        def delete_watch(watch_id):
            watcher.get_watch(watch_id)  # 404 before cluster traffic
            node._call(node.cluster.client_put_registry,
                       "watches", watch_id, None)
            try:
                orig_del_watch(watch_id)
            except Exception:
                pass  # the registry sync may have removed it already
            record(watch_id, None)

        watcher.put_watch = put_watch
        watcher.delete_watch = delete_watch
        self._registry_originals["watch"] = \
            lambda key, value: orig_put_watch(
                key, value["body"], active=value.get("active", True))
        self._registry_originals["del_watch"] = orig_del_watch
        self._registry_sections = getattr(self, "_registry_sections", ()) + (
            ("watches", self._registry_originals["watch"],
             self._registry_originals["del_watch"]),)

    def _wire_replicated_jobs(self) -> None:
        """Rollup jobs and transforms replicate like watches: the config
        AND run-state travel through cluster state, so whichever node holds
        the persistent task (incl. after an owner dies) ticks them
        (RollupJobTask / TransformTask as persistent tasks)."""
        node = self

        def _wrap(service, section, put_name, start_name, stop_name,
                  del_name, state_key, jobs_attr):
            orig_put = getattr(service, put_name)
            orig_start = getattr(service, start_name)
            orig_stop = getattr(service, stop_name)
            orig_del = getattr(service, del_name)
            configs = getattr(service, jobs_attr)

            def current_value(job_id):
                run = service.state.get(job_id, {}).get(state_key, "stopped")
                return {"config": configs.get(job_id), "run_state": run}

            def replicate(job_id, value):
                node._call(node.cluster.client_put_registry,
                           section, job_id, value)
                node._record_registry(section, job_id, value)

            def rput(job_id, body):
                had = job_id in configs
                orig_put(job_id, body)  # validate + apply locally
                try:
                    replicate(job_id, current_value(job_id))
                except Exception:
                    # failed publish must not leave this node diverged
                    if not had:
                        configs.pop(job_id, None)
                        service.state.pop(job_id, None)
                    raise

            def rstart(job_id):
                out = orig_start(job_id)
                # replicate the POST-call state (a batch transform may have
                # already completed and flipped back to stopped)
                replicate(job_id, current_value(job_id))
                return out

            def rstop(job_id):
                out = orig_stop(job_id)
                replicate(job_id, current_value(job_id))
                return out

            def rdel(job_id):
                if job_id not in configs:
                    orig_del(job_id)  # surface the native 404
                    return
                saved_cfg = configs.get(job_id)
                saved_state = dict(service.state.get(job_id) or {})
                orig_del(job_id)
                try:
                    replicate(job_id, None)
                except Exception:
                    configs[job_id] = saved_cfg
                    service.state[job_id] = saved_state
                    raise

            def sync_put(key, value):
                cfg = (value or {}).get("config")
                if cfg is None:
                    return
                try:
                    orig_put(key, cfg)
                except Exception:
                    pass  # already known locally: just apply run state
                if key in service.state:
                    service.state[key][state_key] = \
                        (value or {}).get("run_state", "stopped")

            def sync_del(key):
                try:
                    orig_del(key)
                except Exception:
                    pass

            setattr(service, put_name, rput)
            setattr(service, start_name, rstart)
            setattr(service, stop_name, rstop)
            setattr(service, del_name, rdel)
            self._registry_sections = getattr(
                self, "_registry_sections", ()) + (
                (section, sync_put, sync_del),)

        _wrap(self.rollup, "rollup_jobs", "put_job", "start_job",
              "stop_job", "delete_job", "job_state", "jobs")
        _wrap(self.transform, "transforms", "put", "start", "stop",
              "delete", "state", "transforms")

    def register_builtin_persistent_tasks(self) -> None:
        """Called once post-boot: idempotent registrations (the master's
        task-update no-ops when the id exists)."""
        for tid, interval in (("watcher", 1000), ("ilm", 30_000),
                              ("slm", 60_000), ("rollup", 2000),
                              ("transform", 2000)):
            self.cluster.client_register_persistent_task(
                tid, interval_ms=interval, on_done=lambda r: None,
                on_failure=lambda e: None)

    # --------------------------------------------------- replicated registries
    def _wire_node_dispatch(self) -> None:
        """Register this node's local collectors for the generic routed
        action layer (TransportNodesAction analog): every node serves the
        same named ops; the *_api overrides below fan them out and merge,
        so `_nodes/stats` on node B reflects node A."""
        c = self.cluster

        def _cancel(p):
            t = self.tasks.cancel(p["task_id"])
            return {self.cluster.node_id: {
                "tasks": {t.task_id: t.to_dict(self.cluster.node_id)}}}

        def _stats(p):
            st = {**self.local_node_stats(
                p.get("level"), bool(p.get("include_segment_file_sizes"))),
                "fanout": self.cluster.fanout_stats.snapshot()}
            # block-level recovery progress (peer recovery, relocation,
            # restore) replaces the single-node stub: live targets,
            # sources serving phase 1, reused/shipped blocks, backoff
            # throttle time and retry/giveup counters
            st.setdefault("indices", {})["recovery"] = c.recovery_summary()
            return st

        c.node_collectors.update({
            "info": lambda p: self.local_node_info(),
            # the cross-node serving path's counters ride the stats
            # section: coordinator-side per-phase fan-out tallies +
            # data-plane remote deadline sheds (serving/fanout.py)
            "stats": _stats,
            "hot_threads": lambda p: self.local_hot_threads(
                float(p.get("interval_s", 0.05)),
                top_n=int(p.get("top_n", 3))),
            "traces": lambda p: self.local_traces_section(
                int(p.get("limit", 50))),
            "tasks": lambda p: self.local_tasks_section(p.get("actions")),
            "task_get": lambda p: {
                "completed": False,
                "task": self.tasks.get(p["task_id"]).to_dict(
                    self.cluster.node_id)},
            "task_cancel": _cancel,
            "cat_thread_pool": lambda p: self.local_cat_threadpool_rows(
                p.get("pool_filter")),
            "cat_nodeattrs": lambda p: self.local_cat_nodeattrs_rows(),
            "cat_fielddata": lambda p: self.local_cat_fielddata_rows(
                p.get("field_filter")),
            "cat_tasks": lambda p: self.local_cat_tasks_rows(),
        })
        c.dispatch_executor = functools.partial(
            self.thread_pool.submit, "generic")

    def _fanout(self, op: str, params: Optional[dict] = None,
                timeout: float = 20.0) -> dict:
        return self._call(self.cluster.fanout_nodes, op, params,
                          timeout=timeout)

    def nodes_info_api(self) -> dict:
        out = self._fanout("info")
        return self._nodes_envelope(out["results"],
                                    failed=len(out["failures"]))

    def nodes_stats_api(self, level: str = None,
                        include_segment_file_sizes: bool = False) -> dict:
        out = self._fanout("stats", {
            "level": level,
            "include_segment_file_sizes": include_segment_file_sizes})
        return self._nodes_envelope(out["results"],
                                    failed=len(out["failures"]))

    def hot_threads_api(self, interval_s: float = 0.05,
                        top_n: int = 3) -> str:
        out = self._fanout("hot_threads", {"interval_s": interval_s,
                                           "top_n": top_n})
        return "\n".join(out["results"][nid]
                          for nid in sorted(out["results"]))

    def traces_api(self, limit: int = 50) -> dict:
        """Cluster `GET _nodes/traces`: every node's completed-trace
        ring, merged under the standard `_nodes` envelope — a cross-node
        search shows its coordinator trace on the coordinating node and
        its shard segments on each data node, joined by trace_id."""
        out = self._fanout("traces", {"limit": limit})
        return self._nodes_envelope(out["results"],
                                    failed=len(out["failures"]))

    def tasks_list_api(self, actions=None) -> dict:
        out = self._fanout("tasks", {"actions": actions})
        resp = {"nodes": out["results"]}
        if out["failures"]:
            resp["node_failures"] = [
                {"type": f.get("type", "failed_node_exception"),
                 "reason": f.get("reason", str(f)), "node_id": nid}
                for nid, f in sorted(out["failures"].items())]
        return resp

    def _task_owner(self, task_id: str) -> str:
        owner = str(task_id).rsplit(":", 1)[0]
        if owner not in self.cluster.cluster_state.nodes:
            from elasticsearch_tpu.common.errors import ResourceNotFoundError
            raise ResourceNotFoundError(f"task [{task_id}] isn't running and "
                                        "hasn't stored its results")
        return owner

    def task_get_api(self, task_id: str) -> dict:
        return self._call(self.cluster.dispatch_to_node,
                          self._task_owner(task_id), "task_get",
                          {"task_id": task_id}, timeout=20.0)

    def task_cancel_api(self, task_id: str) -> dict:
        nodes = self._call(self.cluster.dispatch_to_node,
                           self._task_owner(task_id), "task_cancel",
                           {"task_id": task_id}, timeout=20.0)
        return {"nodes": nodes}

    def _wire_cluster_snapshots(self) -> None:
        """Route snapshot/restore through the cluster-state lifecycle
        (cluster/snapshots.py): repositories replicate like the other
        registries; create/restore become master state updates; this node
        contributes the data-plane hooks (blob IO, shard access)."""
        import os
        import time as _time

        from elasticsearch_tpu.cluster.snapshots import (
            RESTORE_IN_PROGRESS, SNAPSHOTS_IN_PROGRESS)
        from elasticsearch_tpu.common.errors import (
            ResourceAlreadyExistsError, ResourceNotFoundError)

        svc = self.snapshots
        lifecycle = self.cluster.snapshot_lifecycle
        orig_put_repo = svc.put_repository
        orig_del_repo = svc.delete_repository
        orig_get = svc.get_snapshots

        # ---- data-plane hooks -------------------------------------------
        lifecycle.repo_factory = svc.get_repository
        # generic pool, NOT the snapshot pool: the REST create handler
        # blocks a snapshot-pool thread polling for completion, and
        # upload jobs queued behind it would deadlock the lifecycle
        lifecycle.executor = functools.partial(
            self.thread_pool.submit, "generic")

        def shard_uploader(repo_name, index, shard_id):
            from elasticsearch_tpu.recovery.snapshot import snapshot_shard
            repo = svc.get_repository(repo_name)
            shard = self.cluster.local_shards.get((index, shard_id))
            if shard is None:
                raise ResourceNotFoundError(
                    f"shard [{index}][{shard_id}] is not allocated here")
            # block-level snapshot (recovery/snapshot.py): sealed
            # segments, cached columnar blocks, the ledger and trained
            # IVF layouts as content-addressed blobs — only blocks the
            # repository has never seen upload
            # active_vector_store(): a text-only shard must not
            # materialize its lazy device store just to snapshot nothing
            return snapshot_shard(
                repo, shard.engine, shard.active_vector_store(),
                settings=self.cluster.cluster_state.settings)

        lifecycle.shard_uploader = shard_uploader

        def shard_restore_hook(restore, index, shard_id, path):
            from elasticsearch_tpu.recovery.snapshot import restore_shard
            repo = svc.get_repository(restore["repo"])
            entry = restore["shards"].get(str(shard_id)) or {}
            if "blocks" in entry:
                # digest-verified reassembly; fetched blobs also land in
                # the node block cache, so a later peer recovery of the
                # same data re-ships nothing
                restore_shard(repo, entry, path,
                              cache=self.cluster.block_cache)
                return
            for fname, digest in (entry.get("files") or {}).items():
                repo.get_blob(digest, os.path.join(path, fname))

        self.cluster.shard_restore_hook = shard_restore_hook

        # ---- repositories replicate through cluster state ---------------
        def put_repository(name, body, verify=True):
            had = name in svc.repositories
            orig_put_repo(name, body, verify=verify)  # validate locally first
            try:
                self._call(self.cluster.client_put_registry,
                           "repositories", name, body)
            except Exception:
                # failed publish must not leave this node diverged: undo the
                # local registration before surfacing the error
                if not had:
                    svc.repositories.pop(name, None)
                raise
            self._record_registry("repositories", name, body)

        def delete_repository(name):
            svc.get_repository(name)  # 404 before cluster traffic
            self._call(self.cluster.client_put_registry,
                       "repositories", name, None)
            try:
                orig_del_repo(name)
            except Exception:
                pass
            self._record_registry("repositories", name, None)

        svc.put_repository = put_repository
        svc.delete_repository = delete_repository
        self._registry_originals["repository"] =             lambda key, value: orig_put_repo(key, value, verify=False)
        self._registry_originals["del_repository"] = orig_del_repo
        self._registry_sections = getattr(self, "_registry_sections", ()) + (
            ("repositories", self._registry_originals["repository"],
             self._registry_originals["del_repository"]),)

        # ---- snapshot create / get / restore through the lifecycle ------
        def create_snapshot(repo_name, snapshot, body=None):
            repo = svc.get_repository(repo_name)
            if snapshot in repo.list_snapshots():
                raise ResourceAlreadyExistsError(
                    f"snapshot with the same name [{snapshot}] "
                    "already exists")
            body = body or {}
            expr = body.get("indices", "_all")
            if isinstance(expr, list):
                expr = ",".join(expr)
            self._call(lifecycle.client_create, repo_name, snapshot, expr)
            deadline = _time.monotonic() + 60
            while _time.monotonic() < deadline:
                try:
                    m = repo.get_manifest(snapshot)
                    return {"snapshot": {
                        "snapshot": snapshot, "state": m["state"],
                        "indices": sorted(m.get("indices", {})),
                        "shards": m.get("shards", {})}}
                except ResourceNotFoundError:
                    _time.sleep(0.1)
            raise ClusterCallError(
                f"snapshot [{snapshot}] did not complete in time")

        def get_snapshots(repo_name, expr="_all"):
            out = orig_get(repo_name, expr)
            from elasticsearch_tpu.common.patterns import (
                matches_csv_patterns)
            sips = self.cluster.cluster_state.metadata.get(
                SNAPSHOTS_IN_PROGRESS) or {}
            listed = {s["snapshot"] for s in out["snapshots"]}
            for entry in sips.values():
                name = entry["snapshot"]
                if entry["repo"] != repo_name or name in listed:
                    continue
                if not matches_csv_patterns(name, expr):
                    continue
                out["snapshots"].append({
                    "snapshot": name, "state": "IN_PROGRESS",
                    "indices": sorted(entry.get("indices", {})),
                    "start_time_in_millis": entry["start_ms"],
                    "end_time_in_millis": None})
            return out

        def restore_snapshot(repo_name, snapshot, body=None):
            import re as _re
            repo = svc.get_repository(repo_name)
            manifest = repo.get_manifest(snapshot)
            body = body or {}
            indices_expr = body.get("indices", "_all")
            rename_pattern = body.get("rename_pattern")
            rename_replacement = body.get("rename_replacement", "")
            targets = {}
            from elasticsearch_tpu.common.patterns import (
                matches_csv_patterns)
            for index_name, entry in manifest["indices"].items():
                if not matches_csv_patterns(index_name, indices_expr):
                    continue
                target = index_name
                if rename_pattern:
                    target = _re.sub(rename_pattern, rename_replacement,
                                     index_name)
                # existence is validated by the MASTER against its current
                # state — this node's applied state may lag a just-committed
                # delete, and a stale local check would reject a valid
                # restore
                targets[target] = entry
            if not targets:
                raise ResourceNotFoundError(
                    f"no indices in snapshot [{snapshot}] match the restore "
                    f"expression [{indices_expr}]")
            self._call(lifecycle.client_restore, repo_name, snapshot,
                       targets)
            # wait for every restored primary to come up (the shaped
            # response reports shard counts, like the single-node path)
            deadline = _time.monotonic() + 60
            done = False
            prim = []
            while _time.monotonic() < deadline:
                state = self.cluster.cluster_state
                prim = [r for r in state.routing
                        if r.index in targets and r.primary]
                if prim and all(r.state == "STARTED" for r in prim) \
                        and not (state.metadata.get(RESTORE_IN_PROGRESS)
                                 or {}).keys() & targets.keys():
                    done = True
                    break
                _time.sleep(0.1)
            if not done:
                started = sum(1 for r in prim if r.state == "STARTED")
                raise ClusterCallError(
                    f"restore of [{snapshot}] did not complete in time "
                    f"({started}/{len(prim)} primaries started)")
            return {"snapshot": {"snapshot": snapshot,
                                 "indices": sorted(targets),
                                 "shards": {"total": len(prim), "failed": 0,
                                            "successful": len(prim)}}}

        svc.create_snapshot = create_snapshot
        svc.get_snapshots = get_snapshots
        svc.restore_snapshot = restore_snapshot

    def _cat_fanout(self, op: str, params: Optional[dict] = None) -> list:
        out = self._fanout(op, params)
        rows: List[Any] = []
        for nid in sorted(out["results"]):
            rows.extend(out["results"][nid] or [])
        return rows

    def cat_threadpool_rows_api(self, pool_filter=None) -> list:
        return self._cat_fanout("cat_thread_pool",
                                {"pool_filter": pool_filter})

    def cat_nodeattrs_rows_api(self) -> list:
        return self._cat_fanout("cat_nodeattrs")

    def cat_fielddata_rows_api(self, field_filter=None) -> list:
        return self._cat_fanout("cat_fielddata",
                                {"field_filter": field_filter})

    def cat_tasks_rows_api(self) -> list:
        return self._cat_fanout("cat_tasks")

    def _wire_replicated_registries(self) -> None:
        """Ingest pipelines, index templates, and stored scripts live in the
        cluster state (IngestMetadata / IndexTemplateMetaData / ScriptMetaData
        analogs): every mutation publishes through the master, every applied
        state syncs the local registries — a pipeline PUT on one node is
        immediately usable on every node."""
        from elasticsearch_tpu.ingest.service import IngestService
        from elasticsearch_tpu.node_admin import TemplateService
        from elasticsearch_tpu.script.service import ScriptService

        node = self

        def replicate(section, key, value):
            node._call(node.cluster.client_put_registry, section, key, value)

        ingest, templates, scripts = self.ingest, self.templates, self.scripts
        # originals come from the CLASS, never from the instance: the script
        # registry is a process-wide singleton, so instance attributes may
        # hold a previous node's wrappers — rebinding from the class keeps
        # wiring idempotent (latest node wins) with no wrapper chains
        orig_put_pipeline = IngestService.put_pipeline.__get__(ingest)
        orig_del_pipeline = IngestService.delete_pipeline.__get__(ingest)
        orig_put_template = TemplateService.put.__get__(templates)
        orig_del_template = TemplateService.delete.__get__(templates)
        orig_put_script = ScriptService.put_stored.__get__(scripts)
        orig_del_script = ScriptService.delete_stored.__get__(scripts)

        record = self._record_registry

        # order: VALIDATE locally, REPLICATE (raises on failure — nothing
        # applied anywhere), then apply locally and record ownership; a
        # failed publish can therefore never leave this node diverged
        def put_pipeline(pid, definition):
            from elasticsearch_tpu.ingest.service import Pipeline
            Pipeline(pid, definition)  # validation only
            replicate("pipelines", pid, definition)
            orig_put_pipeline(pid, definition)
            record("pipelines", pid, definition)

        def delete_pipeline(pid):
            self.ingest.get_pipeline(pid)  # 404 before any cluster traffic
            replicate("pipelines", pid, None)
            orig_del_pipeline(pid)
            record("pipelines", pid, None)

        def put_template(name, body, composable=False):
            if not body.get("index_patterns"):
                raise IllegalArgumentError(
                    "index template must define index_patterns")
            key = f"{'c' if composable else 'l'}:{name}"
            replicate("templates", key, body)
            orig_put_template(name, body, composable=composable)
            record("templates", key, body)

        def delete_template(name, composable=False):
            self.templates.get(name, composable=composable)
            key = f"{'c' if composable else 'l'}:{name}"
            replicate("templates", key, None)
            orig_del_template(name, composable=composable)
            record("templates", key, None)

        def put_stored(sid, body):
            from elasticsearch_tpu.common.errors import ParsingError
            spec = body.get("script")
            if not isinstance(spec, dict) or "source" not in spec:
                raise ParsingError("stored script must define [script.source]")
            replicate("scripts", sid, body)
            orig_put_script(sid, body)
            record("scripts", sid, body)

        def delete_stored(sid):
            self.scripts.get_stored(sid)
            replicate("scripts", sid, None)
            orig_del_script(sid)
            record("scripts", sid, None)

        ingest.put_pipeline = put_pipeline
        ingest.delete_pipeline = delete_pipeline
        templates.put = put_template
        templates.delete = delete_template
        scripts.put_stored = put_stored
        scripts.delete_stored = delete_stored
        self._applied_registries = {}
        self._registry_originals = {
            "pipeline": orig_put_pipeline, "template": orig_put_template,
            "script": orig_put_script, "del_pipeline": orig_del_pipeline,
            "del_template": orig_del_template, "del_script": orig_del_script}
        self.cluster.state_listeners.append(self._sync_registries)

    def _record_registry(self, section, key, value) -> None:
        """Track what this node applied locally (the sync's diff base)."""
        regs = self._applied_registries.setdefault(section, {})
        if value is None:
            regs.pop(key, None)
        else:
            regs[key] = value

    def _sync_registries(self, state) -> None:
        """Reconcile local registries to the cluster-state truth: apply
        adds AND updates (compared against what this node last applied),
        remove entries gone from the state."""
        from elasticsearch_tpu.cluster.cluster_node import REGISTRIES_KEY
        regs = state.metadata.get(REGISTRIES_KEY) or {}
        applied = getattr(self, "_applied_registries", None)
        if applied is None:
            applied = self._applied_registries = {}

        def put_template(key, body):
            self._registry_originals["template"](
                key[2:], body, composable=key.startswith("c:"))

        def del_template(key):
            self._registry_originals["del_template"](
                key[2:], composable=key.startswith("c:"))

        sections = (
            ("pipelines", self._registry_originals["pipeline"],
             self._registry_originals["del_pipeline"]),
            ("templates", put_template, del_template),
            ("scripts", self._registry_originals["script"],
             self._registry_originals["del_script"]),
        ) + tuple(getattr(self, "_registry_sections", ()))
        for section, put_fn, del_fn in sections:
            want = regs.get(section) or {}
            have = applied.setdefault(section, {})
            for key, value in want.items():
                if have.get(key) != value:  # new OR changed definition
                    try:
                        put_fn(key, value)
                        have[key] = value
                    except Exception:
                        pass  # a bad remote definition must not kill apply
            for key in list(have):
                if key not in want:
                    try:
                        del_fn(key)
                    except Exception:
                        pass
                    have.pop(key, None)

    # ------------------------------------------------------------- plumbing
    def _call(self, fn, *args, timeout: float = 30.0, **kwargs) -> Any:
        """Run a callback-style cluster client method from a worker thread:
        schedule it on the node's event loop, block for the result."""
        done = threading.Event()
        box: Dict[str, Any] = {}

        def on_done(result):
            box["r"] = result
            done.set()

        def on_failure(err):
            box["e"] = err
            done.set()

        def invoke():
            try:
                kw = dict(kwargs)
                if "on_failure" in fn.__code__.co_varnames:
                    kw["on_failure"] = on_failure
                fn(*args, on_done=on_done, **kw)
            except Exception as e:  # defensive: surface instead of hanging
                on_failure(e)

        self.loop.call_soon_threadsafe(invoke)
        if not done.wait(timeout):
            raise ClusterCallError("timed out waiting for the cluster")
        if "e" in box:
            err = box["e"]
            raise err if isinstance(err, SearchEngineError) \
                else ClusterCallError(str(err))
        result = box["r"]
        if isinstance(result, dict) and result.get("error") is not None:
            err = result["error"]
            reason = err.get("reason", str(err)) if isinstance(err, dict) else str(err)
            if isinstance(err, dict) and err.get("type") == "index_not_found_exception":
                raise IndexNotFoundError(reason)
            if isinstance(err, dict) \
                    and err.get("type") == "search_context_missing_exception":
                from elasticsearch_tpu.common.errors import (
                    SearchContextMissingError)
                raise SearchContextMissingError(reason)
            raise SearchEngineError(reason)
        return result

    def _write_with_retry(self, index: str, op: dict,
                          timeout_s: float = 30.0,
                          retry_not_found: bool = False) -> dict:
        """Writes wait for an active primary (TransportReplicationAction's
        wait_for_active_shards / cluster-state observer retry): right after
        auto-create or failover the routing may not show a started primary
        yet. IndexNotFound retries ONLY when the caller just auto-created
        (this node's applier may lag the master's commit); a genuinely
        missing index stays a fast 404."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        nf_deadline = _time.monotonic() + min(timeout_s, 10.0)
        while True:
            try:
                return self._call(self.cluster.client_write, index, op)
            except IndexNotFoundError:
                if retry_not_found and _time.monotonic() < nf_deadline:
                    _time.sleep(0.2)
                    continue
                raise
            except SearchEngineError as e:
                if "no active primary" in str(e) \
                        and _time.monotonic() < deadline:
                    _time.sleep(0.2)
                    continue
                raise

    def _meta(self, index: str) -> dict:
        meta = self.cluster.cluster_state.metadata.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        return meta

    # ------------------------------------------------------------ documents
    def index_doc(self, index: str, doc_id: Optional[str], body: dict,
                  op_type: str = "index", refresh: Optional[str] = None,
                  routing: Optional[str] = None,
                  if_seq_no: Optional[int] = None,
                  if_primary_term: Optional[int] = None,
                  version: Optional[int] = None,
                  version_type: str = "internal",
                  pipeline: Optional[str] = None) -> dict:
        import uuid as _uuid
        auto_created = False
        state = self.cluster.cluster_state  # ONE snapshot for this request
        if index not in state.metadata:
            # auto-create FIRST (with matching templates), so a template-
            # provided index.default_pipeline applies to the first doc too
            resolved = self.templates.resolve(index)
            self._call(self.cluster.client_create_index, index,
                       resolved["settings"] or None,
                       resolved["mappings"]
                       if resolved["mappings"].get("properties") else None)
            auto_created = True
            if pipeline is None:
                pipeline = (resolved["settings"] or {}).get(
                    "index.default_pipeline")
        elif pipeline is None:
            # index.default_pipeline lives in the cluster metadata here
            meta = state.metadata.get(index)
            if meta is not None:
                pipeline = (meta.get("settings") or {}).get(
                    "index.default_pipeline")
        if pipeline and pipeline != "_none":
            body = self.ingest.execute(pipeline, index, doc_id, body)
            if body is None:
                return {"_index": index, "_id": doc_id, "result": "noop",
                        "_version": -1, "_seq_no": -1, "_primary_term": 0,
                        "_shards": {"total": 0, "successful": 0, "failed": 0}}
        if doc_id is None:
            doc_id = _uuid.uuid4().hex[:20]
            op_type = "create"
        op = {"type": "index", "id": str(doc_id), "source": body,
              "op_type": op_type, "routing": routing,
              "if_seq_no": if_seq_no, "if_primary_term": if_primary_term,
              "version": version, "version_type": version_type}
        resp = self._write_with_retry(index, op,
                                      retry_not_found=auto_created)
        out = {"_index": index, "_id": resp.get("_id", doc_id),
               "_version": resp.get("_version"),
               "result": resp.get("result", "created"),
               "_seq_no": resp.get("_seq_no"),
               "_primary_term": resp.get("_primary_term"),
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        self._maybe_cluster_refresh(index, refresh)
        if refresh in ("true", "", True):
            out["forced_refresh"] = True
        return out

    def delete_doc(self, index: str, doc_id: str, refresh: Optional[str] = None,
                   routing: Optional[str] = None,
                   if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None,
                   version: Optional[int] = None,
                   version_type: str = "internal") -> dict:
        self._meta(index)
        op = {"type": "delete", "id": str(doc_id), "routing": routing,
              "if_seq_no": if_seq_no, "if_primary_term": if_primary_term,
              "version": version, "version_type": version_type}
        resp = self._write_with_retry(index, op)
        self._maybe_cluster_refresh(index, refresh)
        out = {"_index": index, "_id": doc_id,
               "_version": resp.get("_version"), "result": "deleted",
               "_seq_no": resp.get("_seq_no"),
               "_primary_term": resp.get("_primary_term"),
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if refresh in ("true", "", True):
            out["forced_refresh"] = True
        return out

    def get_doc(self, index: str, doc_id: str, routing: Optional[str] = None,
                source_includes=None, realtime: bool = True) -> dict:
        self._meta(index)
        return self._call(self.cluster.client_get, index, str(doc_id),
                          routing=routing)

    def update_doc(self, index: str, doc_id: str, body: dict,
                   refresh: Optional[str] = None,
                   routing: Optional[str] = None,
                   if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None,
                   source_filter=None) -> dict:
        import copy as _copy

        from elasticsearch_tpu.common.errors import DocumentMissingError
        from elasticsearch_tpu.node import _apply_update_script, _deep_merge
        self._validate_update_body(body)
        if source_filter is None and body and "_source" in body:
            source_filter = body["_source"]

        def _with_get(out, src):
            if source_filter is not None and source_filter is not False:
                doc = {"_source": _copy.deepcopy(src)}
                self._apply_mget_projection(doc, {}, None, index,
                                            source_filter)
                out["get"] = {"_source": doc.get("_source", {}),
                              "found": True}
            return out

        existing = self.get_doc(index, doc_id, routing=routing)
        if not existing.get("found"):
            if "upsert" in body:
                return _with_get(
                    self.index_doc(index, doc_id, body["upsert"],
                                   refresh=refresh, routing=routing),
                    body["upsert"])
            if body.get("doc_as_upsert") and "doc" in body:
                return _with_get(
                    self.index_doc(index, doc_id, body["doc"],
                                   refresh=refresh, routing=routing),
                    body["doc"])
            raise DocumentMissingError(f"[{doc_id}]: document missing")
        if if_seq_no is not None and existing["_seq_no"] != if_seq_no or \
                if_primary_term is not None \
                and existing.get("_primary_term") != if_primary_term:
            from elasticsearch_tpu.common.errors import VersionConflictError
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, required seqNo "
                f"[{if_seq_no}], primary term [{if_primary_term}]")
        source = _copy.deepcopy(existing["_source"])
        if "doc" in body:
            _deep_merge(source, body["doc"])
            if body.get("detect_noop", True) \
                    and source == existing["_source"]:
                return _with_get({
                    "_index": index, "_id": doc_id,
                    "_version": existing["_version"], "result": "noop",
                    "_seq_no": existing["_seq_no"],
                    "_primary_term": existing.get("_primary_term", 1),
                    "_shards": {"total": 0, "successful": 0,
                                "failed": 0}}, source)
        elif "script" in body:
            verdict: Dict[str, Any] = {}
            source = _apply_update_script(source, body["script"],
                                          ctx_extra=verdict)
            op = verdict.get("op", "index")
            if op == "none":
                return _with_get({
                    "_index": index, "_id": doc_id,
                    "_version": existing["_version"], "result": "noop",
                    "_seq_no": existing["_seq_no"],
                    "_primary_term": existing.get("_primary_term", 1),
                    "_shards": {"total": 0, "successful": 0,
                                "failed": 0}}, source)
            if op == "delete":
                out = self.delete_doc(index, doc_id, refresh=refresh,
                                      routing=routing)
                out["result"] = "deleted"
                return out
        else:
            raise IllegalArgumentError("update requires [doc] or [script]")
        out = self.index_doc(index, doc_id, source, refresh=refresh,
                             routing=routing,
                             if_seq_no=existing["_seq_no"],
                             if_primary_term=existing.get("_primary_term"))
        out["result"] = "updated"
        return _with_get(out, source)

    # --------------------------------------------------------------- search
    def search(self, index_expr: Optional[str], body: Optional[dict],
               ignore_throttled: bool = True,
               ignore_unavailable: bool = False,
               allow_no_indices: bool = True,
               expand_wildcards: Optional[str] = None) -> dict:
        if index_expr and ":" in index_expr:
            # cross-cluster search from a clustered coordinator: split
            # `alias:index` parts, one wire request per remote cluster,
            # local part through the distributed scatter below
            # (TransportSearchAction + SearchResponseMerger)
            from elasticsearch_tpu.xpack.ccr import merge_ccs_responses
            local_expr, remote_exprs = self.remotes.split_indices(index_expr)
            remote_resps, clusters = self.remotes.search_remotes(
                remote_exprs, dict(body or {}))
            local_resp = self.search(
                local_expr, body, ignore_throttled=ignore_throttled,
                ignore_unavailable=ignore_unavailable,
                allow_no_indices=allow_no_indices,
                expand_wildcards=expand_wildcards) if local_expr else None
            return merge_ccs_responses(local_resp, remote_resps, body,
                                       clusters)
        if not allow_no_indices and index_expr and "*" in index_expr:
            # IndicesOptions.allowNoIndices=false: an unmatched wildcard is
            # an error at the coordinator, before the scatter
            if not self.cluster.resolve_indices(index_expr):
                raise IndexNotFoundError(index_expr)
        if expand_wildcards and {"closed", "all"} & set(
                str(expand_wildcards).split(",")):
            # closed indices surface through the LOCAL view (cluster
            # metadata doesn't carry index state; closing is node-local)
            for svc in self.indices.resolve(index_expr, expand_closed=True):
                self.indices.check_open(svc)
        if ignore_unavailable and index_expr:
            # lenientExpandOpen: drop concrete names absent from cluster
            # metadata before the scatter
            meta = self.cluster.cluster_state.metadata
            kept = [p.strip() for p in index_expr.split(",")
                    if "*" in p or p.strip() in ("_all", "")
                    or p.strip() in meta]
            if not kept:
                return _empty_search_response()
            index_expr = ",".join(kept)
        t0 = _time.perf_counter()
        # hand the REST thread's telemetry context (trace + task) to the
        # coordinator explicitly: client_search runs on the event loop,
        # where thread-locals cannot follow the request
        resp = self._call(self.cluster.client_search, index_expr,
                          dict(body or {}),
                          telemetry_ctx=_teletrace.capture())
        self.counters["search"] += 1
        took_s = _time.perf_counter() - t0
        _telemetrics.record("search.took", int(took_s * 1e9))
        # the coordinator ships the phase summary on a private key so
        # the slow log gets it on UNPROFILED requests too; pop it before
        # the response reaches the client
        phases = resp.pop("_took_phases", None) \
            if isinstance(resp, dict) else None
        # coordinator slow log: the fan-out path must breach per-index
        # thresholds exactly like the single-node query path; entries
        # carry the fan-out phase summary instead of shard-local nanos
        if isinstance(resp, dict) and "error" not in resp:
            meta = self.cluster.cluster_state.metadata
            # cheap gate: the common case configures no slow-log
            # thresholds anywhere — skip the second index resolution
            # entirely then (the coordinator already resolved once)
            if any(isinstance(m, dict) and any(
                    ".slowlog.threshold." in key
                    for key in (m.get("settings") or {}))
                   for m in meta.values()):
                _task = _teletrace.current_task()
                try:
                    names = self.cluster.resolve_indices(index_expr)
                except Exception:
                    names = []
                for name in names:
                    settings = (meta.get(name) or {}).get("settings") or {}
                    self.search_slow_log.maybe_log(
                        settings, name, took_s,
                        source=(body or {}).get("query"),
                        opaque_id=getattr(_task, "opaque_id", None),
                        trace=_teletrace.current_trace(),
                        phases=phases)
        return resp

    def count(self, index_expr: Optional[str], body: Optional[dict]) -> dict:
        body = dict(body or {})
        body["size"] = 0
        body.pop("sort", None)
        body["track_total_hits"] = True
        resp = self.search(index_expr, body)
        return {"count": resp["hits"]["total"]["value"],
                "_shards": resp.get("_shards",
                                    {"total": 1, "successful": 1,
                                     "skipped": 0, "failed": 0})}

    # ----------------------------------------------------------------- scroll

    def search_scroll_start(self, index_expr: Optional[str],
                            body: Optional[dict], keep_alive: str = "1m",
                            ignore_throttled: bool = True) -> dict:
        """Cluster scroll with REAL per-shard pinned reader contexts
        (reference: SearchService scroll contexts +
        SearchScrollAsyncAction): each shard holds its own sorted
        snapshot under a keepalive; the coordinator keeps per-shard
        cursors and merge-sorts windows per page, so a scroll over
        millions of docs never materializes more than a page per shard."""
        body = dict(body or {})
        if body.get("collapse") is not None:
            raise IllegalArgumentError(
                "cannot use `collapse` in a scroll context")
        return self._call(self.cluster.client_scroll_start, index_expr,
                          body, _parse_keepalive_s(keep_alive))

    def search_scroll_next(self, scroll_id: str,
                           keep_alive: Optional[str] = None) -> dict:
        return self._call(self.cluster.client_scroll_next, scroll_id,
                          _parse_keepalive_s(keep_alive)
                          if keep_alive else None)

    def clear_scroll(self, scroll_id: str) -> dict:
        return self._call(self.cluster.client_scroll_clear, scroll_id)

    def clear_all_scrolls(self) -> dict:
        return self._call(self.cluster.client_scroll_clear_all)

    def pending_cluster_tasks(self) -> list:
        return self.cluster.coordinator.pending_tasks()

    # ------------------------------------------------------- index admin
    def _maybe_cluster_refresh(self, index: str, refresh) -> None:
        if refresh in ("true", "wait_for", True, ""):
            self._call(self.cluster.client_refresh, index)

    def _refresh_indices(self, names) -> None:
        """Bulk epilogue refresh: broadcast through the cluster (the local
        IndicesService holds no cluster shards)."""
        for name in names:
            self._call(self.cluster.client_refresh, name)

    def create_index_api(self, name: str, settings: Optional[dict] = None,
                         mappings: Optional[dict] = None) -> dict:
        return self._call(self.cluster.client_create_index, name,
                          settings, mappings)

    def delete_index_api(self, name: str) -> dict:
        self._meta(name)
        return self._call(self.cluster.client_delete_index, name)

    def cluster_index_names(self) -> List[str]:
        return sorted(self.cluster.cluster_state.metadata)
