"""Immutable cluster state + diffs.

Re-design of `cluster/ClusterState.java` (746 LoC) + `AbstractDiffable`:
the cluster-wide value replicated by the coordination layer. Carries the
elected master, node membership, index metadata, and the routing table
(shard copies → nodes). States are versioned (term, version) and support
diff-based publication (`PublicationTransportHandler.java:404` sends diffs
to nodes that have the previous version).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, FrozenSet, List, Optional, Set


class DiscoveryNode:
    __slots__ = ("node_id", "name", "address", "roles", "attributes")

    def __init__(self, node_id: str, name: str = "", address: str = "",
                 roles: Optional[Set[str]] = None,
                 attributes: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.name = name or node_id
        self.address = address
        self.roles = frozenset(roles or {"master", "data"})
        # awareness/filter attributes (`DiscoveryNode.getAttributes()`:
        # node.attr.* settings, e.g. zone/rack), used by the allocation
        # deciders
        self.attributes = dict(attributes or {})

    @property
    def is_master_eligible(self) -> bool:
        return "master" in self.roles

    def to_dict(self) -> dict:
        return {"id": self.node_id, "name": self.name, "address": self.address,
                "roles": sorted(self.roles), "attributes": self.attributes}

    @staticmethod
    def from_dict(d: dict) -> "DiscoveryNode":
        return DiscoveryNode(d["id"], d.get("name", ""), d.get("address", ""),
                             set(d.get("roles", [])), d.get("attributes"))

    def __eq__(self, other):
        return isinstance(other, DiscoveryNode) and self.node_id == other.node_id

    def __hash__(self):
        return hash(self.node_id)

    def __repr__(self):
        return f"DiscoveryNode({self.node_id})"


class VotingConfiguration:
    """A quorum-defining node-id set (`CoordinationMetaData.VotingConfiguration`)."""

    __slots__ = ("node_ids",)

    EMPTY: "VotingConfiguration"

    def __init__(self, node_ids):
        self.node_ids: FrozenSet[str] = frozenset(node_ids)

    def has_quorum(self, votes) -> bool:
        if not self.node_ids:
            return False
        count = sum(1 for v in votes if v in self.node_ids)
        return count * 2 > len(self.node_ids)

    def __eq__(self, other):
        return isinstance(other, VotingConfiguration) and self.node_ids == other.node_ids

    def __repr__(self):
        return f"VotingConfiguration({sorted(self.node_ids)})"


VotingConfiguration.EMPTY = VotingConfiguration(())


class ShardRoutingEntry:
    """One shard copy's assignment (`cluster/routing/ShardRouting.java`).

    A rebalance move is modelled as the source entry entering RELOCATING
    while a fresh INITIALIZING entry (with `relocation_source` = the source's
    allocation id) recovers on the target node; when the target starts, the
    source entry is dropped (`ShardRouting.relocatingNodeId` analog)."""

    __slots__ = ("index", "shard", "primary", "node_id", "state",
                 "allocation_id", "relocation_source")

    UNASSIGNED = "UNASSIGNED"
    INITIALIZING = "INITIALIZING"
    STARTED = "STARTED"
    RELOCATING = "RELOCATING"

    def __init__(self, index: str, shard: int, primary: bool,
                 node_id: Optional[str], state: str, allocation_id: str,
                 relocation_source: Optional[str] = None):
        self.index = index
        self.shard = shard
        self.primary = primary
        self.node_id = node_id
        self.state = state
        self.allocation_id = allocation_id
        self.relocation_source = relocation_source

    def to_dict(self) -> dict:
        d = {"index": self.index, "shard": self.shard, "primary": self.primary,
             "node": self.node_id, "state": self.state,
             "allocation_id": self.allocation_id}
        if self.relocation_source is not None:
            d["relocation_source"] = self.relocation_source
        return d

    @staticmethod
    def from_dict(d: dict) -> "ShardRoutingEntry":
        return ShardRoutingEntry(d["index"], d["shard"], d["primary"],
                                 d.get("node"), d["state"], d["allocation_id"],
                                 d.get("relocation_source"))

    def copy(self, **kw) -> "ShardRoutingEntry":
        d = self.to_dict()
        d.update({"node" if k == "node_id" else k: v for k, v in kw.items()})
        return ShardRoutingEntry.from_dict(d)


class ClusterState:
    """Immutable; build modified copies via `with_(...)`."""

    __slots__ = ("term", "version", "cluster_name", "master_node_id", "nodes",
                 "metadata", "routing", "last_committed_config",
                 "last_accepted_config", "in_sync_allocations", "settings")

    def __init__(self, term: int = 0, version: int = 0,
                 cluster_name: str = "tpu-search",
                 master_node_id: Optional[str] = None,
                 nodes: Optional[Dict[str, DiscoveryNode]] = None,
                 metadata: Optional[Dict[str, dict]] = None,
                 routing: Optional[List[ShardRoutingEntry]] = None,
                 last_committed_config: VotingConfiguration = VotingConfiguration.EMPTY,
                 last_accepted_config: VotingConfiguration = VotingConfiguration.EMPTY,
                 in_sync_allocations: Optional[Dict[tuple, Set[str]]] = None,
                 settings: Optional[Dict[str, Any]] = None):
        self.term = term
        self.version = version
        self.cluster_name = cluster_name
        self.master_node_id = master_node_id
        self.nodes = dict(nodes or {})
        self.metadata = metadata or {}          # index name -> {settings, mappings, ...}
        self.routing = list(routing or [])
        self.last_committed_config = last_committed_config
        self.last_accepted_config = last_accepted_config
        self.in_sync_allocations = dict(in_sync_allocations or {})
        # persistent cluster-wide settings (`MetaData.persistentSettings()`):
        # cluster.routing.* allocation controls live here
        self.settings = dict(settings or {})

    def with_(self, **kw) -> "ClusterState":
        fields = dict(
            term=self.term, version=self.version, cluster_name=self.cluster_name,
            master_node_id=self.master_node_id, nodes=self.nodes,
            metadata=self.metadata, routing=self.routing,
            last_committed_config=self.last_committed_config,
            last_accepted_config=self.last_accepted_config,
            in_sync_allocations=self.in_sync_allocations,
            settings=self.settings)
        fields.update(kw)
        return ClusterState(**fields)

    # -- routing helpers ------------------------------------------------------
    def shards_of(self, index: str) -> List[ShardRoutingEntry]:
        return [r for r in self.routing if r.index == index]

    def primary_of(self, index: str, shard: int) -> Optional[ShardRoutingEntry]:
        for r in self.routing:
            if r.index == index and r.shard == shard and r.primary \
                    and r.state in (ShardRoutingEntry.STARTED, ShardRoutingEntry.RELOCATING):
                return r
        return None

    def replicas_of(self, index: str, shard: int) -> List[ShardRoutingEntry]:
        return [r for r in self.routing
                if r.index == index and r.shard == shard and not r.primary]

    def shards_on_node(self, node_id: str) -> List[ShardRoutingEntry]:
        return [r for r in self.routing if r.node_id == node_id]

    # -- serialization --------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "term": self.term, "version": self.version,
            "cluster_name": self.cluster_name,
            "master_node": self.master_node_id,
            "nodes": {nid: n.to_dict() for nid, n in self.nodes.items()},
            "metadata": self.metadata,
            "routing": [r.to_dict() for r in self.routing],
            "last_committed_config": sorted(self.last_committed_config.node_ids),
            "last_accepted_config": sorted(self.last_accepted_config.node_ids),
            "in_sync_allocations": {f"{i}:{s}": sorted(a) for (i, s), a
                                    in self.in_sync_allocations.items()},
            "settings": self.settings,
        }

    @staticmethod
    def from_dict(d: dict) -> "ClusterState":
        isa = {}
        for key, ids in d.get("in_sync_allocations", {}).items():
            index, _, shard = key.rpartition(":")
            isa[(index, int(shard))] = set(ids)
        return ClusterState(
            term=d["term"], version=d["version"],
            cluster_name=d.get("cluster_name", "tpu-search"),
            master_node_id=d.get("master_node"),
            nodes={nid: DiscoveryNode.from_dict(nd)
                   for nid, nd in d.get("nodes", {}).items()},
            metadata=d.get("metadata", {}),
            routing=[ShardRoutingEntry.from_dict(r) for r in d.get("routing", [])],
            last_committed_config=VotingConfiguration(d.get("last_committed_config", [])),
            last_accepted_config=VotingConfiguration(d.get("last_accepted_config", [])),
            in_sync_allocations=isa,
            settings=d.get("settings"))

    def diff_from(self, previous: "ClusterState") -> dict:
        """Publication diff: full state only where sections changed
        (`DiffableUtils` analog at section granularity)."""
        d: dict = {"prev_version": previous.version, "term": self.term,
                   "version": self.version, "master_node": self.master_node_id}
        full = self.to_dict()
        prev = previous.to_dict()
        for section in ("nodes", "metadata", "routing", "last_committed_config",
                        "last_accepted_config", "in_sync_allocations",
                        "cluster_name", "settings"):
            if full[section] != prev[section]:
                d[section] = full[section]
        return d

    def apply_diff(self, diff: dict) -> "ClusterState":
        if diff.get("prev_version") != self.version:
            raise ValueError("diff does not apply to this state version")
        base = self.to_dict()
        for k, v in diff.items():
            if k != "prev_version":
                base[k] = v
        return ClusterState.from_dict(base)
