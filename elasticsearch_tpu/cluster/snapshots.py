"""Cluster-state-driven snapshot lifecycle.

Re-design of the reference's three-way split (`snapshots/SnapshotsService
.java` master lifecycle, `SnapshotShardsService.java` per-node shard
uploads driven by applied state, `RestoreService.java` restore re-entering
allocation): a snapshot is an entry in cluster-state metadata that every
node observes —

  1. the master writes `_snapshots_in_progress[repo:name]` with one
     INIT-state entry per primary shard, assigned to the node that holds it;
  2. every node's state listener uploads ITS shards to the repository and
     reports per-shard success/failure back to the master;
  3. when all shards are terminal the master flips the entry to FINALIZING,
     writes the manifest (off the event loop), and removes the entry.

Restore ships the manifest'd indices back INTO allocation: the master
creates index metadata + routing and records `_restore_in_progress[index]`;
when `apply_cluster_state` builds a restored primary it materializes the
shard files from the repository first (cluster_node.py shard_restore_hook),
and the entry clears once every primary reports started.

This module is the pure state machine — blob IO and shard access are hooks
the REST layer installs (`cluster/rest_node.py:_wire_cluster_snapshots`),
keeping repository imports out of the coordination layer.

Round 3 had none of this: a snapshot taken through a 3-node cluster
captured only the receiving node's local shards (silent data loss).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Set, Tuple

SNAPSHOTS_IN_PROGRESS = "_snapshots_in_progress"
RESTORE_IN_PROGRESS = "_restore_in_progress"

MASTER_START_SNAPSHOT = "cluster:admin/snapshot/create"
MASTER_SNAPSHOT_SHARD = "internal:cluster/snapshot/update_shard"
MASTER_FINALIZE_SNAPSHOT = "internal:cluster/snapshot/finalize"
MASTER_START_RESTORE = "cluster:admin/snapshot/restore"
MASTER_CLEAR_RESTORE = "internal:cluster/snapshot/clear_restore"


def _index_names(metadata: dict) -> list:
    return [k for k in metadata if not k.startswith("_")]


class ClusterSnapshotLifecycle:
    """Registers the master handlers + the per-node shard worker listener.

    Data-plane hooks (installed by the REST layer):
      repo_factory(repo_name) -> Repository
      shard_uploader(repo_name, index, shard_id) -> block shard entry
          ({"blocks": [...], "meta": {...}, "stats": {...}} —
          recovery/snapshot.py `snapshot_shard`)
      executor(fn) — run blob IO off the event loop
    """

    def __init__(self, cluster_node):
        self.c = cluster_node
        self.repo_factory: Optional[Callable] = None
        self.shard_uploader: Optional[Callable] = None
        self.executor: Optional[Callable] = None
        self._running: Set[Tuple[str, str]] = set()    # (snap key, shard key)
        self._finalizing: Set[str] = set()
        t = cluster_node.transport
        me = cluster_node.node_id
        t.register(me, MASTER_START_SNAPSHOT, self._on_start_snapshot)
        t.register(me, MASTER_SNAPSHOT_SHARD, self._on_shard_result)
        t.register(me, MASTER_FINALIZE_SNAPSHOT, self._on_finalize)
        t.register(me, MASTER_START_RESTORE, self._on_start_restore)
        t.register(me, MASTER_CLEAR_RESTORE, self._on_clear_restore)
        cluster_node.state_listeners.append(self.on_state_applied)

    # ----------------------------------------------------------- client side
    def client_create(self, repo: str, snapshot: str, indices: str = "_all",
                      on_done=None, on_failure=None) -> None:
        self.c._send_to_master(
            MASTER_START_SNAPSHOT,
            {"repo": repo, "snapshot": snapshot, "indices": indices},
            on_response=on_done or (lambda r: None), on_failure=on_failure)

    def client_restore(self, repo: str, snapshot: str, indices: dict,
                       on_done=None, on_failure=None) -> None:
        """`indices`: {target_name: manifest index entry} — the calling REST
        node reads the manifest (it has repository access; the master need
        not touch blobs to start a restore)."""
        self.c._send_to_master(
            MASTER_START_RESTORE,
            {"repo": repo, "snapshot": snapshot, "indices": indices},
            on_response=on_done or (lambda r: None), on_failure=on_failure)

    # --------------------------------------------------------- master updates
    def _on_start_snapshot(self, sender, request, respond):
        self.c._require_master()
        repo, snapshot = request["repo"], request["snapshot"]
        key = f"{repo}:{snapshot}"
        expr = request.get("indices", "_all")
        now_ms = int(time.time() * 1000)

        cur = self.c.cluster_state.metadata.get(SNAPSHOTS_IN_PROGRESS) or {}
        if key in cur:
            respond({"error": {
                "type": "invalid_snapshot_name_exception",
                "reason": f"snapshot with the same name [{snapshot}] "
                          "is already in progress", "status": 400}})
            return

        def update(base):
            from elasticsearch_tpu.common.patterns import matches_csv_patterns
            meta = dict(base.metadata)
            sips = dict(meta.get(SNAPSHOTS_IN_PROGRESS) or {})
            if key in sips:
                return base
            names = [n for n in _index_names(meta)
                     if matches_csv_patterns(n, expr)]
            shards = {}
            for r in base.routing:
                if r.index in names and r.primary:
                    shards[f"{r.index}#{r.shard}"] = {"node": r.node_id,
                                                      "state": "INIT"}
            entry = {"repo": repo, "snapshot": snapshot,
                     "state": "FINALIZING" if not shards else "IN_PROGRESS",
                     "start_ms": now_ms,
                     "indices": {n: {
                         "settings": dict(meta[n].get("settings") or {}),
                         "mappings": meta[n].get("mappings")
                         or {"properties": {}},
                         "aliases": meta[n].get("aliases") or {}}
                         for n in names},
                     "shards": shards}
            sips[key] = entry
            meta[SNAPSHOTS_IN_PROGRESS] = sips
            return base.with_(metadata=meta)

        self.c._publish_then_respond(update, respond, {"accepted": True},
                                     source=f"start-snapshot [{key}]")

    def _on_shard_result(self, sender, request, respond):
        self.c._require_master()
        key, shard_key = request["key"], request["shard"]
        files, failure = request.get("files"), request.get("failure")

        def update(base):
            meta = dict(base.metadata)
            sips = dict(meta.get(SNAPSHOTS_IN_PROGRESS) or {})
            entry = sips.get(key)
            if entry is None:
                return base
            entry = dict(entry)
            shards = dict(entry["shards"])
            if shard_key not in shards:
                return base
            sh = dict(shards[shard_key])
            if failure is not None:
                sh["state"], sh["failure"] = "FAILED", str(failure)
            else:
                sh["state"], sh["files"] = "SUCCESS", files or {}
            shards[shard_key] = sh
            entry["shards"] = shards
            if all(s["state"] in ("SUCCESS", "FAILED")
                   for s in shards.values()):
                entry["state"] = "FINALIZING"
            sips[key] = entry
            meta[SNAPSHOTS_IN_PROGRESS] = sips
            return base.with_(metadata=meta)

        self.c._publish_then_respond(update, respond, {"acknowledged": True},
                                     source=f"snapshot-shard [{key}]")

    def _on_finalize(self, sender, request, respond):
        self.c._require_master()
        key = request["key"]

        def update(base):
            meta = dict(base.metadata)
            sips = dict(meta.get(SNAPSHOTS_IN_PROGRESS) or {})
            if sips.pop(key, None) is None:
                return base
            meta[SNAPSHOTS_IN_PROGRESS] = sips
            return base.with_(metadata=meta)

        self.c._publish_then_respond(update, respond, {"acknowledged": True},
                                     source=f"finalize-snapshot [{key}]")

    def _on_start_restore(self, sender, request, respond):
        self.c._require_master()
        repo, snapshot = request["repo"], request["snapshot"]
        indices: Dict[str, Any] = request["indices"]

        existing = [n for n in indices
                    if n in self.c.cluster_state.metadata]
        if existing:
            respond({"error": {
                "type": "snapshot_restore_exception",
                "reason": f"cannot restore index [{existing[0]}] because an "
                          "open index with same name already exists in the "
                          "cluster", "status": 500}})
            return

        def update(base):
            from elasticsearch_tpu.cluster import allocation
            state = base
            meta = dict(state.metadata)
            rip = dict(meta.get(RESTORE_IN_PROGRESS) or {})
            for target, entry in indices.items():
                if target in meta:
                    continue
                settings = dict(entry.get("settings") or {})
                settings.setdefault("index.number_of_shards", 1)
                settings.setdefault("index.number_of_replicas", 1)
                meta[target] = {"settings": settings,
                                "mappings": entry.get("mappings")
                                or {"properties": {}},
                                "aliases": entry.get("aliases") or {}}
                rip[target] = {"repo": repo, "snapshot": snapshot,
                               "shards": entry.get("shards") or {}}
                meta[RESTORE_IN_PROGRESS] = rip
                state = state.with_(metadata=meta)
                state = allocation.allocate_new_index(
                    state, target,
                    int(settings["index.number_of_shards"]),
                    int(settings["index.number_of_replicas"]))
                meta = dict(state.metadata)
            return state

        self.c._publish_then_respond(
            update, respond,
            {"accepted": True, "indices": sorted(indices)},
            source=f"restore-snapshot [{repo}:{snapshot}]")

    def _on_clear_restore(self, sender, request, respond):
        self.c._require_master()
        index = request["index"]

        def update(base):
            meta = dict(base.metadata)
            rip = dict(meta.get(RESTORE_IN_PROGRESS) or {})
            if rip.pop(index, None) is None:
                return base
            meta[RESTORE_IN_PROGRESS] = rip
            return base.with_(metadata=meta)

        self.c._publish_then_respond(update, respond, {"acknowledged": True},
                                     source=f"clear-restore [{index}]")

    # ------------------------------------------------- per-node state worker
    def on_state_applied(self, state) -> None:
        """SnapshotShardsService analog: react to applied cluster state."""
        sips = state.metadata.get(SNAPSHOTS_IN_PROGRESS) or {}

        # GC local bookkeeping for completed snapshots
        self._running = {(k, s) for (k, s) in self._running if k in sips}
        self._finalizing = {k for k in self._finalizing if k in sips}

        for key, entry in sips.items():
            for shard_key, sh in entry["shards"].items():
                if (sh["state"] == "INIT" and sh["node"] == self.c.node_id
                        and (key, shard_key) not in self._running):
                    self._running.add((key, shard_key))
                    self._spawn_upload(key, entry, shard_key)
            if (entry["state"] == "FINALIZING" and self.c.is_master
                    and key not in self._finalizing):
                self._finalizing.add(key)
                self._spawn_finalize(key, entry)

        if self.c.is_master:
            # shards assigned to nodes that left can never report: fail
            # them so the snapshot completes as PARTIAL instead of hanging
            for key, entry in sips.items():
                for shard_key, sh in entry["shards"].items():
                    if sh["state"] == "INIT" and sh["node"] not in state.nodes:
                        self._send_master(
                            MASTER_SNAPSHOT_SHARD,
                            {"key": key, "shard": shard_key,
                             "failure": f"node [{sh['node']}] left"})

            rip = state.metadata.get(RESTORE_IN_PROGRESS) or {}
            for index in list(rip):
                prim = [r for r in state.routing
                        if r.index == index and r.primary]
                if prim and all(r.state == "STARTED" for r in prim):
                    self._send_master(MASTER_CLEAR_RESTORE, {"index": index})

    def _send_master(self, action: str, request: dict) -> None:
        """Send from any thread: transport ops must run on the loop."""
        loop = getattr(self.c.transport, "loop", None)
        send = lambda: self.c._send_to_master(  # noqa: E731
            action, request, on_response=lambda r: None,
            on_failure=lambda e: None)
        if loop is not None:
            loop.call_soon_threadsafe(send)
        else:
            send()

    def _submit(self, fn: Callable) -> None:
        if self.executor is not None:
            self.executor(fn)
        else:
            fn()

    def _spawn_upload(self, key: str, entry: dict, shard_key: str) -> None:
        index, _, sid = shard_key.rpartition("#")

        def work():
            try:
                if self.shard_uploader is None:
                    raise RuntimeError("no shard uploader installed")
                files = self.shard_uploader(entry["repo"], index, int(sid))
                self._send_master(MASTER_SNAPSHOT_SHARD,
                                  {"key": key, "shard": shard_key,
                                   "files": files})
            except Exception as e:
                self._send_master(MASTER_SNAPSHOT_SHARD,
                                  {"key": key, "shard": shard_key,
                                   "failure": str(e)})

        self._submit(work)

    def _spawn_finalize(self, key: str, entry: dict) -> None:
        def work():
            try:
                if self.repo_factory is None:
                    raise RuntimeError("no repository factory installed")
                repo = self.repo_factory(entry["repo"])
                shards = entry["shards"]
                failed = sum(1 for s in shards.values()
                             if s["state"] == "FAILED")
                manifest = {
                    "snapshot": entry["snapshot"],
                    "state": "PARTIAL" if failed else "SUCCESS",
                    "start_time_in_millis": entry["start_ms"],
                    "end_time_in_millis": int(time.time() * 1000),
                    "indices": {},
                    "shards": {"total": len(shards), "failed": failed,
                               "successful": len(shards) - failed},
                }
                for name, imeta in entry["indices"].items():
                    ientry = dict(imeta)
                    ientry["shards"] = {}
                    for shard_key, sh in shards.items():
                        idx, _, sid = shard_key.rpartition("#")
                        if idx == name:
                            payload = sh.get("files") or {}
                            if "blocks" in payload:
                                # block manifest (recovery/snapshot.py):
                                # flatten to the same shard-entry shape
                                # the single-node SnapshotService writes
                                ientry["shards"][sid] = {
                                    **payload, "state": sh["state"],
                                    "node": sh["node"]}
                            else:  # pre-block uploads: raw files by digest
                                ientry["shards"][sid] = {
                                    "files": payload,
                                    "state": sh["state"],
                                    "node": sh["node"]}
                    manifest["indices"][name] = ientry
                repo.put_manifest(entry["snapshot"], manifest)
            finally:
                # remove the in-progress entry either way; a failed manifest
                # write surfaces as a missing snapshot, never a stuck entry
                self._send_master(MASTER_FINALIZE_SNAPSHOT, {"key": key})

        self._submit(work)
