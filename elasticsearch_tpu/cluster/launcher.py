"""Multi-process cluster launcher: boot N data nodes as real processes.

Each data node is its own OS process with its own event loop, engine set,
columnar store, and device corpus, serving the framed binary protocol of
`transport/tcp.py` on a real socket — the production counterpart of the
in-process simulator clusters the test suite runs. The coordinator (the
parent process, or any other launched node) joins the same cluster over
TCP; `ClusterNode`/`Coordinator` code is identical on both sides.

Two surfaces:

* CLI (child side): `python -m elasticsearch_tpu.cluster.launcher
  --node-id n1 --port 9301 --data-path /tmp/n1 \
  --peers n0=127.0.0.1:9300,n1=127.0.0.1:9301 --masters n0,n1`
  boots ONE data node and serves until killed. Prints
  `LAUNCHER_READY <node_id> <port>` on stdout once bound.

* `launch_nodes(...)` (parent side): picks ports, spawns the children,
  waits for their ready lines, and returns `NodeProcess` handles with
  `kill()` (SIGKILL — the node-death bench primitive) and
  `terminate()`. `join_cluster(...)` then builds the parent's own
  in-process `ClusterNode` wired to the same peer set over TCP.

The launcher is how `15_real_cluster` bench rows get their
`simulated: false` label: every cross-node byte crosses a kernel socket
boundary between processes, and time is wall-clock.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

DEFAULT_HOST = "127.0.0.1"
READY_PREFIX = "LAUNCHER_READY"


def default_host() -> str:
    """Bind/advertise host for launched nodes: `ES_TPU_BIND_HOST` when
    set, else loopback. Resolved at CALL time (not import) so a test or
    wrapper can flip the env var per launch."""
    return os.environ.get("ES_TPU_BIND_HOST") or DEFAULT_HOST


# --------------------------------------------------------------- addressing

def find_free_ports(n: int, host: Optional[str] = None) -> List[int]:
    """Reserve n distinct ephemeral ports by binding then releasing them.
    The small release-to-rebind race is acceptable on loopback — the
    alternative (children choosing ports) needs a rendezvous channel
    before the cluster exists to provide one."""
    if host is None:
        host = default_host()
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def format_peers(peers: Dict[str, Tuple[str, int]]) -> str:
    return ",".join(f"{nid}={host}:{port}"
                    for nid, (host, port) in sorted(peers.items()))


def parse_peers(spec: str) -> Dict[str, Tuple[str, int]]:
    peers: Dict[str, Tuple[str, int]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        nid, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        peers[nid] = (host, int(port))
    return peers


# ------------------------------------------------------------- child process

def run_data_node(node_id: str, port: int, data_path: str,
                  peers: Dict[str, Tuple[str, int]],
                  masters: List[str], host: Optional[str] = None,
                  policy_config: Optional[dict] = None,
                  cluster_settings: Optional[dict] = None,
                  ready_out=None) -> None:
    """Child-process entry: boot one data node and serve forever.

    Builds the node's own event loop, binds the TCP transport, seeds the
    peer address book, and starts a `ClusterNode` whose discovery address
    is this socket — so any node that learns of us through a committed
    cluster state can also dial us. Blocks in `loop.run_forever()`."""
    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.transport.tcp import (
        AsyncioScheduler, TcpTransportService)

    if host is None:
        host = default_host()
    if policy_config:
        from elasticsearch_tpu.parallel import policy
        policy.configure(**policy_config)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    transport = TcpTransportService(node_id, host=host, port=port, loop=loop)
    loop.run_until_complete(transport.bind())
    for peer_id, (phost, pport) in peers.items():
        if peer_id != node_id:
            transport.add_peer_address(peer_id, phost, pport)
    # bootstrap_state is deterministic for a fixed master list: every
    # process persists the identical version-0 state before first start
    initial = bootstrap_state(sorted(masters))
    if cluster_settings:
        initial = initial.with_(settings={**initial.settings,
                                          **cluster_settings})
    seed = sum(ord(c) for c in node_id)  # stable per node, differs by id
    scheduler = AsyncioScheduler(loop, seed=seed)
    node = ClusterNode(
        node_id, data_path, transport, scheduler,
        seed_peers=[p for p in sorted(peers) if p != node_id],
        initial_state=initial,
        address=f"{host}:{transport.port}")
    node.start()
    out = ready_out or sys.stdout
    print(f"{READY_PREFIX} {node_id} {transport.port}", file=out, flush=True)
    try:
        loop.run_forever()
    finally:
        try:
            node.stop()
            loop.run_until_complete(transport.close())
        except Exception:
            pass
        loop.close()


# ------------------------------------------------------------ parent helpers

@dataclass
class NodeProcess:
    node_id: str
    host: str
    port: int
    proc: subprocess.Popen = field(repr=False)

    @property
    def address(self) -> Tuple[str, int]:
        return self.host, self.port

    def kill(self) -> None:
        """SIGKILL — the unclean node-death the fault benches measure:
        no FIN handshake help from a closing runtime, peers discover the
        death from dead sockets and fault timeouts alone."""
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


def launch_nodes(node_ids: List[str], base_dir: str,
                 peers: Dict[str, Tuple[str, int]],
                 masters: List[str],
                 policy_config: Optional[dict] = None,
                 cluster_settings: Optional[dict] = None,
                 env: Optional[dict] = None,
                 ready_timeout_s: float = 120.0) -> List[NodeProcess]:
    """Spawn one data-node process per id (each id must appear in
    `peers` with its pre-reserved port) and wait for every child's
    ready line. Children inherit JAX_PLATFORMS etc. from `env`."""
    procs: List[NodeProcess] = []
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update(env)
    for nid in node_ids:
        host, port = peers[nid]
        cmd = [sys.executable, "-m", "elasticsearch_tpu.cluster.launcher",
               "--node-id", nid, "--host", host, "--port", str(port),
               "--data-path", os.path.join(base_dir, nid),
               "--peers", format_peers(peers),
               "--masters", ",".join(sorted(masters))]
        if policy_config:
            cmd += ["--policy", json.dumps(policy_config)]
        if cluster_settings:
            cmd += ["--settings", json.dumps(cluster_settings)]
        proc = subprocess.Popen(cmd, env=child_env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        procs.append(NodeProcess(nid, host, port, proc))
    deadline = time.monotonic() + ready_timeout_s
    for np_ in procs:
        while True:
            if time.monotonic() > deadline:
                for p in procs:
                    p.terminate()
                raise TimeoutError(
                    f"node [{np_.node_id}] not ready in {ready_timeout_s}s")
            line = np_.proc.stdout.readline()
            if not line:
                if np_.proc.poll() is not None:
                    for p in procs:
                        p.terminate()
                    raise RuntimeError(
                        f"node [{np_.node_id}] exited rc={np_.proc.returncode}"
                        " before ready")
                continue
            if line.startswith(READY_PREFIX):
                break
    return procs


def join_cluster(node_id: str, data_path: str,
                 peers: Dict[str, Tuple[str, int]],
                 masters: List[str], loop,
                 cluster_settings: Optional[dict] = None,
                 host: Optional[str] = None, port: int = 0,
                 roles: Optional[set] = None):
    """Build the parent process's own `ClusterNode` (typically the bench
    coordinator) on `loop`, wired into the same TCP peer set the
    children were launched with. Returns (node, transport).

    `roles={"master"}` joins a coordinating-only node: it can vote and
    coordinate searches but never holds shard copies, so every data leg
    of a fan-out crosses a socket to a child process."""
    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.transport.tcp import (
        AsyncioScheduler, TcpTransportService)

    want = peers.get(node_id, (host if host is not None
                               else default_host(), port))
    transport = TcpTransportService(node_id, host=want[0], port=want[1],
                                    loop=loop)
    loop.run_until_complete(transport.bind())
    for peer_id, (phost, pport) in peers.items():
        if peer_id != node_id:
            transport.add_peer_address(peer_id, phost, pport)
    initial = bootstrap_state(sorted(masters))
    if cluster_settings:
        initial = initial.with_(settings={**initial.settings,
                                          **cluster_settings})
    scheduler = AsyncioScheduler(loop, seed=sum(ord(c) for c in node_id))
    node = ClusterNode(
        node_id, data_path, transport, scheduler,
        seed_peers=[p for p in sorted(peers) if p != node_id],
        initial_state=initial,
        address=f"{want[0]}:{transport.port}", roles=roles)
    node.start()
    return node, transport


# -------------------------------------------------------------------- CLI

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Boot one TCP data node of a multi-process cluster")
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--host", default=None,
                    help="bind/advertise address (default: "
                         "$ES_TPU_BIND_HOST or 127.0.0.1)")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--peers", required=True,
                    help="comma list of node_id=host:port for ALL nodes")
    ap.add_argument("--masters", required=True,
                    help="comma list of initial master-eligible node ids")
    ap.add_argument("--policy", default=None,
                    help="JSON kwargs for parallel.policy.configure")
    ap.add_argument("--settings", default=None,
                    help="JSON dict merged into the bootstrap cluster "
                         "settings")
    args = ap.parse_args(argv)
    os.makedirs(args.data_path, exist_ok=True)
    run_data_node(
        args.node_id, args.port, args.data_path,
        peers=parse_peers(args.peers),
        masters=[m.strip() for m in args.masters.split(",") if m.strip()],
        host=args.host,
        policy_config=json.loads(args.policy) if args.policy else None,
        cluster_settings=json.loads(args.settings) if args.settings else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
