"""Durable coordination state (the gateway).

Re-design of `gateway/PersistedClusterStateService.java:117` +
`GatewayMetaState.java:79`: every node persists (currentTerm,
lastAcceptedClusterState) to its data path *before* acknowledging joins
or publications, so a full-cluster restart recovers committed metadata
(indices, mappings, voting configs) with terms monotonic — the safety
argument of the consensus layer depends on this durability.

The reference stores state docs in a dedicated Lucene index with
generation files; here each write is a CRC-tagged JSON generation file
committed via write-to-temp → fsync → atomic rename → fsync(dir), with
the previous generation retained for torn-write recovery.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional, Tuple

from elasticsearch_tpu.cluster.coordination import PersistedState
from elasticsearch_tpu.cluster.state import ClusterState

_STATE_DIR = "_state"
_PREFIX = "coord-"
_SUFFIX = ".json"
_KEEP_GENERATIONS = 2


def _canonical(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


class FilePersistedState(PersistedState):
    """File-backed (term, lastAcceptedState); drop-in for the in-memory
    PersistedState the deterministic tests use."""

    def __init__(self, data_path: str,
                 initial_state: Optional[ClusterState] = None):
        self.dir = os.path.join(data_path, _STATE_DIR)
        os.makedirs(self.dir, exist_ok=True)
        loaded = self._load_latest()
        # resume from the HIGHEST generation present (readable or not):
        # new writes must supersede unreadable high-numbered files, or the
        # retention sweep would keep the corrupt ones and delete fresh state
        gens = self._generations()
        self.generation = gens[0][0] if gens else 0
        if loaded is not None:
            _, term, state = loaded
        else:
            term, state = 0, initial_state or ClusterState()
        super().__init__(term, state)

    # -- recovery -------------------------------------------------------------
    def _generations(self):
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(_PREFIX) and name.endswith(_SUFFIX):
                try:
                    out.append((int(name[len(_PREFIX):-len(_SUFFIX)]), name))
                except ValueError:
                    continue
        return sorted(out, reverse=True)

    def _load_latest(self) -> Optional[Tuple[int, int, ClusterState]]:
        """Newest readable generation, or None."""
        for gen, name in self._generations():
            path = os.path.join(self.dir, name)
            try:
                with open(path, "rb") as f:
                    wrapper = json.loads(f.read())
                doc = wrapper["doc"]
                if zlib.crc32(_canonical(doc)) != wrapper["crc"]:
                    continue  # torn write: fall back to previous generation
                return gen, int(doc["term"]), ClusterState.from_dict(doc["state"])
            except (OSError, ValueError, KeyError):
                continue
        return None

    # -- durable mutations ----------------------------------------------------
    def set_term(self, term: int) -> None:
        if term != self.current_term:
            super().set_term(term)
            self._persist()

    def set_last_accepted(self, state: ClusterState) -> None:
        super().set_last_accepted(state)
        self._persist()

    def _persist(self) -> None:
        doc = {"term": self.current_term,
               "state": self.last_accepted.to_dict()}
        payload = json.dumps(
            {"crc": zlib.crc32(_canonical(doc)), "doc": doc}).encode()
        self.generation += 1
        final = os.path.join(self.dir, f"{_PREFIX}{self.generation}{_SUFFIX}")
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        dir_fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        for gen, name in self._generations()[_KEEP_GENERATIONS:]:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass
