"""Shard allocation: assigning shard copies to nodes.

Re-design of `routing/allocation/AllocationService.java` + the balanced
allocator + deciders (SURVEY.md §2.3): pure functions from (cluster state,
event) to a new routing table. Deciders enforced here:
  - same-shard: never two copies of one shard on a node
    (`SameShardAllocationDecider`)
  - balance: new copies go to data nodes with the fewest shards
    (`BalancedShardsAllocator`, weight = shard count)
Events: index created, node joined (allocate unassigned), node left
(promote replicas / reallocate), shard started, shard failed.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.state import ClusterState, ShardRoutingEntry

_alloc_counter = itertools.count()


def _new_allocation_id(index: str, shard: int) -> str:
    return f"{index}[{shard}]#{next(_alloc_counter)}"


def _data_nodes(state: ClusterState) -> List[str]:
    return sorted(nid for nid, n in state.nodes.items() if "data" in n.roles)


def _shard_counts(routing: List[ShardRoutingEntry]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for r in routing:
        if r.node_id:
            counts[r.node_id] = counts.get(r.node_id, 0) + 1
    return counts


def _pick_node(routing: List[ShardRoutingEntry], candidates: List[str],
               exclude: Set[str]) -> Optional[str]:
    counts = _shard_counts(routing)
    usable = [n for n in candidates if n not in exclude]
    if not usable:
        return None
    return min(usable, key=lambda n: (counts.get(n, 0), n))


def allocate_new_index(state: ClusterState, index: str, num_shards: int,
                       num_replicas: int) -> ClusterState:
    """Create INITIALIZING entries for a new index's shards."""
    routing = list(state.routing)
    nodes = _data_nodes(state)
    isa = dict(state.in_sync_allocations)
    for shard in range(num_shards):
        occupied: Set[str] = set()
        primary_node = _pick_node(routing, nodes, occupied)
        primary = ShardRoutingEntry(index, shard, True, primary_node,
                                    ShardRoutingEntry.INITIALIZING if primary_node
                                    else ShardRoutingEntry.UNASSIGNED,
                                    _new_allocation_id(index, shard))
        routing.append(primary)
        if primary_node:
            occupied.add(primary_node)
        for _ in range(num_replicas):
            rnode = _pick_node(routing, nodes, occupied)
            routing.append(ShardRoutingEntry(
                index, shard, False, rnode,
                ShardRoutingEntry.INITIALIZING if rnode else ShardRoutingEntry.UNASSIGNED,
                _new_allocation_id(index, shard)))
            if rnode:
                occupied.add(rnode)
        isa[(index, shard)] = set()
    return state.with_(routing=routing, in_sync_allocations=isa)


def remove_index(state: ClusterState, index: str) -> ClusterState:
    return state.with_(
        routing=[r for r in state.routing if r.index != index],
        in_sync_allocations={k: v for k, v in state.in_sync_allocations.items()
                             if k[0] != index},
        metadata={k: v for k, v in state.metadata.items() if k != index})


def shard_started(state: ClusterState, allocation_id: str) -> ClusterState:
    routing = []
    isa = dict(state.in_sync_allocations)
    for r in state.routing:
        if r.allocation_id == allocation_id and r.state == ShardRoutingEntry.INITIALIZING:
            r = r.copy(state=ShardRoutingEntry.STARTED)
            key = (r.index, r.shard)
            isa[key] = set(isa.get(key, set())) | {allocation_id}
        routing.append(r)
    return state.with_(routing=routing, in_sync_allocations=isa)


def shard_failed(state: ClusterState, allocation_id: str) -> ClusterState:
    """Fail one copy: primaries promote an in-sync replica; the failed copy
    is reallocated if a node is free (`ReplicationOperation` failure path +
    `AllocationService.applyFailedShards`)."""
    failed = next((r for r in state.routing if r.allocation_id == allocation_id), None)
    if failed is None:
        return state
    return _handle_copy_loss(state, [failed])


def node_left(state: ClusterState, node_id: str) -> ClusterState:
    lost = [r for r in state.routing if r.node_id == node_id]
    if not lost:
        return state
    return _handle_copy_loss(state, lost)


def _handle_copy_loss(state: ClusterState, lost: List[ShardRoutingEntry]) -> ClusterState:
    lost_ids = {r.allocation_id for r in lost}
    routing = [r for r in state.routing if r.allocation_id not in lost_ids]
    isa = {k: set(v) for k, v in state.in_sync_allocations.items()}

    for r in lost:
        key = (r.index, r.shard)
        isa.get(key, set()).discard(r.allocation_id)
        if r.primary:
            # promote an in-sync STARTED replica (reference: primary failover
            # only from the in-sync set — data-loss safety)
            promoted = False
            for i, cand in enumerate(routing):
                if (cand.index, cand.shard) == key and not cand.primary \
                        and cand.state == ShardRoutingEntry.STARTED \
                        and cand.allocation_id in isa.get(key, set()):
                    routing[i] = cand.copy(primary=True)
                    promoted = True
                    break
            if not promoted:
                # no safe copy: shard red/unassigned primary
                routing.append(ShardRoutingEntry(
                    r.index, r.shard, True, None, ShardRoutingEntry.UNASSIGNED,
                    _new_allocation_id(r.index, r.shard)))

    state = state.with_(routing=routing, in_sync_allocations=isa)
    return reroute(state)


def reroute(state: ClusterState) -> ClusterState:
    """Allocate unassigned copies and top up missing replicas
    (`AllocationService.reroute`). Balance via an incrementally-updated
    shard-count map (no double counting)."""
    nodes = _data_nodes(state)
    counts = _shard_counts(state.routing)

    def pick(exclude: Set[str]) -> Optional[str]:
        usable = [n for n in nodes if n not in exclude]
        if not usable:
            return None
        chosen = min(usable, key=lambda n: (counts.get(n, 0), n))
        counts[chosen] = counts.get(chosen, 0) + 1
        return chosen

    by_shard: Dict[Tuple[str, int], List[ShardRoutingEntry]] = {}
    for r in state.routing:
        by_shard.setdefault((r.index, r.shard), []).append(r)

    new_routing: List[ShardRoutingEntry] = []
    for key, copies in sorted(by_shard.items()):
        index, shard = key
        desired_replicas = int(state.metadata.get(index, {}).get(
            "settings", {}).get("index.number_of_replicas", 1))
        occupied = {r.node_id for r in copies if r.node_id}
        out = []
        for r in copies:
            if r.state == ShardRoutingEntry.UNASSIGNED and r.node_id is None:
                if r.primary:
                    # NEVER auto-allocate an unassigned primary: no node holds
                    # in-sync data for it, so assigning would fabricate an
                    # empty shard — silent data loss. The shard stays red
                    # until an operator forces allocation (reference:
                    # primaries allocate only to in-sync copy holders;
                    # allocate_empty_primary is an explicit dangerous command)
                    out.append(r)
                    continue
                node = pick(occupied)
                if node is not None:
                    r = r.copy(node=node, state=ShardRoutingEntry.INITIALIZING)
                    occupied.add(node)
            out.append(r)
        # top up replicas only when a live primary exists to recover from
        has_active_primary = any(
            r.primary and r.node_id and r.state != ShardRoutingEntry.UNASSIGNED
            for r in out)
        replica_count = sum(1 for r in out if not r.primary)
        while has_active_primary and replica_count < desired_replicas:
            node = pick(occupied)
            if node is None:
                break
            out.append(ShardRoutingEntry(index, shard, False, node,
                                         ShardRoutingEntry.INITIALIZING,
                                         _new_allocation_id(index, shard)))
            occupied.add(node)
            replica_count += 1
        new_routing.extend(out)
    return state.with_(routing=new_routing)
