"""Shard allocation: assigning shard copies to nodes.

Re-design of `routing/allocation/AllocationService.java` + the weighted
balancer (`BalancedShardsAllocator.java`, 1,231 LoC) + the pluggable decider
chain (`routing/allocation/decider/`): pure functions from (cluster state,
event) to a new routing table.

Deciders (each answers can_allocate / can_remain / can_rebalance with
YES | NO | THROTTLE, reference `Decision.java`):
  - same-shard  (`SameShardAllocationDecider`)
  - enable      (`EnableAllocationDecider`: cluster.routing.allocation.enable
                 and cluster.routing.rebalance.enable)
  - filter      (`FilterAllocationDecider`: cluster- and index-level
                 include/exclude/require on _name/_id/custom node.attr.*)
  - disk threshold (`DiskThresholdDecider`: low watermark gates new
                 allocations, high watermark evicts via can_remain)
  - throttling  (`ThrottlingAllocationDecider`:
                 cluster.routing.allocation.node_concurrent_recoveries)
  - awareness   (`AwarenessAllocationDecider`: spread copies across
                 cluster.routing.allocation.awareness.attributes values)
  - shards-per-node (`ShardsLimitAllocationDecider`:
                 index.routing.allocation.total_shards_per_node)

The balancer weighs nodes with the reference's two-term formula
(`BalancedShardsAllocator.WeightFunction`): theta0 * (nodeShards - avg)
+ theta1 * (nodeIndexShards - avgIndex); `rebalance()` moves STARTED shards
from the heaviest to the lightest eligible node while the weight delta
exceeds cluster.routing.allocation.balance.threshold. Moves are modelled as
RELOCATING source + INITIALIZING target entries (see ShardRoutingEntry).

Events: index created, node joined (allocate unassigned + rebalance), node
left (promote replicas / reallocate), shard started (completes relocations),
shard failed.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.state import ClusterState, ShardRoutingEntry
from elasticsearch_tpu.common.errors import IllegalArgumentError

_alloc_counter = itertools.count()

YES = "YES"
NO = "NO"
THROTTLE = "THROTTLE"


def _new_allocation_id(index: str, shard: int) -> str:
    return f"{index}[{shard}]#{next(_alloc_counter)}"


def _data_nodes(state: ClusterState) -> List[str]:
    return sorted(nid for nid, n in state.nodes.items() if "data" in n.roles)


class AllocationContext:
    """Carries the inputs deciders read: the state, merged settings, and the
    per-node disk usage map (`ClusterInfo` analog: node_id -> {"total_bytes",
    "free_bytes"}, fed by the master's stats collection or tests)."""

    def __init__(self, state: ClusterState,
                 cluster_info: Optional[Dict[str, dict]] = None):
        self.state = state
        self.settings = state.settings
        self.cluster_info = cluster_info or {}

    def setting(self, key: str, default=None):
        return self.settings.get(key, default)

    def index_setting(self, index: str, key: str, default=None):
        meta = self.state.metadata.get(index) or {}
        return (meta.get("settings") or {}).get(key, default)

    def copies_of(self, index: str, shard: int) -> List[ShardRoutingEntry]:
        return [r for r in self.state.routing
                if r.index == index and r.shard == shard]


class AllocationDecider:
    name = "base"

    def can_allocate(self, entry: ShardRoutingEntry, node_id: str,
                     ctx: AllocationContext) -> str:
        return YES

    def can_remain(self, entry: ShardRoutingEntry, node_id: str,
                   ctx: AllocationContext) -> str:
        return YES

    def can_rebalance(self, ctx: AllocationContext) -> str:
        return YES


class SameShardDecider(AllocationDecider):
    """Never two copies of one shard on a node (`SameShardAllocationDecider`)."""
    name = "same_shard"

    def can_allocate(self, entry, node_id, ctx):
        for r in ctx.copies_of(entry.index, entry.shard):
            if r.node_id == node_id and r.allocation_id != entry.allocation_id:
                return NO
        return YES


class EnableDecider(AllocationDecider):
    """cluster.routing.allocation.enable = all|primaries|new_primaries|none;
    cluster.routing.rebalance.enable = all|none (`EnableAllocationDecider`)."""
    name = "enable"

    def can_allocate(self, entry, node_id, ctx):
        mode = str(ctx.setting("cluster.routing.allocation.enable", "all"))
        if mode == "all":
            return YES
        if mode == "none":
            return NO
        if mode in ("primaries", "new_primaries"):
            return YES if entry.primary else NO
        return YES

    def can_rebalance(self, ctx):
        mode = str(ctx.setting("cluster.routing.rebalance.enable", "all"))
        return YES if mode == "all" else NO


def _node_attr(ctx: AllocationContext, node_id: str, attr: str) -> Optional[str]:
    node = ctx.state.nodes.get(node_id)
    if node is None:
        return None
    if attr == "_name":
        return node.name
    if attr == "_id":
        return node.node_id
    return node.attributes.get(attr)


def _matches(value: Optional[str], patterns: str) -> bool:
    if value is None:
        return False
    for pat in str(patterns).split(","):
        pat = pat.strip()
        if not pat:
            continue
        if pat == value or (pat.endswith("*") and value.startswith(pat[:-1])):
            return True
    return False


class FilterDecider(AllocationDecider):
    """include/exclude/require filters at cluster and index scope
    (`FilterAllocationDecider`). can_remain enforces exclusions so changing
    a filter drains shards off the excluded nodes."""
    name = "filter"

    _SCOPES = ("include", "exclude", "require")

    def _filters(self, ctx, index):
        out = []  # (scope, attr, patterns)
        for key, val in ctx.settings.items():
            for scope in self._SCOPES:
                prefix = f"cluster.routing.allocation.{scope}."
                if key.startswith(prefix):
                    out.append((scope, key[len(prefix):], val))
        meta = (ctx.state.metadata.get(index) or {}).get("settings") or {}
        for key, val in meta.items():
            for scope in self._SCOPES:
                prefix = f"index.routing.allocation.{scope}."
                if key.startswith(prefix):
                    out.append((scope, key[len(prefix):], val))
        return out

    def _decide(self, entry, node_id, ctx):
        for scope, attr, patterns in self._filters(ctx, entry.index):
            value = _node_attr(ctx, node_id, attr)
            hit = _matches(value, patterns)
            if scope == "exclude" and hit:
                return NO
            if scope == "require" and not hit:
                return NO
            if scope == "include" and not hit:
                return NO
        return YES

    can_allocate = _decide
    can_remain = _decide


class DiskThresholdDecider(AllocationDecider):
    """Low watermark gates new shards; high watermark forces shards off the
    node (`DiskThresholdDecider`). Watermarks accept "85%" or byte counts."""
    name = "disk_threshold"

    def _used_fraction(self, ctx, node_id) -> Optional[float]:
        info = ctx.cluster_info.get(node_id)
        if not info or not info.get("total_bytes"):
            return None
        return 1.0 - info.get("free_bytes", 0) / info["total_bytes"]

    @staticmethod
    def parse_watermark(raw: str, setting: str = ""):
        """("ratio", used_fraction) for "85%" / "0.85", ("bytes", min_free)
        for "10gb" (reference: DiskThresholdSettings / RatioValue)."""
        s = str(raw).strip()
        if s.endswith("%"):
            return ("ratio", float(s[:-1]) / 100.0)
        try:
            f = float(s)
        except ValueError:
            f = None
        if f is not None and 0.0 <= f <= 1.0 and not s.isdigit():
            # a bare fraction like "0.85"; bare integers ("0", "1",
            # "10737418240") keep their historical byte-count meaning
            return ("ratio", f)
        from elasticsearch_tpu.common.settings import parse_byte_size
        return ("bytes", parse_byte_size(s, setting))

    def _exceeds(self, ctx, node_id, watermark: str, default: str) -> bool:
        raw = str(ctx.setting(watermark, default))
        info = ctx.cluster_info.get(node_id)
        if info is None:
            return False
        try:
            kind, value = self.parse_watermark(raw, watermark)
        except Exception:
            # an unparseable operator value must not melt the cluster or
            # silently disable protection: fall back to the default gate
            kind, value = self.parse_watermark(default, watermark)
        if kind == "ratio":
            frac = self._used_fraction(ctx, node_id)
            return frac is not None and frac >= value
        return info.get("free_bytes", 0) <= value

    def can_allocate(self, entry, node_id, ctx):
        if self._exceeds(ctx, node_id,
                         "cluster.routing.allocation.disk.watermark.low", "85%"):
            return NO
        return YES

    def can_remain(self, entry, node_id, ctx):
        if self._exceeds(ctx, node_id,
                         "cluster.routing.allocation.disk.watermark.high", "90%"):
            return NO
        return YES


class ThrottlingDecider(AllocationDecider):
    """Caps concurrent incoming recoveries per node
    (`ThrottlingAllocationDecider`, node_concurrent_recoveries default 2)."""
    name = "throttling"

    def can_allocate(self, entry, node_id, ctx):
        limit = int(ctx.setting(
            "cluster.routing.allocation.node_concurrent_recoveries", 2))
        initializing = sum(
            1 for r in ctx.state.routing
            if r.node_id == node_id and r.state == ShardRoutingEntry.INITIALIZING
            and r.allocation_id != entry.allocation_id)
        return THROTTLE if initializing >= limit else YES


class AwarenessDecider(AllocationDecider):
    """Spread copies of a shard across values of the awareness attributes
    (`AwarenessAllocationDecider`): a node may hold at most
    ceil(copies / distinct_values) copies for each attribute."""
    name = "awareness"

    def can_allocate(self, entry, node_id, ctx):
        attrs = ctx.setting("cluster.routing.allocation.awareness.attributes")
        if not attrs:
            return YES
        if isinstance(attrs, str):
            attrs = [a.strip() for a in attrs.split(",") if a.strip()]
        copies = ctx.copies_of(entry.index, entry.shard)
        n_copies = len(copies)
        for attr in attrs:
            values = {_node_attr(ctx, nid, attr)
                      for nid in _data_nodes(ctx.state)}
            values.discard(None)
            if not values:
                continue
            my_value = _node_attr(ctx, node_id, attr)
            per_value_cap = -(-n_copies // len(values))  # ceil
            same = sum(1 for r in copies
                       if r.node_id and r.allocation_id != entry.allocation_id
                       and _node_attr(ctx, r.node_id, attr) == my_value)
            if same + 1 > per_value_cap:
                return NO
        return YES


class ShardsLimitDecider(AllocationDecider):
    """index.routing.allocation.total_shards_per_node
    (`ShardsLimitAllocationDecider`)."""
    name = "shards_limit"

    def can_allocate(self, entry, node_id, ctx):
        limit = ctx.index_setting(entry.index,
                                  "index.routing.allocation.total_shards_per_node")
        if limit in (None, -1, "-1"):
            return YES
        count = sum(1 for r in ctx.state.routing
                    if r.index == entry.index and r.node_id == node_id
                    and r.allocation_id != entry.allocation_id)
        return NO if count >= int(limit) else YES


DEFAULT_DECIDERS: List[AllocationDecider] = [
    SameShardDecider(), EnableDecider(), FilterDecider(),
    DiskThresholdDecider(), ThrottlingDecider(), AwarenessDecider(),
    ShardsLimitDecider(),
]


def decide_allocate(entry: ShardRoutingEntry, node_id: str,
                    ctx: AllocationContext,
                    deciders: Optional[List[AllocationDecider]] = None) -> str:
    """Chain verdict: NO wins over THROTTLE wins over YES (`Decision.java`)."""
    verdict = YES
    for d in (deciders or DEFAULT_DECIDERS):
        v = d.can_allocate(entry, node_id, ctx)
        if v == NO:
            return NO
        if v == THROTTLE:
            verdict = THROTTLE
    return verdict


def decide_remain(entry: ShardRoutingEntry, node_id: str,
                  ctx: AllocationContext,
                  deciders: Optional[List[AllocationDecider]] = None) -> str:
    for d in (deciders or DEFAULT_DECIDERS):
        if d.can_remain(entry, node_id, ctx) == NO:
            return NO
    return YES


# --------------------------------------------------------------------------
# balancer weight (BalancedShardsAllocator.WeightFunction)
# --------------------------------------------------------------------------

def _weights(state: ClusterState, index: str) -> Dict[str, float]:
    """weight(node) for placing a copy of `index`: lower = preferred."""
    theta_shard = float(state.settings.get(
        "cluster.routing.allocation.balance.shard", 0.45))
    theta_index = float(state.settings.get(
        "cluster.routing.allocation.balance.index", 0.55))
    nodes = _data_nodes(state)
    if not nodes:
        return {}
    totals = {n: 0 for n in nodes}
    per_index = {n: 0 for n in nodes}
    for r in state.routing:
        # weigh shards by where they will END UP: a RELOCATING source is
        # leaving its node (its target copy is already counted), so counting
        # it would double-weigh in-flight moves and stall convergence
        if r.node_id in totals and r.state not in (
                ShardRoutingEntry.UNASSIGNED, ShardRoutingEntry.RELOCATING):
            totals[r.node_id] += 1
            if r.index == index:
                per_index[r.node_id] += 1
    avg_total = sum(totals.values()) / len(nodes)
    avg_index = sum(per_index.values()) / len(nodes)
    return {n: theta_shard * (totals[n] - avg_total)
            + theta_index * (per_index[n] - avg_index)
            for n in nodes}


def _pick_node(entry: ShardRoutingEntry, ctx: AllocationContext,
               exclude: Set[str]) -> Optional[str]:
    """Lowest-weight node the decider chain allows (THROTTLE defers:
    reroute() runs again on the next state change). Weight ties break on
    the unified dispatch cost model (serving/router.py) — a new copy
    lands on the less-loaded of two equally-balanced nodes — and then on
    node name, so allocation with no serving traffic stays the
    historical deterministic order."""
    from elasticsearch_tpu.serving import router as dispatch_router
    weights = _weights(ctx.state, entry.index)
    candidates = dispatch_router.placement_order(
        (w, n) for n, w in weights.items() if n not in exclude)
    for _, node in candidates:
        if decide_allocate(entry, node, ctx) == YES:
            return node
    return None


def allocate_new_index(state: ClusterState, index: str, num_shards: int,
                       num_replicas: int) -> ClusterState:
    """Create UNASSIGNED entries for a new index's shards; reroute assigns
    them through the decider chain. A brand-new shard's in-sync set is empty,
    which is exactly what licenses allocating its primary to any node (no
    data exists yet to lose)."""
    routing = list(state.routing)
    isa = dict(state.in_sync_allocations)
    for shard in range(num_shards):
        routing.append(ShardRoutingEntry(
            index, shard, True, None, ShardRoutingEntry.UNASSIGNED,
            _new_allocation_id(index, shard)))
        for _ in range(num_replicas):
            routing.append(ShardRoutingEntry(
                index, shard, False, None, ShardRoutingEntry.UNASSIGNED,
                _new_allocation_id(index, shard)))
        isa[(index, shard)] = set()
    state = state.with_(routing=routing, in_sync_allocations=isa)
    return reroute(state)


def remove_index(state: ClusterState, index: str) -> ClusterState:
    return state.with_(
        routing=[r for r in state.routing if r.index != index],
        in_sync_allocations={k: v for k, v in state.in_sync_allocations.items()
                             if k[0] != index},
        metadata={k: v for k, v in state.metadata.items() if k != index})


def shard_started(state: ClusterState, allocation_id: str) -> ClusterState:
    routing = []
    isa = dict(state.in_sync_allocations)
    started: Optional[ShardRoutingEntry] = None
    for r in state.routing:
        if r.allocation_id == allocation_id and r.state == ShardRoutingEntry.INITIALIZING:
            r = r.copy(state=ShardRoutingEntry.STARTED)
            started = r
            key = (r.index, r.shard)
            isa[key] = set(isa.get(key, set())) | {allocation_id}
        routing.append(r)

    if started is not None and started.relocation_source is not None:
        # relocation handoff: drop the RELOCATING source; the target takes
        # over the source's primary flag (ShardRouting.moveToStarted +
        # RoutingNodes.relocationCompleted analog)
        source = next((r for r in routing
                       if r.allocation_id == started.relocation_source), None)
        if source is not None:
            routing = [r for r in routing
                       if r.allocation_id != source.allocation_id]
            key = (source.index, source.shard)
            isa[key] = set(isa.get(key, set())) - {source.allocation_id}
            for i, r in enumerate(routing):
                if r.allocation_id == allocation_id:
                    routing[i] = r.copy(primary=source.primary,
                                        relocation_source=None)
    # a completed recovery frees a throttling slot: reroute drains any
    # copies the ThrottlingDecider deferred (reference: every shard-started
    # task runs AllocationService.reroute)
    state = reroute(state.with_(routing=routing, in_sync_allocations=isa))
    if started is not None and started.relocation_source is not None:
        # a finished relocation may unblock the next balancing move
        # (throttling limits how many run concurrently)
        state = rebalance(state)
    return state


def shard_failed(state: ClusterState, allocation_id: str) -> ClusterState:
    """Fail one copy: primaries promote an in-sync replica; the failed copy
    is reallocated if a node is free (`ReplicationOperation` failure path +
    `AllocationService.applyFailedShards`)."""
    failed = next((r for r in state.routing if r.allocation_id == allocation_id), None)
    if failed is None:
        return state
    return _handle_copy_loss(state, [failed])


def node_left(state: ClusterState, node_id: str) -> ClusterState:
    lost = [r for r in state.routing if r.node_id == node_id]
    if not lost:
        return state
    return _handle_copy_loss(state, lost)


def _handle_copy_loss(state: ClusterState, lost: List[ShardRoutingEntry]) -> ClusterState:
    lost_ids = {r.allocation_id for r in lost}
    routing = [r for r in state.routing if r.allocation_id not in lost_ids]
    isa = {k: set(v) for k, v in state.in_sync_allocations.items()}

    for r in lost:
        key = (r.index, r.shard)
        if not r.primary:
            isa.get(key, set()).discard(r.allocation_id)
        else:
            # promote an in-sync STARTED replica (reference: primary failover
            # only from the in-sync set — data-loss safety)
            promoted = False
            for i, cand in enumerate(routing):
                if (cand.index, cand.shard) == key and not cand.primary \
                        and cand.state == ShardRoutingEntry.STARTED \
                        and cand.allocation_id in isa.get(key, set()):
                    routing[i] = cand.copy(primary=True)
                    promoted = True
                    break
            if promoted:
                isa.get(key, set()).discard(r.allocation_id)
            else:
                # no safe copy: shard red. KEEP the lost primary's id in the
                # in-sync set — a non-empty in-sync set is what stops
                # reroute() from fabricating an empty primary on another
                # node (silent data loss); the shard stays red until the
                # holder returns or an operator forces allocation
                routing.append(ShardRoutingEntry(
                    r.index, r.shard, True, None, ShardRoutingEntry.UNASSIGNED,
                    _new_allocation_id(r.index, r.shard)))

    # cancelled relocations: a RELOCATING source whose target copy died
    # reverts to STARTED; a target whose source died becomes a plain
    # initializing copy (RoutingNodes.cancelRelocation analog)
    alive_targets = {r.relocation_source for r in routing if r.relocation_source}
    alive_ids = {r.allocation_id for r in routing}
    for i, r in enumerate(routing):
        if r.state == ShardRoutingEntry.RELOCATING \
                and r.allocation_id not in alive_targets:
            routing[i] = r.copy(state=ShardRoutingEntry.STARTED)
        elif r.relocation_source and r.relocation_source not in alive_ids:
            routing[i] = r.copy(relocation_source=None)

    state = state.with_(routing=routing, in_sync_allocations=isa)
    return reroute(state)


def reroute(state: ClusterState,
            cluster_info: Optional[Dict[str, dict]] = None) -> ClusterState:
    """Allocate unassigned copies and top up missing replicas
    (`AllocationService.reroute`), through the decider chain. THROTTLEd
    copies stay UNASSIGNED; reroute runs again on every shard-started /
    membership state change, so they allocate as recoveries drain.

    An unassigned primary allocates ONLY when its in-sync set is empty
    (never-started shard: no data exists anywhere). Assigning a primary
    whose in-sync copies are all lost would fabricate an empty shard —
    silent data loss — so such shards stay red until an operator forces
    allocation (reference: primaries allocate only to in-sync copy holders;
    allocate_empty_primary is an explicit dangerous command)."""
    work = list(state.routing)

    def ctx_now() -> AllocationContext:
        return AllocationContext(state.with_(routing=work), cluster_info)

    by_shard: Dict[Tuple[str, int], List[int]] = {}
    for i, r in enumerate(work):
        by_shard.setdefault((r.index, r.shard), []).append(i)

    for key in sorted(by_shard):
        index, shard = key
        desired_replicas = int(state.metadata.get(index, {}).get(
            "settings", {}).get("index.number_of_replicas", 1))
        idxs = by_shard[key]
        occupied = {work[i].node_id for i in idxs if work[i].node_id}
        for i in idxs:
            r = work[i]
            if r.state != ShardRoutingEntry.UNASSIGNED or r.node_id is not None:
                continue
            if r.primary and state.in_sync_allocations.get(key):
                continue
            node = _pick_node(r, ctx_now(), occupied)
            if node is not None:
                work[i] = r.copy(node=node,
                                 state=ShardRoutingEntry.INITIALIZING)
                occupied.add(node)
        # top up replicas only when a live primary exists to recover from
        group = [work[i] for i in idxs]
        has_active_primary = any(
            r.primary and r.node_id and r.state != ShardRoutingEntry.UNASSIGNED
            for r in group)
        replica_count = sum(1 for r in group if not r.primary)
        while has_active_primary and replica_count < desired_replicas:
            probe = ShardRoutingEntry(index, shard, False, None,
                                      ShardRoutingEntry.UNASSIGNED,
                                      _new_allocation_id(index, shard))
            node = _pick_node(probe, ctx_now(), occupied)
            if node is None:
                break
            work.append(probe.copy(node=node,
                                   state=ShardRoutingEntry.INITIALIZING))
            occupied.add(node)
            replica_count += 1

    # deterministic grouped order
    work.sort(key=lambda r: (r.index, r.shard, not r.primary, r.allocation_id))
    return state.with_(routing=work)


def rebalance(state: ClusterState,
              cluster_info: Optional[Dict[str, dict]] = None) -> ClusterState:
    """Weight-driven shard movement (`BalancedShardsAllocator.balance()`):
    while the heaviest/lightest weight delta exceeds the threshold, relocate
    one STARTED shard from the heaviest node to the lightest node the
    deciders allow. Also drains shards whose can_remain is NO (disk high
    watermark, filter exclusions) regardless of balance
    (`AllocationService.shardsWithState` move pass)."""
    ctx = AllocationContext(state, cluster_info)
    if any(d.can_rebalance(ctx) == NO for d in DEFAULT_DECIDERS):
        return _move_shards_that_cannot_remain(state, cluster_info)

    threshold = float(state.settings.get(
        "cluster.routing.allocation.balance.threshold", 1.0))
    moved = True
    while moved:
        moved = False
        ctx = AllocationContext(state, cluster_info)
        # consider each index's weight surface independently (reference
        # balances index-by-index)
        for index in sorted({r.index for r in state.routing}):
            weights = _weights(state, index)
            if len(weights) < 2:
                continue
            heavy = max(weights, key=lambda n: (weights[n], n))
            light = min(weights, key=lambda n: (weights[n], n))
            if weights[heavy] - weights[light] <= threshold:
                continue
            movable = [r for r in state.routing
                       if r.node_id == heavy and r.index == index
                       and r.state == ShardRoutingEntry.STARTED]
            for r in movable:
                target = ShardRoutingEntry(
                    r.index, r.shard, False, light,
                    ShardRoutingEntry.UNASSIGNED,
                    _new_allocation_id(r.index, r.shard),
                    relocation_source=r.allocation_id)
                if decide_allocate(target, light, ctx) != YES:
                    continue
                state = _start_relocation(state, r, light, target.allocation_id)
                moved = True
                break
            if moved:
                break
    return _move_shards_that_cannot_remain(state, cluster_info)


def _start_relocation(state: ClusterState, source: ShardRoutingEntry,
                      target_node: str, target_alloc: str) -> ClusterState:
    routing = []
    for r in state.routing:
        if r.allocation_id == source.allocation_id:
            routing.append(r.copy(state=ShardRoutingEntry.RELOCATING))
        else:
            routing.append(r)
    routing.append(ShardRoutingEntry(
        source.index, source.shard, False, target_node,
        ShardRoutingEntry.INITIALIZING, target_alloc,
        relocation_source=source.allocation_id))
    return state.with_(routing=routing)


def _move_shards_that_cannot_remain(
        state: ClusterState,
        cluster_info: Optional[Dict[str, dict]] = None) -> ClusterState:
    ctx = AllocationContext(state, cluster_info)
    for r in list(state.routing):
        if r.state != ShardRoutingEntry.STARTED or r.node_id is None:
            continue
        if decide_remain(r, r.node_id, ctx) == YES:
            continue
        occupied = {c.node_id for c in ctx.copies_of(r.index, r.shard)
                    if c.node_id}
        probe = ShardRoutingEntry(r.index, r.shard, False, None,
                                  ShardRoutingEntry.UNASSIGNED,
                                  _new_allocation_id(r.index, r.shard),
                                  relocation_source=r.allocation_id)
        target = _pick_node(probe, ctx, occupied)
        if target is None:
            continue
        state = _start_relocation(state, r, target, probe.allocation_id)
        ctx = AllocationContext(state, cluster_info)
    return state
