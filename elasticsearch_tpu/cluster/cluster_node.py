"""A full cluster-aware node: coordination + shard lifecycle + replication +
distributed search.

This composes the layers the reference wires in `node/Node.java`:

- `IndicesClusterStateService.applyClusterState` (reference `:210`): on every
  committed cluster state, diff the routing table against local shards —
  create INITIALIZING copies assigned here (primaries activate the
  replication tracker; replicas run ops-based peer recovery from the
  primary), promote on failover, remove unassigned copies.
- `TransportReplicationAction` / `ReplicationOperation` (§3.3): writes route
  to the primary, execute under the primary term, fan out to in-sync replica
  copies, and acknowledge when all copies respond; a failed copy is reported
  to the master (`shard_failed`) which reroutes.
- Peer recovery (§3.5): ops-based phase 2 from the primary's translog when
  retention covers the gap; otherwise phase 1 copies the primary's commit
  files in CRC-framed chunks under a retention lease
  (`RecoverySourceHandler.java:262,274,290`).
- Two-phase scatter-gather search (§3.2): the coordinating node fans
  QUERY-phase requests (rows+scores+sort+partial aggs only) to the
  latency-ranked copy of each shard, folds responses through a streaming
  bounded reduce, then FETCH round-trips for the global window's documents
  — the host-RPC analog of the compiled ICI merge in
  `parallel/sharded_knn.py`.

Transport/scheduler are injected (same API as testing.deterministic), so the
whole stack runs under the deterministic simulator or a real asyncio TCP
transport unchanged.
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster import allocation
from elasticsearch_tpu.cluster.coordination import (
    FOLLOWER, LEADER, Coordinator,
)
from elasticsearch_tpu.cluster.gateway import FilePersistedState
from elasticsearch_tpu.cluster.routing import shard_id_for
from elasticsearch_tpu.cluster.state import (
    ClusterState, DiscoveryNode, ShardRoutingEntry,
)
from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, IndexNotFoundError, SearchContextMissingError,
    SearchEngineError,
)
from elasticsearch_tpu.common.threadpool import EsRejectedExecutionError
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.serving import fanout as fanout_lib
from elasticsearch_tpu.serving.fanout import ScatterGather
from elasticsearch_tpu.telemetry import trace as telemetry_trace
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.index.seqno import ReplicationTracker
from elasticsearch_tpu.search.service import (
    execute_fetch_phase, execute_query_phase,
)
from elasticsearch_tpu.vectors.store import VectorStoreShard

# transport actions (reference: action names in TransportService registry)
WRITE_PRIMARY = "indices:data/write/primary"
WRITE_REPLICA = "indices:data/write/replica"
QUERY_SHARD = "indices:data/read/query"
FETCH_SHARD = "indices:data/read/fetch"
CAN_MATCH_SHARD = "indices:data/read/search[can_match]"
SCROLL_CREATE = "indices:data/read/scroll[create]"
SCROLL_FETCH = "indices:data/read/scroll[fetch]"
SCROLL_FREE = "indices:data/read/scroll[free]"
SCROLL_NEXT = "indices:data/read/scroll[next]"
SCROLL_CLEAR = "indices:data/read/scroll[clear]"
SCROLL_CLEAR_ALL = "indices:data/read/scroll[clear_all]"
RECOVERY_START = "internal:index/shard/recovery/start_recovery"
RECOVERY_FILE_CHUNK = "internal:index/shard/recovery/file_chunk"
NODES_DISPATCH = "cluster:monitor/nodes/dispatch"
MASTER_CREATE_INDEX = "cluster:admin/indices/create"
MASTER_DELETE_INDEX = "cluster:admin/indices/delete"
MASTER_SHARD_STARTED = "internal:cluster/shard/started"
MASTER_SHARD_FAILED = "internal:cluster/shard/failure"
MASTER_UPDATE_SETTINGS = "cluster:admin/settings/update"
MASTER_PUT_REGISTRY = "cluster:admin/registry/update"
MASTER_PUT_PERSISTENT_TASK = "cluster:admin/persistent/update"

# cluster-state metadata key for persistent background tasks (the
# reference's PersistentTasksCustomMetaData): task_id -> {params,
# interval_ms, assigned_node} — the master assigns each task to exactly
# one live node and reassigns on node-leave
PERSISTENT_TASKS_KEY = "__persistent_tasks__"

# cluster-state metadata key for replicated registries (ingest pipelines,
# templates, stored scripts — the reference stores these in MetaData customs:
# IngestMetadata / IndexTemplateMetaData / ScriptMetaData). Index names may
# not start with "_", so the key cannot collide.
REGISTRIES_KEY = "_registries"


class LocalShard:
    def __init__(self, routing: ShardRoutingEntry, engine: Engine,
                 mapper_service: MapperService, index_settings=None):
        self.routing = routing
        self.mapper_service = mapper_service
        self.tracker = ReplicationTracker(routing.allocation_id)
        s = index_settings or {}
        try:
            from elasticsearch_tpu.indices.service import (
                validate_knn_settings)
            knn_engine, knn_nlist, knn_nprobe = validate_knn_settings(s)
        except Exception:
            # settings are validated at create-index; a bad value that
            # slipped into replicated state (older master) must degrade
            # to the exhaustive default, never crash the state applier
            knn_engine, knn_nlist, knn_nprobe = "tpu", None, "auto"
        from elasticsearch_tpu.common.settings import setting_bool
        try:
            from elasticsearch_tpu.indices.service import (
                validate_segments_settings)
            segments_settings = validate_segments_settings(s)
        except Exception:
            # same degradation contract as the knn settings above: a bad
            # replicated value must not crash the state applier
            segments_settings = {}
        try:
            from elasticsearch_tpu.indices.service import (
                validate_semantic_cache_settings)
            semantic_cache_settings = validate_semantic_cache_settings(s)
        except Exception:
            # same degradation contract: bad replicated value -> feature
            # stays off, never crash the state applier
            semantic_cache_settings = {}
        # lightweight-shard materialization (lazy device store): the
        # VectorStoreShard — batcher threads, device mirrors, IVF state —
        # is only built when the index actually has vector fields or a
        # recovery seed to apply. A text-only shard on a 3-node cluster
        # costs a bare engine, not 3x device-store setup.
        self._vector_store: Optional[VectorStoreShard] = None
        self._vector_store_kwargs = dict(
            dtype=s.get("index.knn.vector_dtype", "bf16"),
            knn_engine=knn_engine, knn_nlist=knn_nlist,
            knn_nprobe=knn_nprobe,
            topup=setting_bool(s.get("index.knn.topup", True)),
            target_batch_latency_ms=float(
                s.get("index.knn.target_batch_latency_ms", 2.0)),
            async_depth=int(s.get("index.knn.async_depth", 2)),
            **segments_settings, **semantic_cache_settings)
        self._attach_engine(engine)

    @property
    def vector_store(self) -> VectorStoreShard:
        if self._vector_store is None:
            self._vector_store = VectorStoreShard(
                **self._vector_store_kwargs)
        return self._vector_store

    def _attach_engine(self, engine: Engine) -> None:
        self.engine = engine
        engine.retained_seq_no_provider = self._min_retained_seq_no
        # restored/recovered engines carry a seed sidecar (columnar
        # blocks + IVF layout); apply it BEFORE the first vector sync so
        # block recovery never re-encodes or re-trains (recovery/seed.py)
        from elasticsearch_tpu.recovery import seed as recovery_seed
        if (self.mapper_service.vector_fields()
                or recovery_seed.has_sidecar(engine.path)):
            recovery_seed.maybe_apply(engine, self.vector_store)
        engine.add_refresh_listener(self._sync_vectors)
        self._sync_vectors(engine.acquire_searcher())

    def _min_retained_seq_no(self) -> int:
        try:
            return self.tracker.min_retained_seq_no()
        except Exception:
            return self.engine.local_checkpoint + 1

    def replace_engine(self, engine: Engine) -> None:
        """Swap in a recovered engine (post phase-1 file copy)."""
        self._attach_engine(engine)

    def _sync_vectors(self, reader):
        vf = self.mapper_service.vector_fields()
        if vf:
            self.vector_store.sync(reader, vf)

    def active_vector_store(self) -> Optional[VectorStoreShard]:
        """The device store when this shard serves vectors; None for a
        text-only shard, so the query path never materializes the lazy
        store just to ignore it."""
        if self._vector_store is not None:
            return self._vector_store
        return self.vector_store if self.mapper_service.vector_fields() \
            else None


class ClusterNode:
    def __init__(self, node_id: str, data_path: str, transport, scheduler,
                 seed_peers: List[str], initial_state: ClusterState,
                 rng=None, address: str = "",
                 attributes: Optional[Dict[str, str]] = None,
                 roles: Optional[Set[str]] = None):
        self.node_id = node_id
        self.data_path = data_path
        self.transport = transport
        self.scheduler = scheduler
        self.local_shards: Dict[Tuple[str, int], LocalShard] = {}
        # pinned per-shard scroll reader contexts (data side) and merged
        # scroll cursors (coordinator side)
        self._shard_scrolls: Dict[str, dict] = {}
        self._client_scrolls: Dict[str, dict] = {}
        # persistent-task execution (PersistentTasksExecutor registry):
        # task_id -> tick callable, supplied by the composition root
        self.persistent_task_executors: Dict[str, Callable[[], None]] = {}
        # generic routed-action layer (TransportNodesAction analog): named
        # local collectors the REST layer registers; NODES_DISPATCH fans a
        # named op out to every node and merges per-node sections
        self.node_collectors: Dict[str, Callable[[dict], Any]] = {}
        self.dispatch_executor: Optional[Callable[[Callable], Any]] = None
        self._running_ptasks: Set[str] = set()
        self.mappers: Dict[str, MapperService] = {}
        from elasticsearch_tpu.search.caches import NodeCaches
        self.caches = NodeCaches()
        # observers of every applied cluster state (registry sync, etc.)
        self.state_listeners: List[Callable[[ClusterState], None]] = []
        # cross-node serving counters (serving/fanout.py): coordinator-side
        # per-phase fan-out accounting + data-plane remote-shed tallies;
        # surfaced through `_nodes/stats fanout` and `profile.fanout`
        self.fanout_stats = fanout_lib.FanoutStats()
        # unified dispatch cost router (serving/router.py): queue wait +
        # transport RTT EWMA + device-leg estimate per candidate route.
        # The RTT feed exists only on the TCP transport; the sim
        # transport's cost collapses to the classic ARS ranking.
        from elasticsearch_tpu.serving import router as router_lib
        self._router = router_lib.DispatchRouter(
            node_id, rtt_provider=getattr(transport, "rtt_ms", None))
        # ARS back-compat alias: tests and the bench harness read/pop
        # this dict directly — it IS the router's service-time EWMA table
        self._ars_ewma = self._router.service_ewma
        # roles gate allocation: a coordinating-only node (no "data")
        # never receives shard copies — the multi-process bench joins its
        # in-parent coordinator this way so every search leg is remote
        node = DiscoveryNode(node_id, address=address, roles=roles,
                             attributes=attributes)
        # durable gateway: term + last-accepted state survive full-cluster
        # restarts (PersistedClusterStateService/GatewayMetaState analog);
        # initial_state seeds only a never-booted node
        persisted = FilePersistedState(data_path, initial_state=initial_state)
        self.coordinator = Coordinator(
            node, persisted, transport, scheduler,
            seed_peers=seed_peers, on_committed=self.apply_cluster_state, rng=rng)
        self.coordinator.membership_listener = self._on_membership_change
        self._register_handlers()
        # cluster-state-driven snapshot/restore lifecycle (SnapshotsService/
        # SnapshotShardsService/RestoreService analogs); data-plane hooks
        # are installed by the REST layer
        from elasticsearch_tpu.cluster.snapshots import (
            ClusterSnapshotLifecycle)
        self.snapshot_lifecycle = ClusterSnapshotLifecycle(self)
        self.shard_restore_hook: Optional[Callable] = None
        # durable elasticity (recovery/): node-local content-addressed
        # block cache — peer recoveries diff the source manifest against
        # it, so retries resume from the last acked block and a restored
        # shard's blocks never re-ship
        from elasticsearch_tpu.recovery.peer import BlockCache
        self.block_cache = BlockCache(os.path.join(data_path, "_blocks"))
        # per-recovery progress (allocation_id -> recovery/progress.py
        # dict, kept after completion for `_cat/recovery`) + lifetime
        # retry counters for `_nodes/stats indices.recovery`
        self.recoveries: Dict[str, dict] = {}
        self.recovery_stats = {"attempts": 0, "retries": 0,
                               "giveups": 0, "completed": 0}
        self._recovery_attempts: Dict[str, int] = {}
        self._recovery_pending: Set[str] = set()
        self._recovery_sources: Set[str] = set()

    # ------------------------------------------------------------------ admin
    def start(self):
        self.coordinator.start()
        self._schedule_scroll_reaper()

    def _schedule_scroll_reaper(self):
        """Periodic keepalive reaper for abandoned scroll contexts
        (reference: SearchService's KEEPALIVE_INTERVAL Reaper job)."""
        def tick():
            self._reap_shard_scrolls()
            now = time.time()
            for sid in [s for s, st in self._client_scrolls.items()
                        if st["expiry"] < now]:
                self._client_scrolls.pop(sid, None)
            self._schedule_scroll_reaper()
        try:
            self.scheduler.schedule_in(60_000, tick, "scroll_reaper")
        except Exception:
            pass  # deterministic test schedulers may be closed

    def stop(self):
        self.coordinator.stop()
        for shard in self.local_shards.values():
            shard.engine.close()

    @property
    def cluster_state(self) -> ClusterState:
        return self.coordinator.committed_state

    @property
    def is_master(self) -> bool:
        return self.coordinator.mode == LEADER

    # ------------------------------------------------- master-side state tasks
    def _on_membership_change(self, state: ClusterState, added: Set[str],
                              removed: Set[str]) -> ClusterState:
        for nid in sorted(removed):  # deterministic under any hash seed
            state = allocation.node_left(state, nid)
        if added:
            state = allocation.reroute(state)
            # a fresh node is empty: move shards onto it until node weights
            # converge (BalancedShardsAllocator.balance on reroute)
            state = allocation.rebalance(state)
        # persistent tasks on departed nodes reassign immediately
        # (PersistentTasksClusterService.shouldReassignPersistentTasks)
        state = self._reassign_persistent_tasks(state)
        return state

    @staticmethod
    def _reassign_persistent_tasks(state: ClusterState) -> ClusterState:
        tasks = state.metadata.get(PERSISTENT_TASKS_KEY)
        if not tasks:
            return state
        live = sorted(state.nodes)
        if not live:
            return state
        loads = {n: 0 for n in live}
        for t in tasks.values():
            if t.get("assigned_node") in loads:
                loads[t["assigned_node"]] += 1
        changed = False
        new_tasks = {}
        for tid in sorted(tasks):
            t = dict(tasks[tid])
            if t.get("assigned_node") not in loads:
                target = min(live, key=lambda n: (loads[n], n))
                t["assigned_node"] = target
                loads[target] += 1
                changed = True
            new_tasks[tid] = t
        if not changed:
            return state
        return state.with_(metadata={**state.metadata,
                                     PERSISTENT_TASKS_KEY: new_tasks})

    def _master_put_persistent_task(self, sender, request, respond):
        self._require_master()
        tid = request["task_id"]

        def update(base: ClusterState) -> ClusterState:
            tasks = {k: dict(v) for k, v in
                     (base.metadata.get(PERSISTENT_TASKS_KEY) or {}).items()}
            if request.get("remove"):
                if tid not in tasks:
                    return base
                tasks.pop(tid)
            else:
                if tid in tasks:
                    return base  # idempotent registration
                tasks[tid] = {"params": request.get("params") or {},
                              "interval_ms": int(request.get(
                                  "interval_ms", 1000)),
                              "assigned_node": None}
            state = base.with_(metadata={**base.metadata,
                                         PERSISTENT_TASKS_KEY: tasks})
            return self._reassign_persistent_tasks(state)

        self._publish_then_respond(update, respond, {"acknowledged": True},
                                   source=f"persistent-task [{tid}]")

    def client_register_persistent_task(self, task_id: str,
                                        params: Optional[dict] = None,
                                        interval_ms: int = 1000,
                                        on_done: Optional[Callable] = None,
                                        on_failure: Optional[Callable] = None
                                        ) -> None:
        self._send_to_master(MASTER_PUT_PERSISTENT_TASK,
                             {"task_id": task_id, "params": params,
                              "interval_ms": interval_ms},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    def client_remove_persistent_task(self, task_id: str,
                                      on_done: Optional[Callable] = None,
                                      on_failure: Optional[Callable] = None
                                      ) -> None:
        self._send_to_master(MASTER_PUT_PERSISTENT_TASK,
                             {"task_id": task_id, "remove": True},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    # node-side execution: a ticker per task assigned to THIS node,
    # started/stopped as committed states change ownership
    def _sync_persistent_tasks(self, state: ClusterState) -> None:
        tasks = state.metadata.get(PERSISTENT_TASKS_KEY) or {}
        mine = {tid for tid, t in tasks.items()
                if t.get("assigned_node") == self.node_id
                and tid in self.persistent_task_executors}
        for tid in mine - self._running_ptasks:
            self._running_ptasks.add(tid)
            interval = int(tasks[tid].get("interval_ms", 1000))
            self._schedule_ptask_tick(tid, interval)
        # tasks no longer mine stop at their next tick check (the loop
        # discards itself from _running_ptasks there — removing here
        # could double-schedule on a fast unassign/reassign cycle)

    def _schedule_ptask_tick(self, tid: str, interval: int) -> None:
        def tick():
            tasks = self.cluster_state.metadata.get(
                PERSISTENT_TASKS_KEY) or {}
            t = tasks.get(tid)
            if t is None or t.get("assigned_node") != self.node_id \
                    or self.coordinator.stopped \
                    or self.node_id not in self.cluster_state.nodes:
                self._running_ptasks.discard(tid)
                return
            # partition guard: a node cut off from the master may hold a
            # stale assignment while a new owner starts; once fault
            # detection demotes this node to CANDIDATE it pauses execution
            # (keeps the loop) until it rejoins — bounding dual execution
            # to the detection window, like the reference's reassignment
            has_cluster = self.coordinator.mode in (LEADER, FOLLOWER)
            fn = self.persistent_task_executors.get(tid)
            if fn is not None and has_cluster:
                try:
                    fn()
                except Exception:
                    pass  # a failing feature tick must not kill the loop
            # interval is re-read so a remove + re-register with a new
            # cadence takes effect at the next tick
            self._schedule_ptask_tick(
                tid, int(t.get("interval_ms", interval)))
        self.scheduler.schedule_in(interval, tick,
                                   f"persistent_task:{tid}:{self.node_id}")

    def _require_master(self):
        if self.coordinator.mode != LEADER:
            # raising fails the transport call → sender's retry loop finds
            # the new master (reference: NotMasterException)
            raise SearchEngineError(f"[{self.node_id}] is not the elected master")

    def _master_create_index(self, sender, request, respond):
        self._require_master()
        name = request["index"]
        # same name rules as the single-node path — in particular no "_"
        # prefix, which is what keeps reserved metadata sections
        # (REGISTRIES_KEY) unreachable as indices
        from elasticsearch_tpu.indices.service import (
            IndicesService, validate_knn_settings,
            validate_semantic_cache_settings)
        IndicesService.validate_index_name(name)
        validate_knn_settings(dict(request.get("settings") or {}))
        validate_semantic_cache_settings(dict(request.get("settings") or {}))

        def update(base: ClusterState) -> ClusterState:
            if name in base.metadata:
                return base
            settings = dict(request.get("settings") or {})
            settings.setdefault("index.number_of_shards", 1)
            settings.setdefault("index.number_of_replicas", 1)
            meta = dict(base.metadata)
            meta[name] = {"settings": settings,
                          "mappings": request.get("mappings") or {"properties": {}}}
            state = base.with_(metadata=meta)
            return allocation.allocate_new_index(
                state, name, int(settings["index.number_of_shards"]),
                int(settings["index.number_of_replicas"]))

        self._publish_then_respond(update, respond, {"acknowledged": True},
                                   source=f"create-index [{name}]")

    def _publish_then_respond(self, update, respond, result: dict,
                              source: str = "cluster-state-update") -> None:
        """Ack only after COMMIT (MasterService publish listener): a stale
        leader's rejected publish must surface as a retryable non-ack, not
        a false acknowledged=true. Updates route through the batching task
        queue, so concurrent submissions coalesce into one publication."""
        def on_committed(ok: bool):
            respond(result if ok else {"__not_committed__": True})

        self.coordinator.submit_state_update(source, update, on_committed)

    def _master_delete_index(self, sender, request, respond):
        self._require_master()
        name = request["index"]
        self._publish_then_respond(
            lambda base: allocation.remove_index(base, name)
            if name in base.metadata else base,
            respond, {"acknowledged": True})

    def _master_update_settings(self, sender, request, respond):
        """`PUT /_cluster/settings` persistent settings: merged into the
        state, then reroute+rebalance so allocation filters / watermarks /
        enable flags take effect immediately (TransportClusterUpdateSettings
        Action reroutes after applying)."""
        self._require_master()
        updates = dict(request.get("persistent") or {})

        def update(base: ClusterState) -> ClusterState:
            merged = dict(base.settings)
            for k, v in updates.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = v
            state = base.with_(settings=merged)
            state = allocation.reroute(state)
            return allocation.rebalance(state)

        self._publish_then_respond(update, respond,
                                   {"acknowledged": True,
                                    "persistent": updates})

    def client_update_settings(self, persistent: dict,
                               on_done: Optional[Callable] = None,
                               on_failure: Optional[Callable] = None) -> None:
        self._send_to_master(MASTER_UPDATE_SETTINGS,
                             {"persistent": persistent},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    def _master_put_registry(self, sender, request, respond):
        """Replicated registries (pipelines/templates/scripts): every
        mutation is a cluster-state update, so every node sees the same
        registry (IngestMetadata/ScriptMetaData analogs)."""
        self._require_master()
        section, key = request["section"], request["key"]
        value = request.get("value")

        def update(base: ClusterState) -> ClusterState:
            meta = dict(base.metadata)
            regs = {k: dict(v) for k, v in
                    (meta.get(REGISTRIES_KEY) or {}).items()}
            sec = regs.setdefault(section, {})
            if value is None:
                sec.pop(key, None)
            else:
                sec[key] = value
            meta[REGISTRIES_KEY] = regs
            return base.with_(metadata=meta)

        self._publish_then_respond(update, respond, {"acknowledged": True})

    def client_put_registry(self, section: str, key: str, value,
                            on_done: Optional[Callable] = None,
                            on_failure: Optional[Callable] = None) -> None:
        self._send_to_master(MASTER_PUT_REGISTRY,
                             {"section": section, "key": key, "value": value},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    def _master_shard_started(self, sender, request, respond):
        self._require_master()
        aid = request["allocation_id"]
        self._publish_then_respond(
            lambda base: allocation.shard_started(base, aid),
            respond, {"ack": True})

    def _master_shard_failed(self, sender, request, respond):
        self._require_master()
        aid = request["allocation_id"]
        self._publish_then_respond(
            lambda base: allocation.shard_failed(base, aid),
            respond, {"ack": True})

    def _send_to_master(self, action: str, request: dict,
                        on_response=None, on_failure=None, retries: int = 60):
        """Master-node action with retry-until-master-known semantics
        (reference: TransportMasterNodeAction observes cluster state and
        retries on NotMasterException / no-master). APPLICATION errors
        (validation etc.) propagate immediately — only master-unavailable
        conditions retry."""
        master = self.cluster_state.master_node_id
        if self.is_master:
            master = self.node_id

        def retry(err=None):
            # a 4xx from the master is the answer, not a reason to re-ask
            status = int(getattr(err, "status", 500)) if err is not None else 500
            if err is not None and 400 <= status < 500 \
                    and "not the elected master" not in str(err):
                if on_failure:
                    on_failure(err)
                return
            if retries <= 0:
                if on_failure:
                    on_failure(SearchEngineError("no elected master"))
                return
            self.scheduler.schedule_in(
                500, lambda: self._send_to_master(action, request, on_response,
                                                  on_failure, retries - 1),
                f"master_retry:{action}")

        if master is None:
            retry()
            return

        def on_resp(resp):
            # the master acked receipt but its publication failed to commit
            # (stepped down mid-publish): retry against the next master
            if isinstance(resp, dict) and resp.get("__not_committed__"):
                retry()
            elif on_response is not None:
                on_response(resp)

        self.transport.send(self.node_id, master, action, request,
                            on_response=on_resp, on_failure=retry)

    # --------------------------------------------------- cluster state applier
    def apply_cluster_state(self, state: ClusterState) -> None:
        """IndicesClusterStateService.applyClusterState analog."""
        # learn peer transport addresses from the published node set, so
        # every node can dial every other (NodeConnectionsService analog);
        # the deterministic test transport routes by id and has no addresses
        add_addr = getattr(self.transport, "add_peer_address", None)
        if add_addr is not None:
            for n in state.nodes.values():
                if n.address and n.node_id != self.node_id:
                    host, _, port = n.address.rpartition(":")
                    if host and port.isdigit():
                        add_addr(n.node_id, host, int(port))

        my_entries = {(r.index, r.shard): r for r in state.routing
                      if r.node_id == self.node_id}

        # remove shards no longer assigned here — including copies reassigned
        # to this node under a NEW allocation_id: the stale engine must go so
        # the create loop below builds the new copy and runs its recovery
        for key in list(self.local_shards):
            mine = my_entries.get(key)
            if mine is None or mine.allocation_id != self.local_shards[key].routing.allocation_id:
                shard = self.local_shards.pop(key)
                shard.engine.close()

        # create / update assigned shards
        for key, entry in my_entries.items():
            index, shard_id = key
            meta = state.metadata.get(index)
            if meta is None:
                continue
            local = self.local_shards.get(key)
            if local is None:
                if index not in self.mappers:
                    from elasticsearch_tpu.index.analysis import (
                        AnalysisRegistry)
                    self.mappers[index] = MapperService(
                        meta.get("mappings") or {"properties": {}},
                        registry=AnalysisRegistry.from_index_settings(
                            meta.get("settings") or {}))
                mapper = self.mappers[index]
                path = os.path.join(self.data_path, index, str(shard_id),
                                    entry.allocation_id.replace("/", "_").replace("#", "_"))
                if entry.primary:
                    # snapshot restore: materialize the shard's files from
                    # the repository BEFORE the engine opens, so the new
                    # primary boots from the snapshotted commit
                    # (RestoreService: restore is a recovery source)
                    from elasticsearch_tpu.cluster.snapshots import (
                        RESTORE_IN_PROGRESS)
                    restore = (state.metadata.get(RESTORE_IN_PROGRESS)
                               or {}).get(index)
                    if restore is not None and self.shard_restore_hook:
                        try:
                            self.shard_restore_hook(restore, index, shard_id,
                                                    path)
                        except Exception as e:
                            self._send_to_master(
                                MASTER_SHARD_FAILED,
                                {"allocation_id": entry.allocation_id,
                                 "reason": f"restore failed: {e}"})
                            continue
                engine = Engine(path, mapper, translog_sync="async")
                local = LocalShard(entry, engine, mapper,
                                   index_settings=meta.get("settings"))
                self.local_shards[key] = local
                if entry.primary:
                    local.tracker.activate_primary_mode(engine.local_checkpoint)
                    self._send_to_master(MASTER_SHARD_STARTED,
                                         {"allocation_id": entry.allocation_id})
                else:
                    self._start_replica_recovery(local, state)
            else:
                was_primary = local.routing.primary
                local.routing = entry
                if entry.primary and not was_primary:
                    # failover promotion (reference: IndexShard#activateWithPrimaryContext)
                    local.tracker = ReplicationTracker(entry.allocation_id)
                    local.tracker.activate_primary_mode(local.engine.local_checkpoint)

        self._sync_persistent_tasks(state)
        for listener in self.state_listeners:
            try:
                listener(state)
            except Exception:
                pass  # a listener bug must not break shard application

    def _start_replica_recovery(self, local: LocalShard, state: ClusterState) -> None:
        entry = local.routing
        prog = self._track_recovery(local)
        self.recovery_stats["attempts"] += 1
        self._recovery_attempts[entry.allocation_id] = \
            self._recovery_attempts.get(entry.allocation_id, 0) + 1
        prog["attempts"] = self._recovery_attempts[entry.allocation_id]
        primary = state.primary_of(entry.index, entry.shard)
        if primary is None or primary.node_id is None:
            # counting this as an attempt keeps the backoff escalating
            # (and eventually gives up -> master reroutes) instead of
            # polling a missing primary at the base interval forever
            self._schedule_recovery_retry(entry, "no active primary")
            return
        prog["source_node"] = primary.node_id

        def on_ops(response):
            if "phase1" in response:
                # translog can't cover the gap: ship the missing blocks
                # first (RecoverySourceHandler.java:262 phase1, at block
                # rather than file granularity), then re-enter ops
                # recovery from the block checkpoint
                self._run_phase1(local, primary.node_id, response["phase1"])
                return
            from elasticsearch_tpu.recovery import progress as rp
            prog["stage"] = rp.STAGE_TRANSLOG
            for op in response["ops"]:
                self._apply_replica_op(local, op)
            prog["ops_replayed"] += len(response["ops"])
            self._finalize_recovery(local, prog)

        def on_fail(_err):
            # primary not ready yet (e.g. promotion not applied there) or the
            # request raced a topology change: retry while still INITIALIZING
            self._schedule_recovery_retry(entry, str(_err))

        self.transport.send(
            self.node_id, primary.node_id, RECOVERY_START,
            {"index": entry.index, "shard": entry.shard,
             "allocation_id": entry.allocation_id,
             "from_seq_no": local.engine.local_checkpoint + 1},
            on_response=on_ops, on_failure=on_fail)
        # dropped-message safety net: if neither response nor failure arrives
        # (partition during recovery), retry while still INITIALIZING
        self.scheduler.schedule_in(
            5000, lambda: self._recovery_watchdog(entry),
            f"recovery_timeout:{entry.allocation_id}")

    def _track_recovery(self, local: LocalShard) -> dict:
        """The progress record for one recovery target (created once per
        allocation; retries mutate the same record)."""
        from elasticsearch_tpu.recovery import progress as rp
        entry = local.routing
        prog = self.recoveries.get(entry.allocation_id)
        if prog is None:
            rtype = "RELOCATION" if entry.relocation_source else "PEER"
            prog = rp.new_progress(entry.index, entry.shard,
                                   entry.allocation_id, rtype,
                                   target_node=self.node_id,
                                   now_ms=int(time.time() * 1000))
            self.recoveries[entry.allocation_id] = prog
        return prog

    def _finalize_recovery(self, local: LocalShard, prog: dict) -> None:
        """Refresh + (for relocations) warm the device path, then report
        started — reference: IndexShard#finalizeRecovery refreshes before
        POST_RECOVERY, so a post-failover copy never serves 0 docs while
        waiting for the next user refresh."""
        from elasticsearch_tpu.recovery import progress as rp
        entry = local.routing
        prog["stage"] = rp.STAGE_FINALIZE
        local.engine.refresh()
        if entry.relocation_source is not None:
            # live relocation: compile the dispatch grid and touch the
            # device arrays through the real serving entry BEFORE routing
            # flips to this copy — the first user search lands warm
            from elasticsearch_tpu.recovery import relocation
            prog["warm"] = relocation.warm_handoff(local)
        prog["stage"] = rp.STAGE_DONE
        prog["stop_ms"] = int(time.time() * 1000)
        self.recovery_stats["completed"] += 1
        self._recovery_attempts.pop(entry.allocation_id, None)
        self._send_to_master(MASTER_SHARD_STARTED,
                             {"allocation_id": entry.allocation_id})

    # recovery retry policy: jittered exponential backoff, capped, with a
    # bounded attempt count — a permanently failing copy is reported to
    # the master (giveup -> reroute) instead of retrying at a fixed
    # interval forever
    _RECOVERY_RETRY_BASE_MS = 500
    _RECOVERY_RETRY_CAP_MS = 30_000
    _RECOVERY_MAX_ATTEMPTS = 10

    def _schedule_recovery_retry(self, entry: ShardRoutingEntry,
                                 reason: str = "") -> None:
        alloc = entry.allocation_id
        local = self.local_shards.get((entry.index, entry.shard))
        if local is None or local.routing.allocation_id != alloc \
                or local.routing.state != ShardRoutingEntry.INITIALIZING:
            return
        n = self._recovery_attempts.get(alloc, 0)
        if n >= self._RECOVERY_MAX_ATTEMPTS:
            self.recovery_stats["giveups"] += 1
            prog = self.recoveries.get(alloc)
            if prog is not None:
                prog["stop_ms"] = int(time.time() * 1000)
            self._recovery_attempts.pop(alloc, None)
            self._send_to_master(
                MASTER_SHARD_FAILED,
                {"allocation_id": alloc,
                 "reason": f"recovery gave up after {n} attempts: {reason}"})
            return
        if alloc in self._recovery_pending:
            return  # a retry is already scheduled; don't stack them
        delay = min(self._RECOVERY_RETRY_CAP_MS,
                    self._RECOVERY_RETRY_BASE_MS << n)
        # deterministic jitter (±25%): decorrelates a herd of replicas
        # retrying against one reborn primary without wall clock or the
        # process hash seed (which would break the simulator's replay)
        span = delay // 2
        delay = delay - span // 2 + \
            zlib.crc32(f"{alloc}:{n}".encode()) % (span + 1)
        self.recovery_stats["retries"] += 1
        prog = self.recoveries.get(alloc)
        if prog is not None:
            prog["throttle_ms"] += delay
        self._recovery_pending.add(alloc)
        self.scheduler.schedule_in(delay,
                                   lambda: self._retry_recovery(entry),
                                   f"recovery_retry:{alloc}")

    def _recovery_watchdog(self, entry: ShardRoutingEntry) -> None:
        """Dropped-message backstop. Unlike a real retry it must not act
        when the recovery finished or a backoff retry is already queued —
        otherwise it would double-fire attempts and defeat the backoff."""
        from elasticsearch_tpu.recovery import progress as rp
        prog = self.recoveries.get(entry.allocation_id)
        if prog is not None and prog["stage"] == rp.STAGE_DONE:
            return
        if entry.allocation_id in self._recovery_pending:
            return
        self._retry_recovery(entry)

    def recovery_summary(self) -> dict:
        """`_nodes/stats indices.recovery` section for this node."""
        from elasticsearch_tpu.recovery import progress as rp
        from elasticsearch_tpu.recovery.snapshot import NODE_STREAM_LIMITER
        out = rp.summarize(self.recoveries.values(), self.recovery_stats,
                           current_as_source=len(self._recovery_sources))
        streams = dict(NODE_STREAM_LIMITER.stats)
        streams["max_streams"] = NODE_STREAM_LIMITER.max_streams
        streams["max_bytes_per_sec"] = NODE_STREAM_LIMITER.max_bytes_per_sec
        # bounded-concurrency snapshot block upload + per-node byte-rate
        # throttle (recovery/snapshot.py limiter)
        out["snapshot_streams"] = streams
        out["throttle_time_in_millis"] = int(
            streams["throttle_time_in_millis"])
        return out

    def _run_phase1(self, local: LocalShard, primary_node: str,
                    phase1: dict) -> None:
        """Target side of block recovery (PeerRecoveryTargetService
        analog): diff the source's block manifest against the node block
        cache, pull ONLY the missing blocks in CRC-framed chunks (each
        landing in the cache as soon as it verifies — a retry after a
        dead source resumes from the last acked block for free), then
        assemble the shard and resume ops recovery from the block
        checkpoint."""
        import base64
        import shutil
        import zlib as _zlib

        from elasticsearch_tpu.recovery import progress as rp
        from elasticsearch_tpu.recovery.manifest import (
            diff_entries, manifest_totals)
        from elasticsearch_tpu.recovery.snapshot import assemble_shard

        entry = local.routing
        prog = self._track_recovery(local)
        entries = list(phase1.get("blocks", []))
        meta = phase1.get("meta")
        if not entries or meta is None:
            return self._schedule_recovery_retry(entry, "empty phase1 manifest")
        missing, _present = diff_entries(entries, self.block_cache.held())
        need, seen = [], set()
        for e in missing:
            if e["digest"] not in seen:
                seen.add(e["digest"])
                need.append(e)
        totals = manifest_totals(entries)
        prog["stage"] = rp.STAGE_BLOCKS
        prog["blocks_total"] = totals["blocks_total"]
        prog["bytes_total"] = totals["bytes_total"]
        prog["blocks_reused"] = totals["blocks_total"] - len(need)
        state = {"idx": 0, "offset": 0, "buf": []}

        def fail(reason):
            self._schedule_recovery_retry(entry, reason)

        def next_block():
            if local.routing.allocation_id != entry.allocation_id:
                return
            if state["idx"] >= len(need):
                return finish()
            e = need[state["idx"]]
            if self.block_cache.has(e["digest"]):
                # landed via a concurrent restore or an earlier attempt
                state["idx"] += 1
                state["offset"] = 0
                state["buf"] = []
                return next_block()
            # budgeted single-RPC (PR-12 ScatterGather): a source that
            # dies mid-transfer resolves as a failure, never a hang
            self._send_guarded(
                primary_node, RECOVERY_FILE_CHUNK,
                {"index": entry.index, "shard": entry.shard,
                 "allocation_id": entry.allocation_id,
                 "digest": e["digest"], "offset": state["offset"]},
                on_chunk, lambda err: fail(str(err)),
                budget_ms=self._REPLICATION_BUDGET_MS, phase="recovery")

        def on_chunk(resp):
            e = need[state["idx"]]
            data = base64.b64decode(resp["data"])
            if (_zlib.crc32(data) & 0xFFFFFFFF) != resp["crc32"]:
                return fail("chunk crc mismatch")
            state["buf"].append(data)
            state["offset"] += len(data)
            if resp.get("last") or state["offset"] >= e["size"]:
                blob = b"".join(state["buf"])
                try:
                    # content-addressed write verifies the digest; a
                    # torn/corrupt transfer is rejected and retried
                    self.block_cache.put(e["digest"], blob)
                except ValueError:
                    return fail(
                        f"block {e['digest'][:8]} failed digest verification")
                prog["blocks_shipped"] += 1
                prog["bytes_shipped"] += len(blob)
                state["idx"] += 1
                state["offset"] = 0
                state["buf"] = []
            next_block()

        def finish():
            # stage every block in memory first (digest-verified reads) so
            # the engine swap below can't strand the shard half-assembled
            blocks = {}
            for e in entries:
                data = self.block_cache.get(e["digest"])
                if data is None:
                    return fail(f"cache lost block {e['digest'][:8]}")
                blocks[e["digest"]] = data
            path = local.engine.path
            local.engine.close()
            for name in os.listdir(path):
                full = os.path.join(path, name)
                if os.path.isdir(full):
                    shutil.rmtree(full, ignore_errors=True)
                else:
                    os.unlink(full)
            assemble_shard(path, entries, meta, blocks.__getitem__)
            engine = Engine(path, local.mapper_service,
                            translog_sync="async")
            local.replace_engine(engine)
            prog["stage"] = rp.STAGE_TRANSLOG
            self._start_replica_recovery(local, self.cluster_state)

        next_block()

    def _retry_recovery(self, entry: ShardRoutingEntry) -> None:
        self._recovery_pending.discard(entry.allocation_id)
        local = self.local_shards.get((entry.index, entry.shard))
        if local is not None and local.routing.allocation_id == entry.allocation_id \
                and local.routing.state == ShardRoutingEntry.INITIALIZING:
            self._start_replica_recovery(local, self.cluster_state)

    def _on_recovery_start(self, sender, request, respond):
        """Primary side (RecoverySourceHandler.recoverToTarget analog):
        ops-only replay when the translog still covers the gap, else a
        phase-1 manifest — commit files snapshotted under a per-recovery
        dir so concurrent flushes can't mutate what the target is copying,
        with a retention lease pinning post-commit history until phase 2."""
        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None or not local.routing.primary:
            raise SearchEngineError(f"not primary for {key}")
        alloc = request["allocation_id"]
        from_seq = int(request.get("from_seq_no", 0))

        if not local.engine.can_replay_from(from_seq):
            respond({"phase1": self._prepare_phase1(local, alloc)})
            return

        ops = local.engine.translog.read_ops(from_seq)
        local.tracker.init_tracking(alloc)
        local.tracker.mark_in_sync(alloc, local.engine.local_checkpoint)
        self._cleanup_phase1(local, alloc)
        respond({"ops": ops, "global_checkpoint": local.tracker.global_checkpoint})

    _RECOVERY_CHUNK = 1 << 20

    def _phase1_dir(self, local: LocalShard, alloc: str) -> str:
        safe = alloc.replace("/", "_").replace("#", "_")
        return os.path.join(local.engine.path, f"_recovery_{safe}")

    def _prepare_phase1(self, local: LocalShard, alloc: str) -> dict:
        """Flush, collect the shard into content-addressed blocks staged
        under a per-recovery dir (so concurrent flushes can't mutate what
        the target is copying), lease the history above the commit
        (RecoverySourceHandler.java:262 phase1 + CcrRetentionLeases-style
        lease so a concurrent flush cannot trim phase-2 ops)."""
        import shutil

        from elasticsearch_tpu.recovery.snapshot import collect_shard_blocks

        engine = local.engine
        engine.flush()
        lease_id = f"peer_recovery/{alloc}"
        retaining = (engine.last_commit_checkpoint or -1) + 1
        try:
            local.tracker.add_retention_lease(lease_id, retaining,
                                              "peer_recovery")
        except IllegalArgumentError:
            local.tracker.renew_retention_lease(lease_id, retaining)
        entries, payloads, meta = collect_shard_blocks(
            engine, getattr(local, "vector_store", None))
        snap_dir = self._phase1_dir(local, alloc)
        shutil.rmtree(snap_dir, ignore_errors=True)
        os.makedirs(snap_dir, exist_ok=True)
        for digest, data in payloads.items():
            with open(os.path.join(snap_dir, digest), "wb") as f:
                f.write(data)
        self._recovery_sources.add(alloc)
        return {"blocks": entries, "meta": meta,
                "from_seq_no": (engine.last_commit_checkpoint or -1) + 1}

    def _cleanup_phase1(self, local: LocalShard, alloc: str) -> None:
        import shutil
        shutil.rmtree(self._phase1_dir(local, alloc), ignore_errors=True)
        self._recovery_sources.discard(alloc)
        try:
            local.tracker.remove_retention_lease(f"peer_recovery/{alloc}")
        except Exception:
            pass

    def _on_recovery_file_chunk(self, sender, request, respond):
        """Primary side: serve one CRC-framed chunk of a staged block,
        addressed by content digest (MultiFileTransfer /
        RecoverySourceHandler.sendFiles analog)."""
        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None or not local.routing.primary:
            raise SearchEngineError(f"not primary for {key}")
        from elasticsearch_tpu.recovery.peer import safe_digest
        snap_dir = self._phase1_dir(local, request["allocation_id"])
        path = os.path.join(snap_dir, safe_digest(request["digest"]))
        offset = int(request["offset"])
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(self._RECOVERY_CHUNK)
        import base64
        import zlib as _zlib
        respond({"digest": request["digest"], "offset": offset,
                 "data": base64.b64encode(data).decode("ascii"),
                 "crc32": _zlib.crc32(data) & 0xFFFFFFFF,
                 "last": offset + len(data) >= os.path.getsize(path)})

    # ------------------------------------------------------------- write path
    def client_write(self, index: str, op: dict,
                     on_done: Callable[[dict], None],
                     on_failure: Optional[Callable[[Exception], None]] = None) -> None:
        """op: {type: index|delete, id, source?}; routes to the primary."""
        state = self.cluster_state
        meta = state.metadata.get(index)
        if meta is None:
            (on_failure or on_done)(IndexNotFoundError(index)
                                    if on_failure else {"error": "index_not_found"})
            return
        num_shards = int(meta["settings"].get("index.number_of_shards", 1))
        sid = shard_id_for(op.get("routing") or op["id"], num_shards)
        primary = state.primary_of(index, sid)
        if primary is None or primary.node_id is None:
            if on_failure:
                on_failure(SearchEngineError(f"no active primary for [{index}][{sid}]"))
            return
        request = {"index": index, "shard": sid, "op": op}
        if primary.node_id == self.node_id:
            self._on_write_primary(self.node_id, request, on_done)
        else:
            self._send_guarded(primary.node_id, WRITE_PRIMARY, request,
                               on_done, on_failure, phase="write_forward")

    def _on_write_primary(self, sender, request, respond):
        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None or not local.routing.primary:
            raise SearchEngineError(f"[{key}] not primary on [{self.node_id}]")
        op = request["op"]
        if op["type"] == "index":
            result = local.engine.index(
                op["id"], op["source"],
                op_type=op.get("op_type", "index"),
                routing=op.get("routing"),
                if_seq_no=op.get("if_seq_no"),
                if_primary_term=op.get("if_primary_term"),
                version=op.get("version"),
                version_type=op.get("version_type", "internal"))
        else:
            result = local.engine.delete(
                op["id"], if_seq_no=op.get("if_seq_no"),
                if_primary_term=op.get("if_primary_term"))
        local.tracker.update_local_checkpoint(local.routing.allocation_id,
                                              local.engine.local_checkpoint)

        state = self.cluster_state
        # fan out to every ASSIGNED copy, INITIALIZING included — a copy mid-
        # recovery must see concurrent ops or they are silently lost when it
        # is later promoted (reference: ReplicationOperation replicates to
        # the tracked set, not just started copies; replica engines dedup by
        # seq_no so recovery-replay overlap is safe)
        replicas = [r for r in state.replicas_of(*key)
                    if r.state in (ShardRoutingEntry.STARTED,
                                   ShardRoutingEntry.INITIALIZING) and r.node_id]
        response = {"_index": request["index"], "_shard": request["shard"],
                    "_id": op["id"], "_seq_no": result.seq_no,
                    "_primary_term": result.primary_term,
                    "_version": result.version, "result": result.result}
        if not replicas:
            respond(response)
            return

        def one_done(outcome, resp, _err, rep):
            if outcome == fanout_lib.OK and isinstance(resp, dict) \
                    and "local_checkpoint" in resp:
                # replica acks carry their local checkpoint: feed the
                # primary's tracker so the global checkpoint advances
                # (ReplicationTracker.java:996 updateLocalCheckpoint) —
                # flush-time translog trimming keys off it via
                # min_retained_seq_no
                try:
                    local.tracker.update_local_checkpoint(
                        rep.allocation_id, int(resp["local_checkpoint"]))
                except Exception:
                    pass
                return
            if outcome != fanout_lib.OK:
                # replica failed to apply, or never answered inside the
                # replication budget (silent partition): ask the master to
                # fail that copy, then ack (reference: ReplicationOperation
                # #onPrimaryOperationFailure; the timed-out case is the
                # unbounded-wait fix — a dropped replica ack must not hang
                # the client write forever)
                self._send_to_master(MASTER_SHARD_FAILED,
                                     {"allocation_id": rep.allocation_id})

        sg = ScatterGather(self.scheduler, phase="replication",
                           budget_ms=self._REPLICATION_BUDGET_MS,
                           stats=self.fanout_stats,
                           on_done=lambda _s: respond(response))
        replica_req = {"index": request["index"], "shard": request["shard"],
                       "op": op, "seq_no": result.seq_no,
                       "primary_term": result.primary_term,
                       "version": result.version,
                       "global_checkpoint": local.tracker.global_checkpoint}
        for rep in replicas:
            def send(on_resp, on_fail, rep=rep):
                self.transport.send(self.node_id, rep.node_id, WRITE_REPLICA,
                                    replica_req, on_response=on_resp,
                                    on_failure=on_fail)
            sg.launch(rep.allocation_id, rep.node_id, send,
                      on_item=lambda o, r, e, rep=rep: one_done(o, r, e, rep))
        sg.seal()

    # replication fan-out budget: the backstop for a replica that neither
    # acks nor fails (silent partition) — the copy is reported failed and
    # the write acks, instead of hanging the client forever
    _REPLICATION_BUDGET_MS = 30_000

    def _send_guarded(self, target: str, action: str, request: dict,
                      on_response, on_failure,
                      budget_ms: Optional[int] = None,
                      phase: str = "forward") -> None:
        """Single-RPC forward with the same no-hang guarantee as the
        fan-outs: a silently dropped response resolves as a failure after
        `budget_ms` (a one-item ScatterGather — the write-to-primary and
        scroll-owner forwards hung forever on a dead target otherwise)."""
        if budget_ms is None:
            budget_ms = self._BROADCAST_BUDGET_MS

        def item(outcome, payload, err):
            if outcome == fanout_lib.OK:
                on_response(payload)
            elif on_failure is not None:
                if err is None:
                    err = SearchEngineError(
                        f"[{action}] to [{target}] got no response in "
                        f"{budget_ms}ms")
                on_failure(err)

        sg = ScatterGather(self.scheduler, phase=phase,
                           budget_ms=budget_ms, stats=self.fanout_stats,
                           on_done=None)
        sg.launch(action, target,
                  lambda ok, fail: self.transport.send(
                      self.node_id, target, action, request,
                      on_response=ok, on_failure=fail),
                  on_item=item)
        sg.seal()
    # scroll create/fetch and broadcast admin fan-outs share one generous
    # backstop budget: these are correctness timers (never hang on a dead
    # node), not latency budgets
    _SCROLL_BUDGET_MS = 30_000
    _BROADCAST_BUDGET_MS = 30_000

    def _on_write_replica(self, sender, request, respond):
        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None:
            raise SearchEngineError(f"no shard {key} on [{self.node_id}]")
        self._apply_replica_op(local, {**request["op"],
                                       "seq_no": request["seq_no"],
                                       "primary_term": request["primary_term"],
                                       "version": request["version"]})
        local.tracker.update_global_checkpoint_on_replica(
            request.get("global_checkpoint", -1))
        respond({"ack": True, "local_checkpoint": local.engine.local_checkpoint})

    def _apply_replica_op(self, local: LocalShard, op: dict) -> None:
        if op.get("type", op.get("op")) in ("index", None):
            local.engine.index(op["id"], op.get("source") or {},
                               seq_no=op["seq_no"],
                               primary_term=op.get("primary_term"),
                               version=op.get("version"), origin="replica",
                               routing=op.get("routing"))
        else:
            try:
                local.engine.delete(op["id"], seq_no=op["seq_no"],
                                    primary_term=op.get("primary_term"),
                                    version=op.get("version"), origin="replica")
            except SearchEngineError:
                pass

    # ------------------------------------------------------------ search path
    def _select_copy(self, copies: List[ShardRoutingEntry],
                     sid: int) -> ShardRoutingEntry:
        """Adaptive replica selection through the unified dispatch cost
        router (SearchExecutionStatsCollector analog): lowest estimated
        queue-wait + RTT + device-leg cost wins; unmeasured nodes rank
        first so every copy gets probed, ties rotate by shard."""
        return self._router.select_copy(copies, sid)

    def _ars_observe(self, node_id: str, took_ms: float) -> None:
        self._router.observe(node_id, float(took_ms))

    def resolve_indices(self, expression: Optional[str]) -> List[str]:
        """Index-name expression → concrete index names from the cluster
        metadata (IndexNameExpressionResolver analog: csv, wildcards,
        _all)."""
        import fnmatch
        # "_"-prefixed keys are reserved metadata sections, not indices
        meta = {n: m for n, m in self.cluster_state.metadata.items()
                if not n.startswith("_")}
        if expression in (None, "", "_all", "*"):
            return sorted(meta)
        out: List[str] = []
        for part in str(expression).split(","):
            part = part.strip()
            if not part:
                continue
            if "*" in part:
                out.extend(n for n in sorted(meta)
                           if fnmatch.fnmatch(n, part) and n not in out)
            elif part in meta:
                if part not in out:
                    out.append(part)
            else:
                # a missing CONCRETE name is an error, not a silent skip
                # (IndexNameExpressionResolver: only wildcards may match
                # nothing)
                raise IndexNotFoundError(part)
        return out

    def client_search(self, index: Optional[str], body: dict,
                      on_done: Callable[[dict], None],
                      telemetry_ctx=None) -> None:
        """Two-phase query-then-fetch scatter-gather with a STREAMING
        incremental reduce (AbstractSearchAsyncAction + QueryPhaseResult
        Consumer:619): the query phase returns (row, score, sort) tuples
        only; per-shard responses fold into a bounded top-(from+size)
        accumulator and batched agg reduce as they arrive, so coordinator
        memory is independent of size x shards; the fetch phase then
        round-trips only for the global window's rows. `index` may be a
        multi-index expression; targets span every resolved index."""
        state = self.cluster_state
        try:
            names = self.resolve_indices(index)
        except IndexNotFoundError as e:
            on_done({"error": {"type": "index_not_found_exception",
                               "reason": str(e)}, "status": 404})
            return
        if not names:
            if index in (None, "", "_all", "*") or "*" in str(index):
                on_done({"took": 0, "timed_out": False,
                         "_shards": {"total": 0, "successful": 0,
                                     "skipped": 0, "failed": 0},
                         "hits": {"total": {"value": 0, "relation": "eq"},
                                  "max_score": None, "hits": []}})
            else:
                on_done({"error": {"type": "index_not_found_exception",
                                   "reason": f"no such index [{index}]"},
                         "status": 404})
            return
        targets: List[Tuple[str, ShardRoutingEntry]] = []
        unsearchable = 0  # red shards: no STARTED copy anywhere
        total_shards = 0
        for name in names:
            num_shards = int(state.metadata[name]["settings"].get(
                "index.number_of_shards", 1))
            total_shards += num_shards
            for sid in range(num_shards):
                copies = [r for r in state.routing
                          if r.index == name and r.shard == sid
                          and r.state == ShardRoutingEntry.STARTED and r.node_id]
                if not copies:
                    unsearchable += 1
                    continue
                targets.append((name, self._select_copy(copies, sid)))
        if not targets:
            # all-red expression: same response CONTRACT as the normal
            # path (took/timed_out/skipped present, red shards counted
            # failed) — the old early return omitted half the _shards
            # object and disagreed in shape with every other response
            on_done({"took": 0, "timed_out": False,
                     "_shards": {"total": total_shards, "successful": 0,
                                 "skipped": 0, "failed": unsearchable},
                     "hits": {"total": {"value": 0, "relation": "eq"},
                              "max_score": None, "hits": []}})
            return

        fan = self._fanout_context(body, telemetry_ctx=telemetry_ctx)

        # can_match pre-filter round (CanMatchPreFilterSearchPhase.java:57):
        # above the threshold, a lightweight range-vs-field-stats RPC prunes
        # shards that provably cannot match before the query phase fans out.
        # Time-range queries prefilter at ANY fan-out width (the reference's
        # default-on-range behavior): the field-stats min/max comparison is
        # exactly the evidence class those queries prune on, and a dashboard
        # time window typically rules out most of a rolling-index target set
        explicit = body.get("pre_filter_shard_size")
        prefilter_size = int(explicit) if explicit is not None else 128
        from elasticsearch_tpu.search.caches import has_range_clauses
        auto_range = (explicit is None
                      and has_range_clauses(body.get("query")))
        if body.get("query") is not None \
                and (len(targets) > prefilter_size
                     or (auto_range and len(targets) > 1)):
            self._can_match_phase(
                body, targets,
                lambda kept, skipped: self._query_phase(
                    body, kept, skipped, total_shards, unsearchable,
                    on_done, fan), fan)
        else:
            self._query_phase(body, targets, 0, total_shards,
                              unsearchable, on_done, fan)

    def _fanout_context(self, body: dict, telemetry_ctx=None) -> dict:
        """Per-request fan-out plan: budgets from the `search.fanout.*`
        cluster settings, the ABSOLUTE deadline from the request's
        `timeout` (propagated into every per-shard sub-request so remote
        admission layers shed on it), the partial-results policy
        (`allow_partial_search_results` overrides the cluster default),
        and the request's trace context (`telemetry.capture()` from the
        REST thread — the coordinator runs on the scheduler thread, so
        thread-locals cannot carry it here)."""
        from elasticsearch_tpu.common.settings import (
            parse_time_value, setting_bool)
        budgets = fanout_lib.budgets_from_settings(
            self.cluster_state.settings)
        started_ms = self.scheduler.now_ms
        deadline_at_ms = None
        timeout = body.get("timeout")
        if timeout not in (None, "", -1, "-1"):
            t_s = parse_time_value(timeout, "timeout")
            if t_s > 0:
                deadline_at_ms = started_ms + int(t_s * 1000)
        partial = budgets["partial_results"]
        if body.get("allow_partial_search_results") is not None:
            partial = setting_bool(body["allow_partial_search_results"])
        trace, trace_parent = None, None
        if telemetry_ctx is not None:
            trace, trace_parent = telemetry_ctx[0], telemetry_ctx[1]
        return {"budgets": budgets, "deadline_at_ms": deadline_at_ms,
                "started_ms": started_ms, "partial": partial,
                "profile": bool(body.get("profile")), "phases": {},
                "trace": trace, "trace_parent": trace_parent}

    def _phase_budget(self, fan: dict, base_budget_ms: int) -> int:
        """Per-shard timer budget for the NEXT phase: the configured phase
        budget, tightened by the request deadline — plus the grace window,
        so a remote's own deadline shed (cheap, attributed) beats the
        coordinator's backstop timer for live-but-slow nodes."""
        if fan["deadline_at_ms"] is None:
            return int(base_budget_ms)
        remaining = max(fan["deadline_at_ms"] - self.scheduler.now_ms, 0)
        return int(min(base_budget_ms,
                       remaining + fan["budgets"]["deadline_grace_ms"]))

    def _phase_deadline_ms(self, fan: dict, base_budget_ms: int) -> int:
        """Absolute deadline stamped on this phase's sub-requests: the
        request's own deadline when it has one, else the phase budget's
        end — either way every sub-request carries an absolute deadline,
        so a remote node never does work whose answer nobody will read."""
        if fan["deadline_at_ms"] is not None:
            return fan["deadline_at_ms"]
        return self.scheduler.now_ms + int(base_budget_ms)

    def _can_match_phase(self, body, targets, proceed, fan):
        flags = {}

        def finish(_summary):
            kept = [(n, e) for n, e in targets
                    if flags.get((n, e.shard), True)]
            skipped = len(targets) - len(kept)
            if not kept:
                # keep one shard so the response still carries proper
                # formatting (reference keeps the first skipped shard)
                kept, skipped = targets[:1], len(targets) - 1
            # pruning yield of the round, next to its launched/ok/failed
            # counters in _nodes/stats `fanout.phases.can_match`
            pc = self.fanout_stats.phase("can_match")
            pc["skipped_shards"] = pc.get("skipped_shards", 0) + skipped
            proceed(kept, skipped)

        # an unresponsive shard defaults to can_match=True (never prune on
        # missing evidence), so timeouts here only cost the pruning win
        sg = ScatterGather(
            self.scheduler, phase="can_match",
            budget_ms=self._phase_budget(
                fan, fan["budgets"]["query_budget_ms"]),
            stats=self.fanout_stats, on_done=finish,
            trace=fan.get("trace"), trace_parent=fan.get("trace_parent"))

        def fold(outcome, resp, _err, name, entry):
            if outcome == fanout_lib.OK and isinstance(resp, dict) \
                    and "can_match" in resp:
                flags[(name, entry.shard)] = bool(resp["can_match"])

        for name, entry in targets:
            req = {"index": name, "shard": entry.shard, "body": body}

            def send(on_resp, on_fail, name=name, entry=entry, req=req):
                if entry.node_id == self.node_id:
                    try:
                        self._on_can_match_shard(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, entry.node_id, CAN_MATCH_SHARD, req,
                        on_response=on_resp, on_failure=on_fail)

            sg.launch((name, entry.shard), entry.node_id, send,
                      on_item=lambda o, r, e, n=name, en=entry:
                      fold(o, r, e, n, en))
        sg.seal()

    def _query_phase(self, body, targets, skipped, num_shards,
                     unsearchable, on_done, fan):
        from elasticsearch_tpu.node import _sort_key_tuple
        from elasticsearch_tpu.search.agg_partials import (
            finalize_aggs, merge_partial_aggs,
        )

        frm = int(body.get("from", 0) or 0)
        size = int(body.get("size", 10) if body.get("size") is not None else 10)
        window = frm + size
        aggs_spec = body.get("aggs") or body.get("aggregations")
        batched_reduce = max(int(body.get("batched_reduce_size", 512)), 2)
        sort_key = ((lambda e: (_sort_key_tuple(e[1], body), e[2]))
                    if body.get("sort")
                    else (lambda e: (-e[0], e[2])))

        # streaming accumulator: top-`window` (score, sort, (index, shard),
        # row, node_id) entries + batched partial-agg buffer
        acc = {"top": [], "agg_buffer": [], "aggs": None, "total": 0,
               "relation": "eq", "max_score": None, "failed": 0,
               "successful": 0, "skipped": skipped, "timed_out": False}

        def fold_aggs(force=False):
            buf = acc["agg_buffer"]
            if not buf or (len(buf) < batched_reduce and not force):
                return
            merged = acc["aggs"]
            for tree in buf:
                merged = tree if merged is None else \
                    merge_partial_aggs(merged, tree, aggs_spec)
            acc["aggs"] = merged
            acc["agg_buffer"] = []

        def fold(outcome, resp, _err, name, entry):
            if isinstance(resp, dict) and "_spans" in resp:
                # the remote's trace segment rode back on the response:
                # fold its spans into the coordinator's trace (their
                # parent ids point at this leg's span, so the merged
                # tree needs no rewriting)
                spans = resp.pop("_spans")
                if fan.get("trace") is not None:
                    fan["trace"].absorb(spans)
            if outcome != fanout_lib.OK:
                # failed / per-shard timer expired / shed at the remote's
                # admission layer: the shard contributed nothing — count
                # it failed, and carry the timeout semantics forward
                acc["failed"] += 1
                if outcome in (fanout_lib.TIMED_OUT, fanout_lib.SHED):
                    acc["timed_out"] = True
                return
            acc["successful"] += 1
            acc["total"] += resp["total"]
            if resp.get("relation") == "gte":
                acc["relation"] = "gte"
            if resp.get("max_score") is not None:
                acc["max_score"] = max(acc["max_score"] or -1e30,
                                       resp["max_score"])
            svs = resp["sort_values"] or [None] * len(resp["rows"])
            entries = [(s, sv, (name, resp["shard"]), row, entry.node_id)
                       for row, s, sv in zip(resp["rows"], resp["scores"], svs)]
            # bounded merge: never hold more than 2*window entries
            acc["top"] = sorted(acc["top"] + entries, key=sort_key)[:window]
            if resp.get("aggregations") is not None:
                acc["agg_buffer"].append(resp["aggregations"])
                fold_aggs()

        fan_trace = fan.get("trace")
        qspan = None
        if fan_trace is not None:
            qspan = fan_trace.begin_span("phase.query",
                                         parent_id=fan.get("trace_parent"),
                                         targets=len(targets))
            # per-leg spans parent under the phase span; ended by
            # query_done below on EVERY completion path (ScatterGather's
            # on_done is structural — the sweep timer guarantees it)

        def query_done(summary):
            if qspan is not None:
                fan_trace.end_span(
                    qspan, status="timeout" if summary["any_timed_out"]
                    else "ok")
            fold_aggs(force=True)
            fan["phases"]["query"] = summary
            if not fan["partial"] and (summary["any_timed_out"]
                                       or acc["failed"] > 0):
                # allow_partial_search_results=false: a timed-out or
                # failed shard fails the whole request (reference:
                # SearchPhaseExecutionException)
                on_done({"error": {
                    "type": "search_phase_execution_exception",
                    "reason": f"{acc['failed']} of {len(targets)} shards "
                              "failed and partial results are disallowed",
                    "phase": "query"}, "status": 503})
                return
            self._fetch_phase(body, acc, num_shards,
                              unsearchable, frm, on_done,
                              finalize_aggs, aggs_spec, fan)

        budgets = fan["budgets"]
        sg = ScatterGather(
            self.scheduler, phase="query",
            budget_ms=self._phase_budget(fan, budgets["query_budget_ms"]),
            stats=self.fanout_stats, observe=self._ars_observe,
            on_done=query_done,
            trace=fan_trace,
            trace_parent=qspan.span_id if qspan is not None else None)
        deadline_ms = self._phase_deadline_ms(fan,
                                              budgets["query_budget_ms"])

        for name, entry in targets:
            req = fanout_lib.attach_deadline(
                {"index": name, "shard": entry.shard, "body": body},
                deadline_ms, self.scheduler.now_ms)

            def send(on_resp, on_fail, entry=entry, req=req):
                if entry.node_id == self.node_id:
                    try:
                        self._on_query_shard(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, entry.node_id, QUERY_SHARD, req,
                        on_response=on_resp, on_failure=on_fail)

            # `request=req` rides the trace context on the deadline
            # envelope, parenting the remote's spans under this leg
            sg.launch((name, entry.shard), entry.node_id, send,
                      on_item=lambda o, r, e, n=name, en=entry:
                      fold(o, r, e, n, en),
                      request=req)
        sg.seal()

    def _fetch_phase(self, body, acc, num_shards,
                     unsearchable, frm, on_done, finalize_aggs, aggs_spec,
                     fan):
        """Second round-trip: materialize _source/highlight for the global
        window only (FetchSearchPhase.java:47), under the fetch-phase
        budget — a dead node can drop hits from the window but never hang
        the response."""
        window_entries = acc["top"][frm:]
        partial_fanin = acc["timed_out"] or acc["failed"] > 0
        out = {
            "took": 0, "timed_out": acc["timed_out"],
            # skipped shards count as successful (SearchResponse: skipped
            # is a subset of successful)
            "_shards": {"total": num_shards,
                        "successful": acc["successful"] + acc.get("skipped", 0),
                        "skipped": acc.get("skipped", 0),
                        "failed": acc["failed"] + unsearchable},
            "hits": {"total": {"value": acc["total"],
                               # a partial fan-in's total only counts the
                               # shards that answered: the true total is
                               # at least this (reference: partial
                               # responses report a lower bound)
                               "relation": "gte" if partial_fanin
                               and acc["successful"] > 0
                               else acc["relation"]},
                     "max_score": acc["max_score"], "hits": []},
        }
        if acc["aggs"] is not None:
            out["aggregations"] = finalize_aggs(acc["aggs"], aggs_spec)

        def finish_response():
            out["took"] = max(self.scheduler.now_ms - fan["started_ms"], 0)
            if out["timed_out"]:
                self.fanout_stats.partial_responses += 1
            from elasticsearch_tpu.search.profile import fanout_profile
            phases = fanout_profile(fan["phases"])
            # private key (popped by the REST layer): the coordinator
            # slow log needs the phase breakdown on EVERY breach, not
            # just on profiled requests
            out["_took_phases"] = phases
            if fan["profile"]:
                out.setdefault("profile", {})["fanout"] = phases
            on_done(out)

        if not window_entries:
            finish_response()
            return

        # group window rows by (index, shard, node)
        by_shard: Dict[Tuple[str, int, str], List[int]] = {}
        for pos, (score, sv, ishard, row, node_id) in enumerate(window_entries):
            by_shard.setdefault((ishard[0], ishard[1], node_id), []).append(pos)
        hits: List[Optional[dict]] = [None] * len(window_entries)

        fan_trace = fan.get("trace")
        fspan = None
        if fan_trace is not None:
            fspan = fan_trace.begin_span("phase.fetch",
                                         parent_id=fan.get("trace_parent"),
                                         targets=len(by_shard))
            # ended by fetch_done on every completion path below

        def fetch_done(summary):
            if fspan is not None:
                fan_trace.end_span(
                    fspan, status="timeout" if summary["any_timed_out"]
                    else "ok")
            fan["phases"]["fetch"] = summary
            out["hits"]["hits"] = [h for h in hits if h is not None]
            finish_response()

        # the request deadline governs QUERY work (the expensive scan);
        # fetch hydrates the window those shards already won and runs
        # under its OWN budget — tightening it by an expired request
        # deadline would shed every hydration and turn partial results
        # into zero hits, defeating the whole partial-results contract
        budgets = fan["budgets"]
        sg = ScatterGather(
            self.scheduler, phase="fetch",
            budget_ms=budgets["fetch_budget_ms"],
            stats=self.fanout_stats, observe=self._ars_observe,
            on_done=fetch_done,
            trace=fan_trace,
            trace_parent=fspan.span_id if fspan is not None else None)
        deadline_ms = self.scheduler.now_ms + budgets["fetch_budget_ms"]

        def fold(outcome, resp, _err, positions):
            if isinstance(resp, dict) and "_spans" in resp:
                spans = resp.pop("_spans")
                if fan_trace is not None:
                    fan_trace.absorb(spans)
            if outcome == fanout_lib.OK:
                for p, hit in zip(positions, resp["hits"]):
                    hits[p] = hit
                return
            out["_shards"]["failed"] += 1
            if outcome in (fanout_lib.TIMED_OUT, fanout_lib.SHED):
                out["timed_out"] = True

        for key, positions in by_shard.items():
            name, shard, node_id = key
            req = fanout_lib.attach_deadline(
                {"index": name, "shard": shard,
                 "rows": [window_entries[p][3] for p in positions],
                 "scores": [window_entries[p][0] for p in positions],
                 "sort_values": [window_entries[p][1] for p in positions],
                 "body": body},
                deadline_ms, self.scheduler.now_ms)

            def send(on_resp, on_fail, node_id=node_id, req=req):
                if node_id == self.node_id:
                    try:
                        self._on_fetch_shard(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(self.node_id, node_id, FETCH_SHARD,
                                        req, on_response=on_resp,
                                        on_failure=on_fail)

            sg.launch(key, node_id, send,
                      on_item=lambda o, r, e, positions=positions:
                      fold(o, r, e, positions),
                      request=req)
        sg.seal()

    def _on_query_shard(self, sender, request, respond):
        """QUERY phase only: (row, score, sort) tuples + partial aggs —
        per-shard network payload independent of the fetch weight
        (QuerySearchResult analog); _source travels in the fetch phase."""
        from elasticsearch_tpu.search.caches import RequestCache

        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None:
            raise SearchEngineError(f"no shard {key} on [{self.node_id}]")
        body = request["body"]

        # trace segment (telemetry): the envelope carried the
        # coordinator's trace context — open a segment with the SAME
        # trace id whose spans parent under the coordinator's leg span.
        # The segment lands in THIS node's ring (per-node attribution in
        # `_nodes/traces`) and its spans ride back on the response for
        # the coordinator to absorb into the one request trace.
        tctx = fanout_lib.trace_ctx_of(request)
        rtrace = None
        if tctx is not None and tctx.get("trace_id"):
            rtrace = telemetry_trace.TRACER.start_remote(
                f"shard.query[{request['index']}][{request['shard']}]",
                node_id=self.node_id, trace_id=tctx["trace_id"],
                parent_span_id=tctx.get("parent_span_id"),
                opaque_id=tctx.get("opaque_id"))

        def answer(payload: dict, status: str = "ok") -> None:
            if rtrace is not None:
                telemetry_trace.TRACER.finish(
                    rtrace, status=None if status == "ok" else status)
                # never mutate a possibly-cached payload: spans go on a
                # copy
                payload = {**payload, "_spans": rtrace.span_dicts()}
            respond(payload)

        # propagated deadline (serving/fanout.py): the coordinator stamped
        # this sub-request with the request's ABSOLUTE deadline. Convert
        # the remaining budget to this process's monotonic clock and hand
        # it to the execution path — device-work legs feed it into the
        # continuous batcher's EDF queue, so an overloaded or late shard
        # sheds at ITS OWN admission layer instead of making the
        # coordinator time out. An already-expired pure-host request is
        # shed right here (no batcher to do it).
        deadline_at = None
        remaining = fanout_lib.remaining_ms(request, self.scheduler.now_ms)
        if remaining is not None:
            has_device_leg = body.get("knn") is not None or (
                isinstance(body.get("query"), dict)
                and "knn" in body["query"])
            if remaining <= 0 and not has_device_leg:
                self.fanout_stats.remote["sheds_admission"] += 1
                answer(fanout_lib.shed_response(request["shard"],
                                                "admission"),
                       status="shed")
                return
            deadline_at = time.monotonic() + remaining / 1000.0

        reader = local.engine.acquire_searcher()
        # shard request cache: whole serialized query-phase responses for
        # size=0 requests, keyed on the reader CONTENT fingerprint
        # (IndicesRequestCache; a no-op refresh keeps its entries)
        cache_key = None
        if self.caches.request.cacheable_tracked(body):
            from elasticsearch_tpu.search.caches import reader_fingerprint
            cache_key = self.caches.request.key(
                key, reader_fingerprint(reader), body)
            cached = self.caches.request.get(cache_key)
            if cached is not None:
                answer(cached)
                return
        # aggs leave the shard as mergeable partial states (HLL/t-digest/
        # sum-count pairs); the coordinator reduce finalizes them
        # (InternalAggregation.reduce analog)
        try:
            # the segment rides the thread for the synchronous execute:
            # the vector-store batcher's queue entries capture it here,
            # so remote queue-wait / dispatch / device-sync spans land in
            # this segment with zero extra plumbing
            with telemetry_trace.use(trace=rtrace):
                t0 = time.perf_counter_ns()
                result = execute_query_phase(
                    reader, local.mapper_service, body,
                    shard_id=request["shard"],
                    vector_store=local.active_vector_store(),
                    partial_aggs=True,
                    query_cache=self.caches.query,
                    deadline_at=deadline_at)
                telemetry_trace.record_span(
                    "shard.query_phase", time.perf_counter_ns() - t0)
        except EsRejectedExecutionError:
            # the continuous batcher's EDF queue shed the device leg on
            # the propagated deadline — exactly the remote-admission shed
            # the fan-out exists to produce. Answer with the structured
            # rejection so the coordinator attributes it (deadline, not
            # node death).
            self.fanout_stats.remote["sheds_batcher"] += 1
            answer(fanout_lib.shed_response(request["shard"],
                                            "batcher_edf"),
                   status="shed")
            return
        except BaseException:
            # an erroring shard must not leak its trace segment (the
            # leaked-span class TPU012 polices): finish it with error
            # status so it still lands in this node's ring, then let the
            # failure travel to the coordinator's on_failure as before
            if rtrace is not None:
                telemetry_trace.TRACER.finish(rtrace, status="error")
            raise
        response = {
            "shard": request["shard"],
            "total": result.total_hits,
            "relation": result.total_relation,
            "max_score": result.max_score,
            "rows": [int(r) for r in result.rows],
            "scores": [float(s) for s in result.scores],
            "sort_values": [list(sv) for sv in result.sort_values]
            if result.sort_values is not None else None,
            "aggregations": result.aggregations,
        }
        if cache_key is not None:
            self.caches.request.put(cache_key, response)
        answer(response)

    def _on_can_match_shard(self, sender, request, respond):
        """Lightweight pre-filter: range-vs-field-stats only, no query
        execution (SearchService#canMatch)."""
        from elasticsearch_tpu.search.caches import can_match

        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None:
            raise SearchEngineError(f"no shard {key} on [{self.node_id}]")
        reader = local.engine.acquire_searcher()
        respond({"shard": request["shard"],
                 "can_match": can_match(reader, local.mapper_service,
                                        request["body"])})

    # ------------------------------------------------------------ scroll
    # Per-shard pinned reader contexts with keepalives (reference:
    # SearchService.createContext + LegacyReaderContext for scrolls,
    # SearchScrollAsyncAction on the coordinator). The shard holds the
    # full sorted row snapshot; the coordinator pulls windows per page,
    # so deep pagination never materializes the corpus anywhere.

    def _on_scroll_create(self, sender, request, respond):
        import uuid as _uuid

        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None:
            raise SearchEngineError(f"no shard {key} on [{self.node_id}]")
        body = dict(request["body"])
        reader = local.engine.acquire_searcher()
        body["size"] = reader.num_docs  # snapshot the full shard ordering
        body["from"] = 0
        body["__unbounded_window__"] = True  # scroll bypasses
        # index.max_result_window: depth is bounded per page, not in total
        body["track_total_hits"] = True  # scrolls always count accurately
        body.pop("aggs", None)
        body.pop("aggregations", None)
        result = execute_query_phase(reader, local.mapper_service, body,
                                     shard_id=request["shard"],
                                     vector_store=local.active_vector_store(),
                                     query_cache=self.caches.query)
        ctx_id = _uuid.uuid4().hex
        keep_s = float(request.get("keep_alive_s", 300))
        self._shard_scrolls[ctx_id] = {
            "index": request["index"], "shard": request["shard"],
            "reader": reader, "body": request["body"],
            "rows": result.rows, "scores": result.scores,
            "sort_values": result.sort_values,
            "expiry": time.time() + keep_s, "keep_s": keep_s,
        }
        respond({"ctx_id": ctx_id, "total": result.total_hits,
                 "relation": result.total_relation,
                 "max_score": result.max_score})

    def _reap_shard_scrolls(self) -> None:
        now = time.time()
        for cid in [c for c, s in self._shard_scrolls.items()
                    if s["expiry"] < now]:
            self._shard_scrolls.pop(cid, None)

    def _on_scroll_fetch(self, sender, request, respond):
        import numpy as np

        from elasticsearch_tpu.search.service import ShardSearchResult

        self._reap_shard_scrolls()
        ctx = self._shard_scrolls.get(request["ctx_id"])
        if ctx is None:
            raise SearchContextMissingError(
                f"No search context found for id [{request['ctx_id']}]")
        if request.get("keep_alive_s"):
            ctx["keep_s"] = float(request["keep_alive_s"])
        ctx["expiry"] = time.time() + ctx["keep_s"]
        pos = int(request["pos"])
        count = int(request["count"])
        rows = ctx["rows"][pos:pos + count]
        scores = ctx["scores"][pos:pos + count]
        svs = ctx["sort_values"][pos:pos + count] \
            if ctx["sort_values"] is not None else None
        result = ShardSearchResult(
            shard_id=ctx["shard"],
            rows=np.asarray(rows, dtype=np.int64),
            scores=np.asarray(scores, dtype=np.float32),
            sort_values=svs, total_hits=len(rows), total_relation="eq",
            aggregations=None, max_score=None)
        hits = execute_fetch_phase(ctx["reader"], self.local_shards[
            (ctx["index"], ctx["shard"])].mapper_service,
            ctx["body"], result, index_name=ctx["index"])
        respond({"hits": hits,
                 "scores": [float(s) for s in scores],
                 "sort_values": [list(sv) if sv is not None else None
                                 for sv in svs] if svs is not None else None,
                 "exhausted": pos + count >= len(ctx["rows"])})

    def _on_scroll_free(self, sender, request, respond):
        freed = self._shard_scrolls.pop(request["ctx_id"], None) is not None
        respond({"freed": freed})

    def client_scroll_start(self, index: Optional[str], body: dict,
                            keep_alive_s: float,
                            on_done: Callable[[dict], None]) -> None:
        """Open per-shard scroll contexts on every target shard, then
        serve the first page through the merged cursor."""
        import uuid as _uuid

        state = self.cluster_state
        try:
            names = self.resolve_indices(index)
        except IndexNotFoundError as e:
            on_done({"error": {"type": "index_not_found_exception",
                               "reason": str(e)}, "status": 404})
            return
        targets: List[Tuple[str, ShardRoutingEntry]] = []
        for name in names:
            num_shards = int(state.metadata[name]["settings"].get(
                "index.number_of_shards", 1))
            for sid in range(num_shards):
                copies = [r for r in state.routing
                          if r.index == name and r.shard == sid
                          and r.state == ShardRoutingEntry.STARTED
                          and r.node_id]
                if copies:
                    targets.append((name, self._select_copy(copies, sid)))
        if not targets:
            on_done({"_scroll_id": _uuid.uuid4().hex, "took": 0,
                     "timed_out": False,
                     "_shards": {"total": 0, "successful": 0, "skipped": 0,
                                 "failed": 0},
                     "hits": {"total": {"value": 0, "relation": "eq"},
                              "max_score": None, "hits": []}})
            return
        size = int(body.get("size", 10) if body.get("size") is not None
                   else 10)
        # the id carries the coordinating node so ANY node can serve or
        # clear it (the reference encodes context locations in the id)
        scroll_id = f"{self.node_id}~{_uuid.uuid4().hex}"
        sstate = {
            "body": body, "size": size, "keep_s": keep_alive_s,
            "expiry": time.time() + keep_alive_s,
            "total": 0, "relation": "eq", "max_score": None,
            "shards": [],  # {node, ctx, pos, buffer, exhausted, failed}
        }
        failed_creates = {"n": 0}

        def created(outcome, resp, entry):
            if outcome == fanout_lib.OK and isinstance(resp, dict) \
                    and "ctx_id" in resp:
                sstate["total"] += int(resp.get("total", 0))
                if resp.get("relation") == "gte":
                    sstate["relation"] = "gte"
                ms = resp.get("max_score")
                if ms is not None:
                    sstate["max_score"] = max(sstate["max_score"] or -1e30,
                                              ms)
                sstate["shards"].append({
                    "node": entry.node_id, "ctx": resp["ctx_id"],
                    "pos": 0, "buffer": [], "exhausted": False,
                    "failed": False})
            else:
                failed_creates["n"] += 1

        def creates_done(_summary):
            self._client_scrolls[scroll_id] = sstate
            self._scroll_page(scroll_id, sstate, failed_creates["n"],
                              on_done)

        sg = ScatterGather(self.scheduler, phase="scroll_create",
                           budget_ms=self._SCROLL_BUDGET_MS,
                           stats=self.fanout_stats, on_done=creates_done)
        for name, entry in targets:
            req = {"index": name, "shard": entry.shard, "body": body,
                   "keep_alive_s": keep_alive_s}

            def send(on_resp, on_fail, entry=entry, req=req):
                if entry.node_id == self.node_id:
                    try:
                        self._on_scroll_create(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, entry.node_id, SCROLL_CREATE, req,
                        on_response=on_resp, on_failure=on_fail)

            sg.launch((name, entry.shard), entry.node_id, send,
                      on_item=lambda o, r, e, en=entry: created(o, r, en))
        sg.seal()

    def _scroll_page(self, scroll_id: str, sstate: dict, failed: int,
                     on_done: Callable[[dict], None]) -> None:
        """Fill per-shard buffers to >= size (or exhaustion), then emit the
        globally-ordered next page (SearchScrollQueryThenFetchAsyncAction's
        lastEmittedDoc accounting, done with per-shard cursors)."""
        from elasticsearch_tpu.node import _sort_key_tuple

        size = sstate["size"]
        body = sstate["body"]
        need = [sh for sh in sstate["shards"]
                if not sh["exhausted"] and not sh["failed"]
                and len(sh["buffer"]) < size]
        if not need:
            # keep untouched-but-live shard contexts alive: a shard whose
            # buffer stays full would otherwise never see a fetch and
            # could expire mid-scroll (keepalive piggyback, count=0)
            for sh in sstate["shards"]:
                if sh["exhausted"] or sh["failed"]:
                    continue
                if len(sh["buffer"]) >= size:
                    req = {"ctx_id": sh["ctx"], "pos": sh["pos"],
                           "count": 0, "keep_alive_s": sstate["keep_s"]}
                    if sh["node"] == self.node_id:
                        try:
                            self._on_scroll_fetch(self.node_id, req,
                                                  lambda _r: None)
                        except Exception:
                            pass
                    else:
                        self.transport.send(
                            self.node_id, sh["node"], SCROLL_FETCH, req,
                            on_response=lambda _r: None,
                            on_failure=lambda _e: None)
            # merge: pick the top `size` across buffers
            sort_spec = body.get("sort")

            def rank(item):
                _hit, score, sv = item
                if sort_spec:
                    return _sort_key_tuple(sv, body)
                return (-(score if score is not None else -1e30),)
            candidates = []
            for sh in sstate["shards"]:
                for item in sh["buffer"]:
                    candidates.append((rank(item), sh, item))
            candidates.sort(key=lambda t: t[0])
            page = candidates[:size]
            for _, sh, item in page:
                sh["buffer"].remove(item)
            hits = [item[0] for _, _, item in page]
            runtime_failed = sum(1 for sh in sstate["shards"]
                                 if sh["failed"])
            shards_total = len(sstate["shards"]) + failed
            on_done({"_scroll_id": scroll_id, "took": 0,
                     "timed_out": False,
                     "_shards": {"total": shards_total,
                                 "successful": len(sstate["shards"])
                                 - runtime_failed,
                                 "skipped": 0,
                                 "failed": failed + runtime_failed},
                     "hits": {"total": {"value": sstate["total"],
                                        "relation": sstate["relation"]},
                              "max_score": sstate["max_score"],
                              "hits": hits}})
            return
        def fetched(outcome, resp, sh):
            if outcome == fanout_lib.OK and isinstance(resp, dict) \
                    and "hits" in resp:
                svs = resp.get("sort_values")
                for i, h in enumerate(resp["hits"]):
                    sh["buffer"].append(
                        (h, resp["scores"][i] if resp.get("scores") else None,
                         tuple(svs[i]) if svs is not None
                         and svs[i] is not None else None))
                sh["pos"] += len(resp["hits"])
                if resp.get("exhausted"):
                    sh["exhausted"] = True
            else:
                # a shard that failed OR never answered inside the budget
                # stops contributing to the scroll; remaining shards keep
                # paging (same partial semantics as the search fan-out)
                sh["failed"] = True

        sg = ScatterGather(
            self.scheduler, phase="scroll_fetch",
            budget_ms=self._SCROLL_BUDGET_MS, stats=self.fanout_stats,
            on_done=lambda _s: self._scroll_page(scroll_id, sstate,
                                                 failed, on_done))
        for sh in need:
            req = {"ctx_id": sh["ctx"], "pos": sh["pos"],
                   "count": max(size, 1),
                   "keep_alive_s": sstate["keep_s"]}

            def send(on_resp, on_fail, sh=sh, req=req):
                if sh["node"] == self.node_id:
                    try:
                        self._on_scroll_fetch(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, sh["node"], SCROLL_FETCH, req,
                        on_response=on_resp, on_failure=on_fail)

            sg.launch(sh["ctx"], sh["node"], send,
                      on_item=lambda o, r, e, s=sh: fetched(o, r, s))
        sg.seal()

    def _scroll_owner(self, scroll_id: str) -> Optional[str]:
        owner = scroll_id.split("~", 1)[0] if "~" in scroll_id else None
        if owner and owner != self.node_id \
                and owner in self.cluster_state.nodes:
            return owner
        return None

    def client_scroll_next(self, scroll_id: str,
                           keep_alive_s: Optional[float],
                           on_done: Callable[[dict], None]) -> None:
        owner = self._scroll_owner(scroll_id)
        if owner:
            self._send_guarded(
                owner, SCROLL_NEXT,
                {"scroll_id": scroll_id, "keep_alive_s": keep_alive_s},
                on_done,
                lambda e: on_done({"error": {
                    "type": "search_context_missing_exception",
                    "reason": str(e)}, "status": 404}),
                phase="scroll_forward")
            return
        sstate = self._client_scrolls.get(scroll_id)
        if sstate is None or sstate["expiry"] < time.time():
            self._client_scrolls.pop(scroll_id, None)
            on_done({"error": {
                "type": "search_context_missing_exception",
                "reason": f"No search context found for id [{scroll_id}]"},
                "status": 404})
            return
        if keep_alive_s:
            sstate["keep_s"] = keep_alive_s
        sstate["expiry"] = time.time() + sstate["keep_s"]
        self._scroll_page(scroll_id, sstate, 0, on_done)

    def _on_scroll_clear_all(self, sender, request, respond):
        """Free every scroll THIS node coordinates (one leg of the
        cluster-wide _all broadcast)."""
        ids = list(self._client_scrolls)
        pending = {"count": len(ids), "freed": 0}
        if not ids:
            respond({"num_freed": 0})
            return

        def one(resp):
            pending["freed"] += int((resp or {}).get("num_freed", 0))
            pending["count"] -= 1
            if pending["count"] == 0:
                respond({"num_freed": pending["freed"]})

        for sid in ids:
            self.client_scroll_clear(sid, one)

    def client_scroll_clear_all(self, on_done: Callable[[dict], None]) -> None:
        """Broadcast _all scroll clearing to every node (any node may be
        coordinating scrolls the client started elsewhere)."""
        nodes = sorted(self.cluster_state.nodes) or [self.node_id]
        freed = {"n": 0}

        def one(outcome, resp, _err):
            if outcome == fanout_lib.OK:
                freed["n"] += int((resp or {}).get("num_freed", 0))

        sg = ScatterGather(
            self.scheduler, phase="scroll_clear",
            budget_ms=self._BROADCAST_BUDGET_MS, stats=self.fanout_stats,
            on_done=lambda _s: on_done({"succeeded": True,
                                        "num_freed": freed["n"]}))
        for nid in nodes:
            def send(on_resp, on_fail, nid=nid):
                if nid == self.node_id:
                    try:
                        self._on_scroll_clear_all(self.node_id, {}, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, nid, SCROLL_CLEAR_ALL, {},
                        on_response=on_resp, on_failure=on_fail)

            sg.launch(nid, nid, send, on_item=one)
        sg.seal()

    def client_scroll_clear(self, scroll_id: str,
                            on_done: Callable[[dict], None]) -> None:
        owner = self._scroll_owner(scroll_id)
        if owner:
            self._send_guarded(
                owner, SCROLL_CLEAR, {"scroll_id": scroll_id},
                on_done,
                lambda e: on_done({"succeeded": True, "num_freed": 0}),
                phase="scroll_forward")
            return
        sstate = self._client_scrolls.pop(scroll_id, None)
        if sstate is None:
            on_done({"succeeded": True, "num_freed": 0})
            return
        shards = [sh for sh in sstate["shards"] if not sh["failed"]]
        if not shards:
            on_done({"succeeded": True, "num_freed": 0})
            return
        freed = {"n": 0}

        def one(outcome, resp, _err):
            if outcome == fanout_lib.OK and isinstance(resp, dict) \
                    and resp.get("freed"):
                freed["n"] += 1

        sg = ScatterGather(
            self.scheduler, phase="scroll_clear",
            budget_ms=self._BROADCAST_BUDGET_MS, stats=self.fanout_stats,
            on_done=lambda _s: on_done({"succeeded": True,
                                        "num_freed": freed["n"]}))
        for sh in shards:
            req = {"ctx_id": sh["ctx"]}

            def send(on_resp, on_fail, sh=sh, req=req):
                if sh["node"] == self.node_id:
                    try:
                        self._on_scroll_free(self.node_id, req, on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(
                        self.node_id, sh["node"], SCROLL_FREE, req,
                        on_response=on_resp, on_failure=on_fail)

            sg.launch(sh["ctx"], sh["node"], send, on_item=one)
        sg.seal()

    def _on_fetch_shard(self, sender, request, respond):
        """FETCH phase: materialize hits for the coordinator's global
        window rows (FetchSearchPhase / SearchService.executeFetchPhase)."""
        import numpy as np

        from elasticsearch_tpu.search.service import ShardSearchResult

        key = (request["index"], request["shard"])
        local = self.local_shards.get(key)
        if local is None:
            raise SearchEngineError(f"no shard {key} on [{self.node_id}]")
        tctx = fanout_lib.trace_ctx_of(request)
        rtrace = None
        if tctx is not None and tctx.get("trace_id"):
            rtrace = telemetry_trace.TRACER.start_remote(
                f"shard.fetch[{request['index']}][{request['shard']}]",
                node_id=self.node_id, trace_id=tctx["trace_id"],
                parent_span_id=tctx.get("parent_span_id"),
                opaque_id=tctx.get("opaque_id"))

        def answer(payload: dict, status: str = "ok") -> None:
            if rtrace is not None:
                telemetry_trace.TRACER.finish(
                    rtrace, status=None if status == "ok" else status)
                payload = {**payload, "_spans": rtrace.span_dicts()}
            respond(payload)

        # propagated-deadline admission: a fetch arriving past the
        # request's deadline hydrates hits nobody will read — shed it
        remaining = fanout_lib.remaining_ms(request, self.scheduler.now_ms)
        if remaining is not None and remaining <= 0:
            self.fanout_stats.remote["sheds_admission"] += 1
            answer(fanout_lib.shed_response(request["shard"], "admission"),
                   status="shed")
            return
        body = request["body"]
        reader = local.engine.acquire_searcher()
        svs = request.get("sort_values")
        result = ShardSearchResult(
            shard_id=request["shard"],
            rows=np.asarray(request["rows"], dtype=np.int64),
            scores=np.asarray(request["scores"], dtype=np.float32),
            sort_values=[tuple(sv) if sv is not None else None for sv in svs]
            if svs is not None and any(sv is not None for sv in svs) else None,
            total_hits=len(request["rows"]), total_relation="eq",
            aggregations=None, max_score=None)
        t0 = time.perf_counter_ns()
        try:
            hits = execute_fetch_phase(reader, local.mapper_service, body,
                                       result,
                                       index_name=request["index"])
        except BaseException:
            # same no-leak rule as the query side: an erroring fetch
            # finishes its segment with error status before propagating
            if rtrace is not None:
                telemetry_trace.TRACER.finish(rtrace, status="error")
            raise
        if rtrace is not None:
            rtrace.record_span("hydrate", time.perf_counter_ns() - t0,
                               parent_id=rtrace.root.span_id,
                               hits=len(hits))
        answer({"hits": hits})

    def client_get(self, index: str, doc_id: str,
                   on_done: Callable[[dict], None],
                   routing: Optional[str] = None) -> None:
        state = self.cluster_state
        meta = state.metadata.get(index)
        if meta is None:
            on_done({"found": False, "error": "index_not_found"})
            return
        num_shards = int(meta["settings"].get("index.number_of_shards", 1))
        sid = shard_id_for(routing if routing is not None else doc_id,
                           num_shards)
        primary = state.primary_of(index, sid)
        if primary is None:
            on_done({"found": False, "error": "no_primary"})
            return

        request = {"index": index, "shard": sid, "id": doc_id}
        if primary.node_id == self.node_id:
            self._on_get(self.node_id, request, on_done)
        else:
            self._send_guarded(primary.node_id, "indices:data/read/get",
                               request, on_done,
                               lambda e: on_done({"found": False,
                                                  "error": str(e)}),
                               phase="get_forward")

    def _on_get(self, sender, request, respond):
        local = self.local_shards.get((request["index"], request["shard"]))
        if local is None:
            respond({"found": False})
            return
        doc = local.engine.get(request["id"])
        if doc is None:
            respond({"_index": request["index"], "_id": request["id"], "found": False})
        else:
            out = {"_index": request["index"], "_id": request["id"],
                   "found": True, "_source": doc["_source"],
                   "_seq_no": doc["_seq_no"], "_version": doc["_version"],
                   "_primary_term": doc.get("_primary_term", 1)}
            if doc.get("_routing") is not None:
                out["_routing"] = doc["_routing"]
            respond(out)

    def refresh_all(self) -> None:
        for shard in self.local_shards.values():
            shard.engine.refresh()

    # ------------------------------------------------------------------ wiring
    def _register_handlers(self):
        t = self.transport
        me = self.node_id
        t.register(me, WRITE_PRIMARY, self._on_write_primary)
        t.register(me, WRITE_REPLICA, self._on_write_replica)
        t.register(me, QUERY_SHARD, self._on_query_shard)
        t.register(me, FETCH_SHARD, self._on_fetch_shard)
        t.register(me, CAN_MATCH_SHARD, self._on_can_match_shard)
        t.register(me, SCROLL_CREATE, self._on_scroll_create)
        t.register(me, SCROLL_FETCH, self._on_scroll_fetch)
        t.register(me, SCROLL_FREE, self._on_scroll_free)
        t.register(me, SCROLL_NEXT,
                   lambda s, req, respond: self.client_scroll_next(
                       req["scroll_id"], req.get("keep_alive_s"), respond))
        t.register(me, SCROLL_CLEAR,
                   lambda s, req, respond: self.client_scroll_clear(
                       req["scroll_id"], respond))
        t.register(me, SCROLL_CLEAR_ALL, self._on_scroll_clear_all)
        t.register(me, "indices:data/read/get", self._on_get)
        t.register(me, "indices:admin/refresh", self._on_refresh)
        t.register(me, RECOVERY_START, self._on_recovery_start)
        t.register(me, RECOVERY_FILE_CHUNK, self._on_recovery_file_chunk)
        t.register(me, MASTER_CREATE_INDEX, self._master_create_index)
        t.register(me, MASTER_DELETE_INDEX, self._master_delete_index)
        t.register(me, MASTER_SHARD_STARTED, self._master_shard_started)
        t.register(me, MASTER_SHARD_FAILED, self._master_shard_failed)
        t.register(me, MASTER_UPDATE_SETTINGS, self._master_update_settings)
        t.register(me, MASTER_PUT_REGISTRY, self._master_put_registry)
        t.register(me, MASTER_PUT_PERSISTENT_TASK,
                   self._master_put_persistent_task)
        t.register(me, NODES_DISPATCH, self._on_nodes_dispatch)

    # routed actions ----------------------------------------------------------
    def _on_nodes_dispatch(self, sender, request, respond):
        """Run a named registered collector locally and respond with its
        section — the nodeOperation half of TransportNodesAction."""
        op = (request or {}).get("op")
        fn = self.node_collectors.get(op)
        if fn is None:
            respond({"error": {"type": "unknown_dispatch_op",
                               "reason": f"no collector [{op}]"}})
            return
        params = (request or {}).get("params") or {}

        def work():
            try:
                out = {"result": fn(params)}
            except Exception as e:  # surface to the caller, never hang
                out = {"error": {"type": type(e).__name__, "reason": str(e),
                                 "status": int(getattr(e, "status", 500))}}
            loop = getattr(self.transport, "loop", None)
            if loop is not None:
                loop.call_soon_threadsafe(respond, out)
            else:  # simulator transport: synchronous respond
                respond(out)

        if self.dispatch_executor is not None:
            # collectors may block (hot-threads sampling, fs probes): run on
            # the generic pool, never on the event loop
            self.dispatch_executor(work)
        else:
            work()

    def _transport_send(self, target: str, action: str, request: dict,
                        on_response, on_failure,
                        timeout_ms: Optional[int]) -> None:
        """send() with timeout when the transport supports it (the
        deterministic sim transport's send has no timeout kwarg)."""
        if not hasattr(self, "_send_takes_timeout"):
            import inspect
            self._send_takes_timeout = "timeout_ms" in                 inspect.signature(self.transport.send).parameters
        if self._send_takes_timeout:
            self.transport.send(self.node_id, target, action, request,
                                on_response=on_response,
                                on_failure=on_failure, timeout_ms=timeout_ms)
        else:
            self.transport.send(self.node_id, target, action, request,
                                on_response=on_response,
                                on_failure=on_failure)

    def fanout_nodes(self, op: str, params: Optional[dict] = None,
                     on_done: Optional[Callable] = None,
                     timeout_ms: int = 10000) -> None:
        """Broadcast a named collector op to every cluster node and merge:
        on_done({"results": {node_id: section}, "failures": {node_id: err}}).
        Unreachable nodes become failures, not errors — the merged response
        reports partial coverage the way TransportNodesAction does."""
        targets = list(self.cluster_state.nodes.keys()) or [self.node_id]
        results: Dict[str, Any] = {}
        failures: Dict[str, Any] = {}
        remaining = {"n": len(targets)}

        def finish_one():
            remaining["n"] -= 1
            if remaining["n"] == 0 and on_done is not None:
                on_done({"results": results, "failures": failures})

        def callbacks(nid):
            def on_resp(resp):
                if isinstance(resp, dict) and resp.get("error") is not None:
                    failures[nid] = resp["error"]
                else:
                    results[nid] = (resp or {}).get("result")
                finish_one()

            def on_fail(err):
                failures[nid] = {"type": "node_unreachable",
                                 "reason": str(err)}
                finish_one()

            return on_resp, on_fail

        for nid in targets:
            on_resp, on_fail = callbacks(nid)
            self._transport_send(nid, NODES_DISPATCH,
                                 {"op": op, "params": params or {}},
                                 on_resp, on_fail, timeout_ms)

    def dispatch_to_node(self, node_id: str, op: str,
                         params: Optional[dict] = None,
                         on_done: Optional[Callable] = None,
                         on_failure: Optional[Callable] = None,
                         timeout_ms: int = 10000) -> None:
        """Run a named collector op on ONE node (task get/cancel routing)."""
        def on_resp(resp):
            if isinstance(resp, dict) and resp.get("error") is not None:
                err = resp["error"]
                # rebuild the remote's error class so error.type/status
                # round-trip (clustered /_tasks/{id} must 404 with
                # resource_not_found_exception, as single-node does)
                from elasticsearch_tpu.common import errors as _errors
                cls = getattr(_errors, str(err.get("type", "")),
                              SearchEngineError)
                if not (isinstance(cls, type)
                        and issubclass(cls, SearchEngineError)):
                    cls = SearchEngineError
                exc = cls(err.get("reason", str(err)))
                exc.status = int(err.get("status", getattr(cls, "status", 500)))
                if on_failure:
                    on_failure(exc)
                return
            if on_done:
                on_done((resp or {}).get("result"))

        self._transport_send(node_id, NODES_DISPATCH,
                             {"op": op, "params": params or {}},
                             on_resp, on_failure, timeout_ms)

    # client admin helpers ----------------------------------------------------
    def client_create_index(self, name: str, settings: Optional[dict] = None,
                            mappings: Optional[dict] = None,
                            on_done: Optional[Callable] = None,
                            on_failure: Optional[Callable] = None) -> None:
        self._send_to_master(MASTER_CREATE_INDEX,
                             {"index": name, "settings": settings,
                              "mappings": mappings},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    def client_delete_index(self, name: str, on_done: Optional[Callable] = None,
                            on_failure: Optional[Callable] = None) -> None:
        self._send_to_master(MASTER_DELETE_INDEX, {"index": name},
                             on_response=on_done or (lambda r: None),
                             on_failure=on_failure)

    def _on_refresh(self, sender, request, respond):
        index = (request or {}).get("index")
        for (idx, _sid), shard in self.local_shards.items():
            if index is None or idx == index:
                shard.engine.refresh()
        respond({"ack": True})

    def client_refresh(self, index: Optional[str],
                       on_done: Callable[[dict], None]) -> None:
        """Cluster-wide refresh: broadcast to every node holding shards
        (RefreshAction broadcast-by-node analog)."""
        state = self.cluster_state
        targets = sorted({n for n in state.nodes})
        if not targets:
            targets = [self.node_id]
        counts = {"ok": 0, "failed": 0}

        def one(outcome, _resp, _err):
            # an unreachable or unresponsive node means its shards were
            # NOT refreshed — the response must say so, not claim success
            # (RefreshAction reports per-shard failures)
            counts["ok" if outcome == fanout_lib.OK else "failed"] += 1

        sg = ScatterGather(
            self.scheduler, phase="refresh",
            budget_ms=self._BROADCAST_BUDGET_MS, stats=self.fanout_stats,
            on_done=lambda _s: on_done(
                {"_shards": {"total": len(targets),
                             "successful": counts["ok"],
                             "failed": counts["failed"]}}))
        for t in targets:
            def send(on_resp, on_fail, t=t):
                if t == self.node_id:
                    try:
                        self._on_refresh(self.node_id, {"index": index},
                                         on_resp)
                    except Exception as e:
                        on_fail(e)
                else:
                    self.transport.send(self.node_id, t,
                                        "indices:admin/refresh",
                                        {"index": index},
                                        on_response=on_resp,
                                        on_failure=on_fail)

            sg.launch(t, t, send, on_item=one)
        sg.seal()
