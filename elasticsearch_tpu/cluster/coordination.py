"""Cluster coordination: the Raft-like consensus of the reference.

Re-design of `cluster/coordination/` (SURVEY.md §2.3). Two pieces:

- `CoordinationState` — the safety core, a faithful port of the protocol
  semantics of `CoordinationState.java` (573 LoC), which SURVEY calls
  well-specified and deterministic-testable: terms with single join votes,
  election quorums over BOTH last-committed and last-accepted voting
  configurations (`isElectionQuorum:109`), two-phase publish/commit with
  the freshness invariant on accepted states.

- `Coordinator` — the liveness machinery (`Coordinator.java`, 1,467 LoC):
  CANDIDATE/LEADER/FOLLOWER modes, randomized election backoff
  (`ElectionSchedulerFactory.java:47`), leader→follower heartbeats
  (`FollowersChecker.java:64`), follower→leader checks
  (`LeaderChecker.java:62`), join handling, node-left removal, and
  publication fan-out (`Publication.java:255`). Scheduling and messaging go
  through injected abstractions so the whole thing runs identically on the
  deterministic simulator (tests) and on the asyncio TCP transport
  (production).

Safety invariants the simulation suite asserts:
  * at most one leader per term;
  * a committed (term, version) is never lost by later leaders;
  * accepted states only move forward in (term, version) order.
"""

from __future__ import annotations

import random
import zlib
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.state import (
    ClusterState, DiscoveryNode, VotingConfiguration,
)

CANDIDATE = "CANDIDATE"
LEADER = "LEADER"
FOLLOWER = "FOLLOWER"

# transport action names (reference: JoinHelper / PublicationTransportHandler)
START_JOIN_ACTION = "internal:cluster/coordination/start_join"
JOIN_ACTION = "internal:cluster/coordination/join"
PUBLISH_ACTION = "internal:cluster/coordination/publish_state"
COMMIT_ACTION = "internal:cluster/coordination/commit_state"
FOLLOWER_CHECK_ACTION = "internal:coordination/fault_detection/follower_check"
LEADER_CHECK_ACTION = "internal:coordination/fault_detection/leader_check"
PEER_FIND_ACTION = "internal:discovery/request_peers"


class CoordinationError(Exception):
    pass


class PersistedState:
    """Durable (term, lastAcceptedState) — reference: gateway
    PersistedClusterStateService (§2.10); in-memory for tests, file-backed in
    production (gateway.py)."""

    def __init__(self, term: int = 0, state: Optional[ClusterState] = None):
        self.current_term = term
        self.last_accepted = state or ClusterState()

    def set_term(self, term: int) -> None:
        self.current_term = term

    def set_last_accepted(self, state: ClusterState) -> None:
        self.last_accepted = state

    def mark_committed(self) -> None:
        pass


class CoordinationState:
    """Safety core. All mutations validate preconditions and raise
    CoordinationError on violations, mirroring CoordinationState.java."""

    def __init__(self, node_id: str, persisted: PersistedState):
        self.node_id = node_id
        self.persisted = persisted
        self.join_votes: Set[str] = set()
        self.election_won = False
        self.publish_votes: Set[str] = set()
        self.last_published_version = 0
        self.last_published_config = VotingConfiguration.EMPTY

    # -- accessors ------------------------------------------------------------
    @property
    def current_term(self) -> int:
        return self.persisted.current_term

    @property
    def last_accepted(self) -> ClusterState:
        return self.persisted.last_accepted

    @property
    def last_accepted_term(self) -> int:
        return self.last_accepted.term

    @property
    def last_accepted_version(self) -> int:
        return self.last_accepted.version

    def is_election_quorum(self, votes: Set[str]) -> bool:
        """Quorum in BOTH the last-committed and last-accepted configs
        (`isElectionQuorum:109`) — the key to safe reconfiguration."""
        return (self.last_accepted.last_committed_config.has_quorum(votes)
                and self.last_accepted.last_accepted_config.has_quorum(votes))

    def is_publish_quorum(self, votes: Set[str]) -> bool:
        return (self.last_accepted.last_committed_config.has_quorum(votes)
                and self.last_published_config.has_quorum(votes))

    # -- elections ------------------------------------------------------------
    def handle_start_join(self, source_node: str, term: int) -> dict:
        """A candidate asked us to join its term; grants at most one join
        per term (`handleStartJoin:170`)."""
        if term <= self.current_term:
            raise CoordinationError(
                f"incoming term {term} not greater than current term {self.current_term}")
        self.persisted.set_term(term)
        self.join_votes = set()
        self.election_won = False
        self.publish_votes = set()
        self.last_published_version = 0
        self.last_published_config = VotingConfiguration.EMPTY
        return {"source": self.node_id, "target": source_node, "term": term,
                "last_accepted_term": self.last_accepted_term,
                "last_accepted_version": self.last_accepted_version}

    def handle_join(self, join: dict) -> bool:
        """Candidate-side: count a vote. The freshness check guarantees the
        winner's accepted state is at least as recent as any voter's
        (`handleJoin` safety argument)."""
        if join["term"] != self.current_term:
            raise CoordinationError(
                f"join term {join['term']} != current term {self.current_term}")
        last_term, last_version = join["last_accepted_term"], join["last_accepted_version"]
        if last_term > self.last_accepted_term or (
                last_term == self.last_accepted_term
                and last_version > self.last_accepted_version):
            raise CoordinationError(
                "joining node has a fresher accepted state than the candidate")
        self.join_votes.add(join["source"])
        prev = self.election_won
        self.election_won = self.is_election_quorum(self.join_votes)
        return self.election_won and not prev

    # -- publication ----------------------------------------------------------
    def handle_client_value(self, state: ClusterState) -> dict:
        """Leader proposes the next state (`handleClientValue`)."""
        if not self.election_won:
            raise CoordinationError("cannot publish: election not won")
        if state.term != self.current_term:
            raise CoordinationError(
                f"proposed state term {state.term} != current term {self.current_term}")
        if state.version <= max(self.last_published_version, self.last_accepted_version):
            raise CoordinationError(
                f"proposed version {state.version} not ahead of published "
                f"{self.last_published_version} / accepted {self.last_accepted_version}")
        self.publish_votes = set()
        self.last_published_version = state.version
        self.last_published_config = state.last_accepted_config
        return {"term": state.term, "version": state.version,
                "state": state.to_dict()}

    def handle_publish_request(self, request: dict) -> dict:
        """Any node accepts a proposal newer than what it has
        (`handlePublishRequest`)."""
        term, version = request["term"], request["version"]
        if term != self.current_term:
            raise CoordinationError(
                f"publish term {term} != current term {self.current_term}")
        if term == self.last_accepted_term and version <= self.last_accepted_version:
            raise CoordinationError(
                f"publish version {version} not newer than accepted "
                f"{self.last_accepted_version} in same term")
        state = ClusterState.from_dict(request["state"])
        self.persisted.set_last_accepted(state)
        return {"source": self.node_id, "term": term, "version": version}

    def handle_publish_response(self, response: dict) -> Optional[dict]:
        """Leader-side: count acks; at quorum emit the commit
        (`handlePublishResponse`)."""
        if not self.election_won:
            raise CoordinationError("not the elected leader")
        if response["term"] != self.current_term or \
                response["version"] != self.last_published_version:
            raise CoordinationError("publish response for a different round")
        self.publish_votes.add(response["source"])
        if self.is_publish_quorum(self.publish_votes):
            return {"term": response["term"], "version": response["version"]}
        return None

    def handle_commit(self, commit: dict) -> ClusterState:
        """Any node marks its accepted state committed (`handleCommit`)."""
        if commit["term"] != self.current_term:
            raise CoordinationError(
                f"commit term {commit['term']} != current term {self.current_term}")
        if commit["term"] != self.last_accepted_term or \
                commit["version"] != self.last_accepted_version:
            raise CoordinationError("commit does not match accepted state")
        committed = self.last_accepted.with_(
            last_committed_config=self.last_accepted.last_accepted_config)
        self.persisted.set_last_accepted(committed)
        self.persisted.mark_committed()
        return committed


def bootstrap_state(initial_master_nodes: List[str],
                    cluster_name: str = "tpu-search") -> ClusterState:
    """Initial cluster formation (`ClusterBootstrapService`): a version-0
    state whose voting configuration is the configured initial master nodes.
    Every node persists this same state before first start."""
    config = VotingConfiguration(initial_master_nodes)
    return ClusterState(term=0, version=0, cluster_name=cluster_name,
                        nodes={},
                        last_committed_config=config,
                        last_accepted_config=config)


class Coordinator:
    """Liveness: mode transitions, elections, heartbeats, publication."""

    def __init__(self, node: DiscoveryNode, persisted: PersistedState,
                 transport, scheduler, seed_peers: List[str],
                 on_committed: Optional[Callable[[ClusterState], None]] = None,
                 election_min_ms: int = 100, election_max_ms: int = 1000,
                 heartbeat_interval_ms: int = 500, fault_timeout_ms: int = 3000,
                 rng: Optional[random.Random] = None):
        self.node = node
        self.state = CoordinationState(node.node_id, persisted)
        self.transport = transport
        self.scheduler = scheduler     # DeterministicTaskQueue-compatible
        self.seed_peers = list(seed_peers)
        self.on_committed = on_committed or (lambda s: None)
        self.mode = CANDIDATE
        self.known_leader: Optional[str] = None
        self.last_leader_ping_ms = 0
        self.election_min_ms = election_min_ms
        self.election_max_ms = election_max_ms
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.fault_timeout_ms = fault_timeout_ms
        # stable seed: builtin hash() varies per process (PYTHONHASHSEED),
        # which made election timing nondeterministic across test runs
        self.rng = rng or random.Random(
            zlib.crc32(node.node_id.encode("utf-8")))
        self.committed_state: ClusterState = persisted.last_accepted
        self.stopped = False
        self._election_round = 0
        # joiner transport addresses learned from join requests, published
        # in DiscoveryNode.address so every node can dial every other
        # (reference: JoinRequest carries the joining DiscoveryNode)
        self._join_addresses: Dict[str, str] = {}
        # full DiscoveryNode dicts from join requests (roles/attributes)
        self._join_nodes: Dict[str, dict] = {}
        # client acks gated on COMMIT, not publish-start: (term, version,
        # callback(bool)) fired from _apply_committed, failed on demotion
        # (reference: MasterService ack listeners / publish listener)
        self._commit_waiters: List[Tuple[int, int, Callable[[bool], None]]] = []
        # MasterService task batching (MasterService.submitStateUpdateTask
        # + TaskBatcher): queued updaters coalesce into one publication per
        # drain; `cluster.pending_tasks` introspects this queue
        self._pending_tasks: List[dict] = []
        self._executing_tasks: List[dict] = []
        self._task_insert_order = 0
        self._drain_scheduled = False
        self._publication_inflight = False
        # optional hook: (state, added_ids, removed_ids) -> state, applied by
        # the leader after membership changes so shard allocation follows
        # node join/leave (reference: AllocationService wired into
        # JoinTaskExecutor / NodeRemovalClusterStateTaskExecutor)
        self.membership_listener: Optional[Callable[[ClusterState, Set[str], Set[str]], ClusterState]] = None
        self._register_handlers()

    # ------------------------------------------------------------------ wiring
    def _register_handlers(self) -> None:
        t = self.transport
        me = self.node.node_id
        t.register(me, START_JOIN_ACTION, self._on_start_join)
        t.register(me, JOIN_ACTION, self._on_join)
        t.register(me, PUBLISH_ACTION, self._on_publish)
        t.register(me, COMMIT_ACTION, self._on_commit)
        t.register(me, FOLLOWER_CHECK_ACTION, self._on_follower_check)
        t.register(me, LEADER_CHECK_ACTION, self._on_leader_check)
        t.register(me, PEER_FIND_ACTION, self._on_peer_find)

    def start(self) -> None:
        self._schedule_election()
        self._schedule_fault_check()

    def stop(self) -> None:
        self.stopped = True

    # -------------------------------------------------------------- elections
    def _schedule_election(self) -> None:
        """(Re)start the election timer chain. A generation token ensures at
        most ONE live chain: demotions bump the generation, orphaning any
        older chain at its next tick, and the chain dies on leaving CANDIDATE
        instead of ticking for the node's lifetime."""
        if self.stopped:
            return
        self._election_generation = getattr(self, "_election_generation", 0) + 1
        self._chain_election(self._election_generation)

    def _chain_election(self, generation: int) -> None:
        self._election_round += 1
        # randomized backoff grows with consecutive failed rounds
        upper = min(self.election_max_ms * self._election_round, 10 * self.election_max_ms)
        delay = self.rng.randint(self.election_min_ms, max(upper, self.election_min_ms + 1))

        def maybe_elect():
            if self.stopped or generation != self._election_generation:
                return  # orphaned chain: a newer chain owns elections now
            if self.mode != CANDIDATE:
                return  # chain ends; _become_candidate starts a fresh one
            # pre-vote round (PreVoteCollector + JoinHelper): probe peers
            # first — if a live leader exists, JOIN it at its term instead
            # of starting a term-bumping election that would destabilize
            # the whole cluster just to admit one node
            self._pre_vote_then_elect(generation)
            self._chain_election(generation)

        self.scheduler.schedule_in(delay, maybe_elect, f"election:{self.node.node_id}")

    def _pre_vote_then_elect(self, generation: int) -> None:
        targets = sorted(self._broadcast_targets() - {self.node.node_id})
        if not targets:
            self._start_election()
            return
        poll = {"pending": len(targets), "leader": None, "term": 0,
                "done": False}

        def finish():
            if poll["done"]:
                return
            poll["done"] = True
            if self.stopped or self.mode != CANDIDATE \
                    or generation != self._election_generation:
                return
            leader, term = poll["leader"], poll["term"]
            if leader and leader != self.node.node_id \
                    and term >= self.state.current_term:
                self._send_join_to_leader(leader, term)
            else:
                self._start_election()

        def one(resp):
            if isinstance(resp, dict) and resp.get("leader") \
                    and resp.get("term", 0) >= self.state.current_term:
                if resp["term"] >= poll["term"]:
                    poll["leader"], poll["term"] = resp["leader"], resp["term"]
            poll["pending"] -= 1
            if poll["pending"] == 0:
                finish()

        for target in targets:
            self.transport.send(self.node.node_id, target, PEER_FIND_ACTION,
                                {"source": self.node.node_id},
                                on_response=one,
                                on_failure=lambda _e: one(None))
        # lost responses must not stall the chain: close the poll after a
        # beat either way (the chain's next tick re-probes)
        self.scheduler.schedule_in(1000, finish,
                                   f"pre_vote_close:{self.node.node_id}")

    def _send_join_to_leader(self, leader: str, term: int) -> None:
        """JoinHelper.sendJoinRequest analog: adopt the live leader's term
        and hand it our join; the leader adds us and the publication makes
        us a follower — no election, no disruption."""
        if term > self.state.current_term:
            try:
                join = self.state.handle_start_join(leader, term)
            except CoordinationError:
                return
        else:
            join = {"source": self.node.node_id, "target": leader,
                    "term": self.state.current_term,
                    "last_accepted_term": self.state.last_accepted_term,
                    "last_accepted_version": self.state.last_accepted_version}
        join["address"] = self.node.address
        join["node"] = self.node.to_dict()
        # tpulint: disable=TPU010(join is fire-and-forget by protocol design: a lost join is retried by the election timeout, not a callback)
        self.transport.send(self.node.node_id, leader, JOIN_ACTION, join)

    def _voting_nodes(self) -> Set[str]:
        config = (self.state.last_accepted.last_accepted_config.node_ids
                  | self.state.last_accepted.last_committed_config.node_ids)
        return set(config) if config else set(self.seed_peers) | {self.node.node_id}

    def _broadcast_targets(self) -> Set[str]:
        return (set(self.seed_peers) | set(self.state.last_accepted.nodes)
                | self._voting_nodes() | {self.node.node_id})

    def _start_election(self) -> None:
        term = self.state.current_term + 1
        for target in sorted(self._broadcast_targets()):
            # tpulint: disable=TPU010(election liveness comes from the randomized election timer rescheduling itself, never from per-message callbacks)
            self.transport.send(self.node.node_id, target, START_JOIN_ACTION,
                                {"source": self.node.node_id, "term": term})

    def _on_start_join(self, sender: str, request: dict, respond) -> None:
        try:
            join = self.state.handle_start_join(request["source"], request["term"])
        except CoordinationError:
            return
        # a higher term always knocks a leader/follower back to candidate
        if self.mode != CANDIDATE:
            self._become_candidate("received start-join for a newer term")
        join["address"] = self.node.address  # so the leader can publish it
        # full node identity (roles, awareness attributes) travels with the
        # join (reference: JoinRequest carries the joining DiscoveryNode)
        join["node"] = self.node.to_dict()
        # tpulint: disable=TPU010(a lost join after start-join is retried by the next election round; the protocol has no per-join failure path)
        self.transport.send(self.node.node_id, request["source"], JOIN_ACTION, join)
        respond({"ack": True})

    def _on_join(self, sender: str, join: dict, respond) -> None:
        if join.get("address"):
            self._join_addresses[join["source"]] = join["address"]
        if join.get("node"):
            self._join_nodes[join["source"]] = join["node"]
        try:
            won_now = self.state.handle_join(join)
        except CoordinationError:
            return
        if won_now and self.mode == CANDIDATE:
            self._become_leader()
        elif self.mode == LEADER and join["term"] == self.state.current_term:
            # node joining an established leader → add to the cluster
            self._leader_add_node(join["source"])
        respond({"ack": True})

    def _become_leader(self) -> None:
        self.mode = LEADER
        self.known_leader = self.node.node_id
        # fresh grace for every follower: stale pre-election timestamps must
        # not count against nodes under the new reign
        self._follower_last_ok = {}
        self._publish_first_state()
        self._schedule_heartbeat()

    def _become_candidate(self, reason: str) -> None:
        if self.mode == CANDIDATE:
            return
        self.mode = CANDIDATE
        self.known_leader = None
        self._election_round = 0
        self._fail_commit_waiters()
        self._schedule_election()

    def _become_follower(self, leader_id: str) -> None:
        was_leader = self.mode == LEADER
        self.mode = FOLLOWER
        self.known_leader = leader_id
        self.last_leader_ping_ms = self.scheduler.now_ms
        if was_leader:
            self._fail_commit_waiters()

    def _fail_commit_waiters(self) -> None:
        """Uncommitted client updates die with the leadership: fail their
        waiters so callers retry against the next master."""
        waiters, self._commit_waiters = self._commit_waiters, []
        for _, _, cb in waiters:
            try:
                cb(False)
            except Exception:
                pass

    # ------------------------------------------------------------ publication
    def _next_state_base(self) -> ClusterState:
        return self.state.last_accepted

    def _publish_first_state(self) -> None:
        base = self._next_state_base()
        nodes = dict(base.nodes)
        nodes[self.node.node_id] = self.node
        for voter in sorted(self.state.join_votes):
            if voter in self._join_nodes:
                nodes.setdefault(voter,
                                 DiscoveryNode.from_dict(self._join_nodes[voter]))
            else:
                nodes.setdefault(voter, DiscoveryNode(
                    voter, address=self._join_addresses.get(voter, "")))
        config = self._choose_voting_config(nodes)
        state = base.with_(
            term=self.state.current_term,
            version=max(base.version, self.state.last_published_version) + 1,
            master_node_id=self.node.node_id, nodes=nodes,
            last_accepted_config=config)
        if self.membership_listener is not None:
            # nodes (re)joining via election-time join votes must trigger
            # allocation just like post-election joins, or shards left
            # unassigned by their departure never re-allocate
            added = set(nodes) - set(base.nodes)
            removed = set(base.nodes) - set(nodes)
            state = self.membership_listener(state, added, removed)
        self._publish(state)

    def submit_state_update(self, source: str,
                            updater: Callable[[ClusterState], ClusterState],
                            on_committed_result: Optional[
                                Callable[[bool], None]] = None) -> None:
        """Batched MasterService entry (MasterService.submitStateUpdateTask
        :133,197): tasks queue and coalesce — all tasks queued while a
        publication is in flight apply over ONE base state and publish
        once, so e.g. a dynamic-mapping storm from concurrent bulks costs
        O(1) publications, not O(requests)."""
        import time as _time
        self._task_insert_order += 1
        self._pending_tasks.append({
            "insert_order": self._task_insert_order, "source": source,
            "updater": updater, "cb": on_committed_result,
            "queued_at": _time.time(), "executing": False})
        self._maybe_drain_tasks()

    def pending_tasks(self) -> List[dict]:
        """`_cluster/pending_tasks` view: queued AND currently-executing
        tasks (the reference shows in-flight tasks too)."""
        import time as _time
        now = _time.time()
        out = []
        for t in self._executing_tasks + self._pending_tasks:
            ms = max(int((now - t["queued_at"]) * 1000), 0)
            out.append({"insert_order": t["insert_order"],
                        "priority": "NORMAL", "source": t["source"],
                        "executing": t["executing"],
                        "time_in_queue_millis": ms,
                        "time_in_queue": f"{ms}ms"})
        return out

    def _maybe_drain_tasks(self) -> None:
        if self._drain_scheduled:
            return
        self._drain_scheduled = True
        self.scheduler.schedule(self._drain_tasks,
                                f"master_task_drain:{self.node.node_id}")

    def _drain_tasks(self) -> None:
        self._drain_scheduled = False
        if not self._pending_tasks:
            return
        if self._publication_inflight:
            # keep queueing behind the in-flight publication; drain again
            # when it commits (the commit callback re-arms us)
            return
        batch, self._pending_tasks = self._pending_tasks, []
        for t in batch:
            t["executing"] = True
        self._executing_tasks = batch

        def composite(base: ClusterState) -> ClusterState:
            st = base
            for t in batch:
                try:
                    st = t["updater"](st)
                except Exception as e:  # one bad task must not sink the batch
                    t["error"] = e
            return st

        self._publication_inflight = True

        def done(ok: bool) -> None:
            self._publication_inflight = False
            self._executing_tasks = []
            for t in batch:
                cb = t["cb"]
                if cb is not None:
                    cb(False if "error" in t else ok)
            if self._pending_tasks:
                self._maybe_drain_tasks()

        self.publish_state_update(composite, done)

    def publish_state_update(self, updater: Callable[[ClusterState], ClusterState],
                             on_committed_result: Optional[Callable[[bool], None]] = None) -> bool:
        """MasterService entry: compute and publish the next state.

        on_committed_result(ok): fired True once the state COMMITS (never on
        mere publish-start — a stale leader's publish can be rejected by a
        newer term, and acking early loses the change silently), False if
        this leader steps down before commit. A no-op update fires True
        immediately."""
        if self.mode != LEADER:
            if on_committed_result:
                on_committed_result(False)
            return False
        base = self._next_state_base()
        new_state = updater(base)
        if new_state is base:
            if on_committed_result:
                on_committed_result(True)
            return False
        new_state = new_state.with_(
            term=self.state.current_term,
            version=max(base.version, self.state.last_published_version) + 1,
            master_node_id=self.node.node_id)
        if on_committed_result:
            self._commit_waiters.append(
                (new_state.term, new_state.version, on_committed_result))
        self._publish(new_state)
        return True

    def _choose_voting_config(self, nodes: Dict[str, DiscoveryNode]) -> VotingConfiguration:
        """Reconfigurator (`Reconfigurator.java:38`): largest odd subset of
        master-eligible live nodes, keeping the current config's members
        preferred for stability."""
        eligible = sorted(n.node_id for n in nodes.values() if n.is_master_eligible)
        if not eligible:
            return self.state.last_accepted.last_accepted_config
        count = len(eligible) if len(eligible) % 2 == 1 else len(eligible) - 1
        current = self.state.last_accepted.last_accepted_config.node_ids
        preferred = sorted(eligible, key=lambda n: (n not in current, n))
        return VotingConfiguration(preferred[:max(count, 1)])

    def _publish(self, state: ClusterState) -> None:
        try:
            request = self.state.handle_client_value(state)
        except CoordinationError:
            return
        # publication timeout (reference: Coordinator.publishTimeout →
        # becomeCandidate): a leader that cannot commit steps down, which is
        # what lets a healed stale leader re-enter the election flow
        publish_term, publish_version = state.term, state.version

        def check_committed():
            if (self.mode == LEADER
                    and self.state.current_term == publish_term
                    and self.committed_state.version < publish_version):
                self._become_candidate("publication timed out without commit")

        self.scheduler.schedule_in(self.fault_timeout_ms, check_committed,
                                   f"publish_timeout:{self.node.node_id}")
        # self-ack first (the leader accepts its own proposal)
        try:
            response = self.state.handle_publish_request(request)
            self._count_publish_response(response, state)
        except CoordinationError:
            pass
        for target in sorted(set(state.nodes) - {self.node.node_id}):
            # tpulint: disable=TPU010(publication is quorum-joined and bounded by the publish_timeout timer armed above; a lost ack is just a missing vote)
            self.transport.send(
                self.node.node_id, target, PUBLISH_ACTION, request,
                on_response=lambda resp, s=state: self._count_publish_response(resp, s))

    def _count_publish_response(self, response: dict, state: ClusterState) -> None:
        try:
            commit = self.state.handle_publish_response(response)
        except CoordinationError:
            return
        if commit is not None:
            # quorum reached: commit locally and broadcast
            try:
                committed = self.state.handle_commit(commit)
                self._apply_committed(committed)
            except CoordinationError:
                pass
            for target in sorted(set(state.nodes) - {self.node.node_id}):
                # tpulint: disable=TPU010(a follower that misses the commit learns the state from the next publication or leader-check; no callback can help)
                self.transport.send(self.node.node_id, target, COMMIT_ACTION, commit)

    def _on_publish(self, sender: str, request: dict, respond) -> None:
        if request["term"] > self.state.current_term:
            # implicit join of a newer term via publication (reference:
            # Coordinator#handlePublishRequest joins the term)
            try:
                self.state.handle_start_join(sender, request["term"])
            except CoordinationError:
                pass
        try:
            response = self.state.handle_publish_request(request)
        except CoordinationError:
            return
        master = request["state"].get("master_node")
        if master and master != self.node.node_id:
            self._become_follower(master)
        respond(response)

    def _on_commit(self, sender: str, commit: dict, respond) -> None:
        try:
            committed = self.state.handle_commit(commit)
        except CoordinationError:
            return
        self._apply_committed(committed)
        respond({"ack": True})

    def _apply_committed(self, state: ClusterState) -> None:
        if state.version <= self.committed_state.version and \
                state.term <= self.committed_state.term:
            if (state.term, state.version) <= (self.committed_state.term,
                                               self.committed_state.version):
                return
        self.committed_state = state
        self.last_leader_ping_ms = self.scheduler.now_ms
        if self._commit_waiters:
            # success only for SAME-term publications at or below the
            # committed version; a commit from a NEWER term supersedes this
            # leader's uncommitted updates — those must fail (retry), never
            # false-ack on another leader's unrelated commit
            done, failed, keep = [], [], []
            for t, v, cb in self._commit_waiters:
                if t == state.term and v <= state.version:
                    done.append(cb)
                elif t < state.term:
                    failed.append(cb)
                else:
                    keep.append((t, v, cb))
            self._commit_waiters = keep
            for cb in done:
                try:
                    cb(True)
                except Exception:
                    pass
            for cb in failed:
                try:
                    cb(False)
                except Exception:
                    pass
        self.on_committed(state)

    # ---------------------------------------------------------- reconfiguration
    def _leader_add_node(self, node_id: str) -> None:
        # a (re)joining node gets a fresh fault-detection grace period: a
        # stale last-ok stamp from before it left must not instantly
        # re-remove it (the bug class: rejoin-then-removed loops)
        self._follower_last_ok = getattr(self, "_follower_last_ok", {})
        self._follower_last_ok[node_id] = self.scheduler.now_ms

        def add(base: ClusterState) -> ClusterState:
            addr = self._join_addresses.get(node_id, "")
            existing = base.nodes.get(node_id)
            if existing is not None and (not addr or existing.address == addr):
                return base
            nodes = dict(base.nodes)
            if node_id in self._join_nodes:
                nodes[node_id] = DiscoveryNode.from_dict(self._join_nodes[node_id])
            else:
                nodes[node_id] = DiscoveryNode(
                    node_id, address=addr or (existing.address if existing else ""))
            state = base.with_(nodes=nodes,
                               last_accepted_config=self._choose_voting_config(nodes))
            if self.membership_listener is not None:
                state = self.membership_listener(state, {node_id}, set())
            return state

        self.publish_state_update(add)

    def _leader_remove_node(self, node_id: str) -> None:
        def remove(base: ClusterState) -> ClusterState:
            if node_id not in base.nodes:
                return base
            nodes = dict(base.nodes)
            nodes.pop(node_id)
            state = base.with_(nodes=nodes,
                               last_accepted_config=self._choose_voting_config(nodes))
            if self.membership_listener is not None:
                state = self.membership_listener(state, set(), {node_id})
            return state

        self.publish_state_update(remove)

    # ------------------------------------------------------------ fault checks
    def _schedule_heartbeat(self) -> None:
        if self.stopped or self.mode != LEADER:
            return

        def beat():
            if self.stopped or self.mode != LEADER:
                return
            for target in sorted(set(self.committed_state.nodes) - {self.node.node_id}):
                # tpulint: disable=TPU010(heartbeats are the failure detector itself: a silent follower is detected by _check_followers aging, not by a send callback)
                self.transport.send(
                    self.node.node_id, target, FOLLOWER_CHECK_ACTION,
                    {"term": self.state.current_term, "leader": self.node.node_id},
                    on_response=lambda resp, t=target:
                    self._on_follower_check_response(t, resp))
            self._check_followers()
            self._schedule_heartbeat()

        self.scheduler.schedule_in(self.heartbeat_interval_ms, beat,
                                   f"heartbeat:{self.node.node_id}")

    def _note_follower_ok(self, node_id: str) -> None:
        self._follower_last_ok = getattr(self, "_follower_last_ok", {})
        self._follower_last_ok[node_id] = self.scheduler.now_ms

    def _on_follower_check_response(self, node_id: str, resp) -> None:
        if isinstance(resp, dict) and resp.get("ack") is False:
            # the follower is at a newer term: we are a stale leader; step
            # down and rejoin rather than removing healthy nodes one by one
            if self.mode == LEADER and resp.get("term", 0) > self.state.current_term:
                self._become_candidate("follower reports a newer term")
            return
        self._note_follower_ok(node_id)

    def _check_followers(self) -> None:
        """Remove followers that missed fault_timeout of acks
        (`FollowersChecker` removal)."""
        last_ok = getattr(self, "_follower_last_ok", {})
        now = self.scheduler.now_ms
        for target in sorted(set(self.committed_state.nodes) - {self.node.node_id}):
            seen = last_ok.get(target)
            if seen is None:
                last_ok[target] = now  # grace period starts now
            elif now - seen > self.fault_timeout_ms:
                self._leader_remove_node(target)
        self._follower_last_ok = last_ok

    def _on_follower_check(self, sender: str, request: dict, respond) -> None:
        if request["term"] < self.state.current_term:
            # NACK with our term so the stale leader steps down and rejoins
            # the current term promptly, instead of silently timing us out
            # of the cluster (FollowersChecker responds with an exception
            # carrying the follower's term for the same reason)
            respond({"ack": False, "term": self.state.current_term})
            return
        if request["term"] > self.state.current_term:
            try:
                self.state.handle_start_join(sender, request["term"])
            except CoordinationError:
                pass
        if self.mode != FOLLOWER or self.known_leader != request["leader"]:
            self._become_follower(request["leader"])
        self.last_leader_ping_ms = self.scheduler.now_ms
        respond({"ack": True, "term": self.state.current_term})

    def _schedule_fault_check(self) -> None:
        if self.stopped:
            return

        def check():
            if self.stopped:
                return
            if self.mode == FOLLOWER and \
                    self.scheduler.now_ms - self.last_leader_ping_ms > self.fault_timeout_ms:
                self._become_candidate("leader check timeout")
            self._schedule_fault_check()

        self.scheduler.schedule_in(self.heartbeat_interval_ms, check,
                                   f"leader_check:{self.node.node_id}")

    def _on_leader_check(self, sender: str, request: dict, respond) -> None:
        respond({"is_leader": self.mode == LEADER, "term": self.state.current_term})

    def _on_peer_find(self, sender: str, request: dict, respond) -> None:
        respond({"leader": self.known_leader if self.mode != CANDIDATE else None,
                 "peers": sorted(self.committed_state.nodes),
                 "term": self.state.current_term})
