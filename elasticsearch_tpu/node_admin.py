"""Node feature services: scroll, async-search, tasks, templates, reindex
family, field caps, validate, explain.

Kept beside `node.py` (the document/search facade) the way the reference
splits TransportActions by package: scroll contexts (`SearchService` scroll
keepalives), async-search (`x-pack/async-search`), task manager
(`tasks/TaskManager.java:63`), index templates
(`MetaDataIndexTemplateService`), reindex/update-by-query/delete-by-query
(`modules/reindex`), field caps, query validation and explain.
"""

from __future__ import annotations

import fnmatch
import re
import threading
import time
import uuid as _uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError, ResourceNotFoundError, SearchEngineError,
)
from elasticsearch_tpu.common.settings import parse_time_value


class ScrollContext:
    __slots__ = ("scroll_id", "slices", "cursor", "body", "expiry",
                 "keep_alive", "total")

    def __init__(self, scroll_id, slices, body, keep_alive_s):
        self.scroll_id = scroll_id
        # slices: list of (svc, reader, rows, scores, sort_values)
        self.slices = slices
        self.cursor = 0
        self.body = body
        self.keep_alive = keep_alive_s
        self.expiry = time.time() + keep_alive_s
        self.total = len(slices)


class ScrollService:
    """Scroll cursors over point-in-time readers (reference:
    SearchService scroll contexts + SearchScrollAsyncAction)."""

    def __init__(self):
        self._contexts: Dict[str, ScrollContext] = {}

    def create(self, slices, body, keep_alive_s: float) -> str:
        scroll_id = _uuid.uuid4().hex
        self._contexts[scroll_id] = ScrollContext(scroll_id, slices, body, keep_alive_s)
        return scroll_id

    def get(self, scroll_id: str) -> ScrollContext:
        self.evict_expired()
        sc = self._contexts.get(scroll_id)
        if sc is None:
            raise ResourceNotFoundError(f"No search context found for id [{scroll_id}]",
                                        scroll_id=scroll_id)
        sc.expiry = time.time() + sc.keep_alive
        return sc

    def delete(self, scroll_id: str) -> bool:
        return self._contexts.pop(scroll_id, None) is not None

    def delete_all(self) -> int:
        n = len(self._contexts)
        self._contexts.clear()
        return n

    def evict_expired(self) -> None:
        now = time.time()
        for sid in [s for s, c in self._contexts.items() if c.expiry < now]:
            del self._contexts[sid]


class AsyncSearchService:
    """x-pack async-search shape: submit returns an id immediately; results
    are retrievable until deleted/expired. Executes on a worker thread."""

    def __init__(self):
        self._results: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def submit(self, run: Callable[[], dict],
               wait_for_completion_s: float = 1.0,
               keep_alive_s: float = 300.0) -> dict:
        search_id = _uuid.uuid4().hex
        entry = {"id": search_id, "is_running": True, "is_partial": True,
                 "start_time_in_millis": int(time.time() * 1000),
                 "expiration_time_in_millis": int((time.time() + keep_alive_s) * 1000),
                 "response": None, "error": None}
        with self._lock:
            self._results[search_id] = entry

        done = threading.Event()

        def work():
            try:
                resp = run()
                with self._lock:
                    entry["response"] = resp
            except SearchEngineError as e:
                with self._lock:
                    entry["error"] = e.to_dict()
            except Exception as e:  # never lose the terminal state
                with self._lock:
                    entry["error"] = {"type": "exception", "reason": str(e)}
            finally:
                with self._lock:
                    entry["is_running"] = False
                    entry["is_partial"] = False
                done.set()

        t = threading.Thread(target=work, daemon=True)
        t.start()
        done.wait(timeout=wait_for_completion_s)
        return self.status(search_id)

    def _evict_expired(self) -> None:
        now_ms = time.time() * 1000
        for sid in [s for s, e in self._results.items()
                    if not e["is_running"] and e["expiration_time_in_millis"] < now_ms]:
            del self._results[sid]

    def status(self, search_id: str) -> dict:
        with self._lock:
            self._evict_expired()
            entry = self._results.get(search_id)
            if entry is None:
                raise ResourceNotFoundError(f"async search [{search_id}] not found")
            out = {"id": search_id, "is_running": entry["is_running"],
                   "is_partial": entry["is_partial"],
                   "start_time_in_millis": entry["start_time_in_millis"],
                   "expiration_time_in_millis": entry["expiration_time_in_millis"]}
            if entry["response"] is not None:
                out["response"] = entry["response"]
            if entry["error"] is not None:
                out["error"] = entry["error"]
            return out

    def delete(self, search_id: str) -> bool:
        with self._lock:
            return self._results.pop(search_id, None) is not None


class Task:
    """One live in-flight request (TaskManager.java's Task + the
    CancellableTask headers): carries the caller's `X-Opaque-ID` and the
    request's trace so `GET _tasks` answers action / node / running time
    / opaque id / trace id / current span for anything in flight. The
    Task object itself is the cancellation token — the continuous
    batcher's EDF queue holds a reference and sheds queued entries the
    moment `cancelled` flips (serving/batcher.py `_claim_locked`)."""

    __slots__ = ("task_id", "action", "description", "start_ms",
                 "start_mono_ns", "cancellable", "cancelled", "status",
                 "opaque_id", "trace")

    def __init__(self, task_id, action, description, cancellable=True,
                 opaque_id=None, trace=None):
        self.task_id = task_id
        self.action = action
        self.description = description
        self.start_ms = int(time.time() * 1000)   # epoch for display only
        # running time is a DURATION: monotonic clock (tpulint TPU012's
        # wall-clock-duration bug class — time.time can step backwards)
        self.start_mono_ns = time.monotonic_ns()
        self.cancellable = cancellable
        self.cancelled = False
        self.status: dict = {}
        self.opaque_id = opaque_id
        self.trace = trace   # telemetry.trace.Trace | None

    def to_dict(self, node_id: str) -> dict:
        out = {"node": node_id, "id": int(self.task_id.split(":")[1]),
               "type": "transport", "action": self.action,
               "description": self.description,
               "start_time_in_millis": self.start_ms,
               "running_time_in_nanos":
                   time.monotonic_ns() - self.start_mono_ns,
               "cancellable": self.cancellable,
               "cancelled": self.cancelled,
               "headers": ({"X-Opaque-Id": self.opaque_id}
                           if self.opaque_id else {}),
               "status": self.status or None}
        if self.trace is not None:
            out["trace_id"] = self.trace.trace_id
            current = self.trace.current_span_name()
            if current is not None:
                out["current_span"] = current
        return out


class TaskManager:
    """Per-node task registry with cancellation (TaskManager.java:63)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._counter = 0
        self._tasks: Dict[str, Task] = {}
        self._lock = threading.Lock()

    def register(self, action: str, description: str = "",
                 cancellable: bool = True, opaque_id=None,
                 trace=None) -> Task:
        with self._lock:
            self._counter += 1
            task = Task(f"{self.node_id}:{self._counter}", action, description,
                        cancellable, opaque_id=opaque_id, trace=trace)
            self._tasks[task.task_id] = task
            return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.task_id, None)

    def list_tasks(self, actions: Optional[str] = None) -> List[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            patterns = actions.split(",")
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p) for p in patterns)]
        return tasks

    def get(self, task_id: str) -> Task:
        with self._lock:
            t = self._tasks.get(task_id)
        if t is None:
            node = str(task_id).rsplit(":", 1)[0]
            if node != self.node_id:
                raise ResourceNotFoundError(
                    f"task [{task_id}] belongs to the node [{node}] which "
                    f"isn't part of the cluster and there is no record of "
                    f"the task")
            raise ResourceNotFoundError(
                f"task [{task_id}] isn't running and hasn't stored its "
                f"results")
        return t

    def cancel(self, task_id: str) -> Task:
        t = self.get(task_id)
        if not t.cancellable:
            raise IllegalArgumentError(f"task [{task_id}] is not cancellable")
        t.cancelled = True
        return t


class TemplateService:
    """Index templates (legacy `_template` + composable `_index_template`):
    matched by index_patterns at index auto-creation, merged by priority."""

    def __init__(self):
        self.templates: Dict[str, dict] = {}          # legacy
        self.index_templates: Dict[str, dict] = {}    # composable

    def put(self, name: str, body: dict, composable: bool = False) -> None:
        store = self.index_templates if composable else self.templates
        patterns = body.get("index_patterns")
        if not patterns:
            raise IllegalArgumentError(
                "index template must define index_patterns: index "
                "patterns are missing")
        body = dict(body)
        # patterns normalize to a list (MetaDataIndexTemplateService)
        body["index_patterns"] = ([patterns] if isinstance(patterns, str)
                                  else list(patterns))
        store[name] = body

    def get(self, name: str, composable: bool = False) -> dict:
        store = self.index_templates if composable else self.templates
        if name not in store:
            raise ResourceNotFoundError(f"index template matching [{name}] not found")
        return store[name]

    def delete(self, name: str, composable: bool = False) -> None:
        store = self.index_templates if composable else self.templates
        if name not in store:
            raise ResourceNotFoundError(f"index template matching [{name}] not found")
        del store[name]

    def resolve(self, index_name: str) -> dict:
        """Merged settings/mappings/aliases for a new index."""
        matches: List[tuple] = []
        for name, t in self.templates.items():
            if any(fnmatch.fnmatch(index_name, p) for p in t.get("index_patterns", [])):
                matches.append((int(t.get("order", 0)), 0, name, t))
        for name, t in self.index_templates.items():
            if any(fnmatch.fnmatch(index_name, p) for p in t.get("index_patterns", [])):
                body = t.get("template", {})
                matches.append((int(t.get("priority", 0)), 1, name,
                                {**body, "index_patterns": t["index_patterns"]}))
        matches.sort(key=lambda m: (m[0], m[1]))
        settings: dict = {}
        mappings: dict = {"properties": {}}
        aliases: dict = {}
        for _, _, _, t in matches:
            settings.update(t.get("settings") or {})
            props = (t.get("mappings") or {}).get("properties") or {}
            mappings["properties"].update(props)
            aliases.update(t.get("aliases") or {})
        return {"settings": settings, "mappings": mappings, "aliases": aliases}


# ---------------------------------------------------------------------------
# reindex family — executed against the Node facade
# ---------------------------------------------------------------------------

def reindex(node, body: dict) -> dict:
    """POST /_reindex (reference: modules/reindex): scan source, bulk into
    dest, optional query filter + ingest pipeline + script."""
    src = body.get("source", {})
    dest = body.get("dest", {})
    if "index" not in src or "index" not in dest:
        raise IllegalArgumentError("reindex requires source.index and dest.index")
    query = src.get("query", {"match_all": {}})
    pipeline = dest.get("pipeline")
    script = body.get("script")
    max_docs = body.get("max_docs")
    task = node.tasks.register("indices:data/write/reindex",
                               f"reindex from [{src['index']}] to [{dest['index']}]")
    created = updated = 0
    failures = []
    try:
        docs = _scan_all(node, src["index"], query)
        for doc in docs:
            if task.cancelled or (max_docs is not None and created + updated >= max_docs):
                break
            source = doc["_source"]
            if script is not None:
                from elasticsearch_tpu.node import _apply_update_script
                ctx_doc = dict(source)
                source = _apply_update_script(ctx_doc, script)
            if pipeline is not None:
                source = node.ingest.execute(pipeline, dest["index"], doc["_id"], source)
                if source is None:
                    continue
            try:
                r = node.index_doc(dest["index"], doc["_id"], source)
                if r["result"] == "created":
                    created += 1
                else:
                    updated += 1
            except SearchEngineError as e:
                failures.append({"id": doc["_id"], "cause": e.to_dict()})
        for svc_name in {dest["index"]}:
            node.indices.get(svc_name).refresh()
    finally:
        node.tasks.unregister(task)
    return {"took": 0, "timed_out": False, "total": created + updated,
            "created": created, "updated": updated, "deleted": 0,
            "batches": 1, "version_conflicts": 0, "noops": 0,
            "retries": {"bulk": 0, "search": 0}, "failures": failures}


def update_by_query(node, index: str, body: dict) -> dict:
    query = (body or {}).get("query", {"match_all": {}})
    script = (body or {}).get("script")
    task = node.tasks.register("indices:data/write/update/byquery",
                               f"update-by-query [{index}]")
    updated = 0
    deleted = 0
    noops = 0
    failures = []
    try:
        for doc in _scan_all(node, index, query):
            if task.cancelled:
                break
            source = doc["_source"]
            op = "index"
            if script is not None:
                from elasticsearch_tpu.node import _apply_update_script
                verdict = {}
                try:
                    source = _apply_update_script(dict(source), script,
                                                  ctx_extra=verdict)
                except SearchEngineError as e:
                    failures.append({"id": doc["_id"], "cause": e.to_dict()})
                    continue
                op = verdict.get("op", "index")
            try:
                if op == "none":
                    noops += 1
                elif op == "delete":
                    node.delete_doc(doc["_index"], doc["_id"])
                    deleted += 1
                else:
                    node.index_doc(doc["_index"], doc["_id"], source,
                                   if_seq_no=doc.get("_seq_no"),
                                   if_primary_term=doc.get("_primary_term"))
                    updated += 1
            except SearchEngineError as e:
                failures.append({"id": doc["_id"], "cause": e.to_dict()})
        node.indices.get(index).refresh()
    finally:
        node.tasks.unregister(task)
    # total = every processed doc, failures included (the ES contract)
    return {"took": 0, "total": updated + deleted + noops + len(failures),
            "updated": updated, "deleted": deleted,
            "version_conflicts": len(failures), "noops": noops,
            "failures": failures}


def delete_by_query(node, index: str, body: dict) -> dict:
    query = (body or {}).get("query")
    if query is None:
        raise IllegalArgumentError("delete_by_query requires a query")
    task = node.tasks.register("indices:data/write/delete/byquery",
                               f"delete-by-query [{index}]")
    deleted = 0
    failures = []
    try:
        for doc in _scan_all(node, index, query):
            if task.cancelled:
                break
            try:
                node.delete_doc(doc["_index"], doc["_id"])
                deleted += 1
            except SearchEngineError as e:
                failures.append({"id": doc["_id"], "cause": e.to_dict()})
        node.indices.get(index).refresh()
    finally:
        node.tasks.unregister(task)
    return {"took": 0, "total": deleted, "deleted": deleted,
            "version_conflicts": len(failures), "failures": failures}


def _scan_all(node, index_expr: str, query: dict) -> List[dict]:
    """Materialize all matching docs (id + source + seqno) across indices."""
    out = []
    for svc in node.indices.resolve(index_expr):
        svc.refresh()
        reader = svc.combined_reader()
        from elasticsearch_tpu.search.queries import SearchContext, parse_query
        ctx = SearchContext(reader, svc.mapper_service)
        ds = parse_query(query).execute(ctx)
        for row in ds.rows:
            doc_id = reader.get_id(int(row))
            full = None
            shard = svc.shard_of_row(int(row))
            got = shard.engine.get(doc_id)
            if got is not None:
                import copy as _copy
                # deep copy: callers (reindex scripts/pipelines) mutate these
                out.append({"_index": svc.name, "_id": doc_id,
                            "_source": _copy.deepcopy(got["_source"]),
                            "_seq_no": got["_seq_no"],
                            "_primary_term": got["_primary_term"]})
    return out


# ---------------------------------------------------------------------------
# field caps / validate / explain
# ---------------------------------------------------------------------------

_AGGREGATABLE = {"keyword", "long", "integer", "short", "byte", "double", "float",
                 "half_float", "scaled_float", "date", "date_nanos", "boolean",
                 "ip", "geo_point", "unsigned_long", "version", "murmur3",
                 "token_count", "constant_keyword", "wildcard", "flattened",
                 "integer_range", "long_range", "float_range", "double_range",
                 "date_range", "ip_range", "histogram", "aggregate_metric_double"}


def _index_field_caps(ms) -> Dict[str, tuple]:
    """path -> (type, searchable, aggregatable, meta) for one index,
    including synthesized object entries for un-mapped ancestor paths
    (reference: FieldCapabilitiesFetcher walks object mappers too)."""
    caps: Dict[str, tuple] = {}
    for path, mapper in ms.all_mappers():
        t = mapper.type_name
        p = mapper.params
        if t == "nested":
            caps[path] = ("nested", False, False, None)
            continue
        searchable = p.get("index", True) not in (False, "false")
        aggregatable = (t in _AGGREGATABLE
                        and p.get("doc_values", True) not in (False, "false"))
        caps[path] = (t, searchable, aggregatable, p.get("meta"))
    for path in list(caps):
        parts = path.split(".")
        for i in range(1, len(parts)):
            anc = ".".join(parts[:i])
            if anc not in caps:
                caps[anc] = ("object", False, False, None)
    return caps


def field_caps(node, index_expr: Optional[str], fields: str,
               include_unmapped: bool = False) -> dict:
    """_field_caps (reference: TransportFieldCapabilitiesAction +
    FieldCapabilities.Builder merge rules): per (field, type) bucket,
    searchable/aggregatable AND across indices, `indices` listed only when
    the field has >1 type bucket, non_searchable/-aggregatable indices
    listed only when mixed, `meta` values unioned into sorted lists."""
    patterns = [f.strip() for f in (fields or "*").split(",")]
    indices = node.indices.resolve(index_expr)
    index_names = [s.name for s in indices]
    # field -> type -> list of (index, searchable, aggregatable, meta)
    percap: Dict[str, Dict[str, list]] = {}
    for svc in indices:
        for path, (t, se, ag, meta) in _index_field_caps(
                svc.mapper_service).items():
            if not any(fnmatch.fnmatch(path, p) for p in patterns):
                continue
            percap.setdefault(path, {}).setdefault(t, []).append(
                (svc.name, se, ag, meta))
    out: Dict[str, dict] = {}
    for field, types in sorted(percap.items()):
        mapped_in = {i for rows in types.values() for (i, _, _, _) in rows}
        buckets = dict(types)
        if include_unmapped and len(mapped_in) < len(index_names):
            buckets["unmapped"] = [(i, False, False, None)
                                   for i in index_names if i not in mapped_in]
        multi_typed = len(buckets) > 1
        rendered = {}
        for t, rows in buckets.items():
            entry = {
                "type": t, "metadata_field": False,
                "searchable": all(se for (_, se, _, _) in rows),
                "aggregatable": all(ag for (_, _, ag, _) in rows),
            }
            if multi_typed:
                entry["indices"] = sorted(i for (i, _, _, _) in rows)
            non_se = sorted(i for (i, se, _, _) in rows if not se)
            if non_se and len(non_se) < len(rows):
                entry["non_searchable_indices"] = non_se
            non_ag = sorted(i for (i, _, ag, _) in rows if not ag)
            if non_ag and len(non_ag) < len(rows):
                entry["non_aggregatable_indices"] = non_ag
            merged_meta: Dict[str, set] = {}
            for (_, _, _, meta) in rows:
                for k, v in (meta or {}).items():
                    vals = v if isinstance(v, list) else [v]
                    merged_meta.setdefault(k, set()).update(map(str, vals))
            if merged_meta:
                entry["meta"] = {k: sorted(v) for k, v in merged_meta.items()}
            rendered[t] = entry
        out[field] = rendered
    return {"indices": index_names, "fields": out}


def validate_query(node, index_expr: Optional[str], body: dict,
                   explain: bool = False) -> dict:
    from elasticsearch_tpu.search.queries import parse_query
    shards = {"total": 1, "successful": 1, "failed": 0}
    try:
        body = body or {}
        bad = [k for k in body if k not in ("query", "rewrite",
                                            "all_shards", "explain")]
        if bad:
            raise ParsingError(f"request does not support [{bad[0]}]")
        q = parse_query(body.get("query"))
        explanation = "*:*" if (body.get("query") is None
                                or "match_all" in (body.get("query") or {}))             else str(q.to_dict())
        out = {"valid": True, "_shards": shards}
        if explain:
            out["explanations"] = [{"index": s.name, "valid": True,
                                    "explanation": explanation}
                                   for s in node.indices.resolve(index_expr)]
        return out
    except (ParsingError, IllegalArgumentError) as e:
        out = {"valid": False, "_shards": shards}
        if explain:
            # rendered like the wrapped Java exception string the tests
            # match; parse errors carry the nested-chain suffix the real
            # toString has, "request does not support" stays bare
            msg = f"org.elasticsearch.common.ParsingException: {e}"
            if "request does not support" not in str(e):
                msg += f"; nested: ParsingException[{e}];"
            out["error"] = msg
        return out


def explain_doc(node, index: str, doc_id: str, body: dict,
                source_spec=None) -> dict:
    from elasticsearch_tpu.search.queries import SearchContext, parse_query
    if not body or "query" not in body:
        from elasticsearch_tpu.common.errors import (
            ActionRequestValidationError)
        raise ActionRequestValidationError(
            "Validation Failed: 1: query is missing;")
    svc = node.indices.get(index)
    svc.refresh()
    reader = svc.combined_reader()
    ctx = SearchContext(reader, svc.mapper_service)
    q = parse_query((body or {}).get("query"))
    ds = q.execute(ctx)
    target_rows = [int(r) for r in ds.rows if reader.get_id(int(r)) == doc_id]
    if not target_rows:
        doc_exists = any(reader.get_id(int(r)) == doc_id
                         for r in reader.live_global_rows())
        return {"_index": svc.name, "_id": doc_id, "matched": False,
                "explanation": {"value": 0.0,
                                "description": "no matching term" if doc_exists
                                else "document not found", "details": []}}
    idx = list(ds.rows).index(target_rows[0])
    score = float(ds.scores[idx]) if ds.scores is not None else 1.0
    out = {"_index": svc.name, "_id": doc_id, "matched": True,
           "explanation": {"value": score,
                           "description": f"score from query {q.to_dict()}",
                           "details": []}}
    if source_spec is not None and source_spec is not False:
        from elasticsearch_tpu.search.service import _filter_source
        src_doc = reader.get_source(target_rows[0]) or {}
        includes, excludes = source_spec
        out["get"] = {"found": True,
                      "_source": _filter_source(src_doc, includes, excludes)}
    return out
