"""Python client library for the tpu-search REST API.

Re-design of the reference's client stack (`client/rest` — the low-level
`RestClient` with host round-robin, dead-host marking and retries — and
`client/rest-high-level`'s typed request/response mirror, plus
`client/sniffer`). The high-level surface follows the namespaced layout
users of the reference's clients know: `client.search(...)`,
`client.indices.create(...)`, `client.cluster.health()`, `client.ml.*`.

Zero external dependencies: http.client over the framework's x-content
layer, so any of the four content types can be used on the wire.
"""

from __future__ import annotations

import http.client
import random
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.common.xcontent import XContentType


class TransportError(Exception):
    """Non-2xx response or no host reachable."""

    def __init__(self, status: int, message: str, body: Any = None):
        super().__init__(f"[{status}] {message}")
        self.status = status
        self.body = body


class ConnectionError_(TransportError):
    def __init__(self, message: str):
        super().__init__(0, message)


class Transport:
    """Low-level client (reference: client/rest RestClient.java —
    round-robin over hosts, dead-host cooldown, retry on connect failure)."""

    def __init__(self, hosts: Sequence[Union[str, Tuple[str, int]]],
                 timeout: float = 30.0, max_retries: int = 3,
                 content_type: str = XContentType.JSON,
                 dead_host_cooldown: float = 60.0,
                 use_ssl: bool = False, ssl_context=None,
                 ca_certs: Optional[str] = None,
                 ssl_assert_hostname: bool = True):
        # https scheme (or use_ssl=True) switches to TLS connections;
        # ca_certs verifies the server against a CA bundle. Hostname
        # verification stays ON unless explicitly opted out (certs
        # without the right SANs must not silently weaken TLS).
        self.use_ssl = use_ssl
        self.ssl_context = ssl_context
        if ssl_context is None:
            import ssl as _ssl
            self.ssl_context = _ssl.create_default_context(
                cafile=ca_certs) if ca_certs \
                else _ssl.create_default_context()
            if not ssl_assert_hostname:
                self.ssl_context.check_hostname = False
        self.hosts: List[Tuple[str, int]] = []
        for h in hosts:
            if isinstance(h, str):
                if "//" in h:
                    parsed = urllib.parse.urlsplit(h)
                    if parsed.scheme == "https":
                        self.use_ssl = True
                    self.hosts.append((parsed.hostname or "localhost",
                                       parsed.port or 9200))
                elif ":" in h:
                    name, _, port = h.partition(":")
                    self.hosts.append((name, int(port)))
                else:
                    self.hosts.append((h, 9200))
            else:
                self.hosts.append(tuple(h))  # type: ignore[arg-type]
        if not self.hosts:
            raise ValueError("at least one host is required")
        self.timeout = timeout
        self.max_retries = max_retries
        self.content_type = content_type
        self.dead_host_cooldown = dead_host_cooldown
        self._dead: Dict[Tuple[str, int], float] = {}
        self._rr = random.randrange(len(self.hosts))

    def _alive_hosts(self) -> List[Tuple[str, int]]:
        now = time.time()
        alive = [h for h in self.hosts
                 if self._dead.get(h, 0) <= now]
        return alive or list(self.hosts)  # all dead: try everything again

    def perform_request(self, method: str, path: str,
                        params: Optional[dict] = None,
                        body: Any = None,
                        raw_body: Optional[bytes] = None,
                        headers: Optional[dict] = None) -> Any:
        query = ""
        if params:
            query = "?" + urllib.parse.urlencode(
                {k: _param_str(v) for k, v in params.items() if v is not None})
        payload = raw_body
        hdrs = {"Accept": self.content_type}
        if payload is None and body is not None:
            payload = xcontent.dumps(body, self.content_type)
            hdrs["Content-Type"] = self.content_type
        elif raw_body is not None:
            hdrs["Content-Type"] = "application/x-ndjson"
        hdrs.update(headers or {})

        last_error: Optional[Exception] = None
        hosts = self._alive_hosts()
        for attempt in range(self.max_retries + 1):
            host, port = hosts[(self._rr + attempt) % len(hosts)]
            if self.use_ssl:
                conn = http.client.HTTPSConnection(
                    host, port, timeout=self.timeout,
                    context=self.ssl_context)
            else:
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=self.timeout)
            try:
                # connect separately: only connect-phase failures are safe
                # to retry — once the request is sent, a timeout may mean
                # the server is still executing it, and re-sending would
                # double-apply writes (reference clients default
                # retry_on_timeout=false for the same reason)
                conn.connect()
            except OSError as e:
                conn.close()
                self._dead[(host, port)] = time.time() + self.dead_host_cooldown
                last_error = e
                continue
            try:
                conn.request(method, path + query, body=payload, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except OSError as e:
                self._dead[(host, port)] = time.time() + self.dead_host_cooldown
                raise ConnectionError_(
                    f"request to {host}:{port} failed after send "
                    f"(not retried): {e}") from e
            finally:
                conn.close()
            self._rr = (self._rr + 1) % len(hosts)
            out = self._decode(resp.getheader("content-type"), data)
            if resp.status >= 300:
                reason = out
                if isinstance(out, dict):
                    err = out.get("error")
                    if isinstance(err, dict):
                        reason = err.get("reason", str(err))
                    elif err is not None:
                        reason = str(err)
                raise TransportError(resp.status, str(reason), out)
            return out
        raise ConnectionError_(
            f"no host reachable after {self.max_retries + 1} attempts: "
            f"{last_error}")

    @staticmethod
    def _decode(content_type: Optional[str], data: bytes) -> Any:
        if not data:
            return None
        ct = (content_type or "application/json").split(";")[0].strip()
        if ct.startswith("text/"):
            return data.decode("utf-8", "replace")
        try:
            return xcontent.loads(data, xcontent.XContentType.from_media_type(ct))
        except Exception:
            return data.decode("utf-8", "replace")


def _param_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _idx(index: str) -> str:
    """Percent-quote an index expression for the request path (commas and
    wildcards stay literal — multi-index expressions)."""
    return urllib.parse.quote(index, safe="*,")


def _doc_path(index: str, doc_id: Optional[str]) -> str:
    base = f"/{_idx(index)}/_doc"
    return base + (f"/{urllib.parse.quote(str(doc_id))}" if doc_id is not None
                   else "")


class _Namespace:
    def __init__(self, transport: Transport):
        self._t = transport


class IndicesClient(_Namespace):
    def create(self, index: str, body: Optional[dict] = None, **params):
        return self._t.perform_request("PUT", f"/{_idx(index)}", params, body)

    def delete(self, index: str, **params):
        return self._t.perform_request("DELETE", f"/{_idx(index)}", params)

    def exists(self, index: str) -> bool:
        try:
            self._t.perform_request("HEAD", f"/{_idx(index)}")
            return True
        except TransportError as e:
            if e.status == 404:
                return False
            raise

    def refresh(self, index: str = "_all", **params):
        return self._t.perform_request("POST", f"/{_idx(index)}/_refresh", params)

    def get(self, index: str, **params):
        return self._t.perform_request("GET", f"/{_idx(index)}", params)

    def get_mapping(self, index: str, **params):
        return self._t.perform_request("GET", f"/{_idx(index)}/_mapping", params)

    def put_mapping(self, index: str, body: dict, **params):
        return self._t.perform_request("PUT", f"/{_idx(index)}/_mapping", params,
                                       body)

    def get_settings(self, index: str, **params):
        return self._t.perform_request("GET", f"/{_idx(index)}/_settings", params)

    def put_settings(self, body: dict, index: str = "_all", **params):
        return self._t.perform_request("PUT", f"/{_idx(index)}/_settings", params,
                                       body)

    def stats(self, index: str = "_all", **params):
        return self._t.perform_request("GET", f"/{_idx(index)}/_stats", params)

    def analyze(self, body: dict, index: Optional[str] = None, **params):
        path = f"/{_idx(index)}/_analyze" if index else "/_analyze"
        return self._t.perform_request("POST", path, params, body)

    def put_alias(self, index: str, name: str, **params):
        return self._t.perform_request("PUT", f"/{_idx(index)}/_alias/{name}",
                                       params)

    def put_template(self, name: str, body: dict, **params):
        return self._t.perform_request("PUT", f"/_template/{name}", params,
                                       body)

    def rollover(self, alias: str, body: Optional[dict] = None, **params):
        return self._t.perform_request("POST", f"/{_idx(alias)}/_rollover", params,
                                       body)

    def freeze(self, index: str, **params):
        return self._t.perform_request("POST", f"/{_idx(index)}/_freeze", params)

    def unfreeze(self, index: str, **params):
        return self._t.perform_request("POST", f"/{_idx(index)}/_unfreeze", params)

    def forcemerge(self, index: str = "_all", **params):
        return self._t.perform_request("POST", f"/{_idx(index)}/_forcemerge",
                                       params)


class ClusterClient(_Namespace):
    def health(self, **params):
        return self._t.perform_request("GET", "/_cluster/health", params)

    def stats(self, **params):
        return self._t.perform_request("GET", "/_cluster/stats", params)

    def state(self, **params):
        return self._t.perform_request("GET", "/_cluster/state", params)

    def put_settings(self, body: dict, **params):
        return self._t.perform_request("PUT", "/_cluster/settings", params,
                                       body)

    def get_settings(self, **params):
        return self._t.perform_request("GET", "/_cluster/settings", params)


class CatClient(_Namespace):
    def _cat(self, what: str, **params):
        params.setdefault("format", "json")
        return self._t.perform_request("GET", f"/_cat/{what}", params)

    def indices(self, **params):
        return self._cat("indices", **params)

    def shards(self, **params):
        return self._cat("shards", **params)

    def health(self, **params):
        return self._cat("health", **params)

    def nodes(self, **params):
        return self._cat("nodes", **params)

    def count(self, **params):
        return self._cat("count", **params)


class IngestClient(_Namespace):
    def put_pipeline(self, pipeline_id: str, body: dict, **params):
        return self._t.perform_request("PUT",
                                       f"/_ingest/pipeline/{pipeline_id}",
                                       params, body)

    def get_pipeline(self, pipeline_id: str = "*", **params):
        return self._t.perform_request("GET",
                                       f"/_ingest/pipeline/{pipeline_id}",
                                       params)

    def delete_pipeline(self, pipeline_id: str, **params):
        return self._t.perform_request("DELETE",
                                       f"/_ingest/pipeline/{pipeline_id}",
                                       params)

    def simulate(self, body: dict, **params):
        return self._t.perform_request("POST", "/_ingest/pipeline/_simulate",
                                       params, body)


class MlClient(_Namespace):
    def put_job(self, job_id: str, body: dict, **params):
        return self._t.perform_request(
            "PUT", f"/_ml/anomaly_detectors/{job_id}", params, body)

    def open_job(self, job_id: str, **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/_open", params)

    def close_job(self, job_id: str, **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/_close", params)

    def post_data(self, job_id: str, records: List[dict], **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/_data", params, records)

    def flush_job(self, job_id: str, **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/_flush", params)

    def get_buckets(self, job_id: str, body: Optional[dict] = None, **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/results/buckets",
            params, body or {})

    def get_records(self, job_id: str, body: Optional[dict] = None, **params):
        return self._t.perform_request(
            "POST", f"/_ml/anomaly_detectors/{job_id}/results/records",
            params, body or {})

    def put_datafeed(self, datafeed_id: str, body: dict, **params):
        return self._t.perform_request(
            "PUT", f"/_ml/datafeeds/{datafeed_id}", params, body)

    def start_datafeed(self, datafeed_id: str, **params):
        return self._t.perform_request(
            "POST", f"/_ml/datafeeds/{datafeed_id}/_start", params)


class SqlClient(_Namespace):
    def query(self, body: dict, **params):
        return self._t.perform_request("POST", "/_sql", params, body)

    def translate(self, body: dict, **params):
        return self._t.perform_request("POST", "/_sql/translate", params,
                                       body)


class SnapshotClient(_Namespace):
    def create_repository(self, repository: str, body: dict, **params):
        return self._t.perform_request("PUT", f"/_snapshot/{repository}",
                                       params, body)

    def create(self, repository: str, snapshot: str,
               body: Optional[dict] = None, **params):
        return self._t.perform_request(
            "PUT", f"/_snapshot/{repository}/{snapshot}", params, body)

    def restore(self, repository: str, snapshot: str,
                body: Optional[dict] = None, **params):
        return self._t.perform_request(
            "POST", f"/_snapshot/{repository}/{snapshot}/_restore", params,
            body)

    def get(self, repository: str, snapshot: str = "_all", **params):
        return self._t.perform_request(
            "GET", f"/_snapshot/{repository}/{snapshot}", params)


class TasksClient(_Namespace):
    def list(self, **params):
        return self._t.perform_request("GET", "/_tasks", params)


class EnrichClient(_Namespace):
    def put_policy(self, name: str, body: dict, **params):
        return self._t.perform_request("PUT", f"/_enrich/policy/{name}",
                                       params, body)

    def execute_policy(self, name: str, **params):
        return self._t.perform_request(
            "POST", f"/_enrich/policy/{name}/_execute", params)


class GraphClient(_Namespace):
    def explore(self, index: str, body: dict, **params):
        return self._t.perform_request("POST", f"/{_idx(index)}/_graph/explore",
                                       params, body)


class TpuSearchClient:
    """High-level client (reference: RestHighLevelClient.java layout)."""

    def __init__(self, hosts: Sequence[Union[str, Tuple[str, int]]] =
                 ("localhost:9200",), **transport_kwargs):
        self.transport = Transport(hosts, **transport_kwargs)
        self.indices = IndicesClient(self.transport)
        self.cluster = ClusterClient(self.transport)
        self.cat = CatClient(self.transport)
        self.ingest = IngestClient(self.transport)
        self.ml = MlClient(self.transport)
        self.sql = SqlClient(self.transport)
        self.snapshot = SnapshotClient(self.transport)
        self.tasks = TasksClient(self.transport)
        self.enrich = EnrichClient(self.transport)
        self.graph = GraphClient(self.transport)

    # ------------------------------------------------------------ documents
    def index(self, index: str, body: dict, id: Optional[str] = None,
              **params):
        if id is None:
            return self.transport.perform_request(
                "POST", f"/{_idx(index)}/_doc", params, body)
        return self.transport.perform_request(
            "PUT", _doc_path(index, id), params, body)

    def get(self, index: str, id: str, **params):
        return self.transport.perform_request("GET", _doc_path(index, id),
                                              params)

    def exists(self, index: str, id: str) -> bool:
        try:
            self.transport.perform_request("HEAD", _doc_path(index, id))
            return True
        except TransportError as e:
            if e.status == 404:
                return False
            raise

    def delete(self, index: str, id: str, **params):
        return self.transport.perform_request("DELETE", _doc_path(index, id),
                                              params)

    def update(self, index: str, id: str, body: dict, **params):
        return self.transport.perform_request(
            "POST", f"/{_idx(index)}/_update/{id}", params, body)

    def mget(self, body: dict, index: Optional[str] = None, **params):
        path = f"/{_idx(index)}/_mget" if index else "/_mget"
        return self.transport.perform_request("POST", path, params, body)

    def bulk(self, operations: List[dict], index: Optional[str] = None,
             **params):
        path = f"/{_idx(index)}/_bulk" if index else "/_bulk"
        raw = b"\n".join(xcontent.dumps(op, XContentType.JSON)
                         for op in operations) + b"\n"
        return self.transport.perform_request("POST", path, params,
                                              raw_body=raw)

    # --------------------------------------------------------------- search
    def search(self, index: Optional[str] = None,
               body: Optional[dict] = None, **params):
        path = f"/{_idx(index)}/_search" if index else "/_search"
        return self.transport.perform_request("POST", path, params,
                                              body or {})

    def msearch(self, searches: List[dict], **params):
        raw = b"\n".join(xcontent.dumps(line, XContentType.JSON)
                         for line in searches) + b"\n"
        return self.transport.perform_request("POST", "/_msearch", params,
                                              raw_body=raw)

    def count(self, index: Optional[str] = None,
              body: Optional[dict] = None, **params):
        path = f"/{_idx(index)}/_count" if index else "/_count"
        return self.transport.perform_request("POST", path, params, body)

    def scroll(self, scroll_id: str, scroll: str = "1m", **params):
        return self.transport.perform_request(
            "POST", "/_search/scroll", params,
            {"scroll_id": scroll_id, "scroll": scroll})

    def clear_scroll(self, scroll_id: str, **params):
        return self.transport.perform_request(
            "DELETE", "/_search/scroll", params, {"scroll_id": [scroll_id]})

    def rank_eval(self, index: str, body: dict, **params):
        return self.transport.perform_request(
            "POST", f"/{_idx(index)}/_rank_eval", params, body)

    # ----------------------------------------------------------------- misc
    def info(self):
        return self.transport.perform_request("GET", "/")

    def ping(self) -> bool:
        try:
            self.transport.perform_request("GET", "/")
            return True
        except (TransportError, OSError):
            return False


# the familiar import alias
Client = TpuSearchClient
