"""elasticsearch_tpu — a TPU-native distributed search engine.

A from-scratch re-design of the capability surface of Elasticsearch
(reference: ywangd/elasticsearch @ v8.0.0-pre) for TPU hardware:

- Host runtime (Python/asyncio + C++ hot paths): REST API, cluster
  coordination, replication, durability (translog/snapshots).
- Device programs (JAX/XLA/Pallas): dense_vector kNN as batched
  matmul + top-k, sharded over a `jax.sharding.Mesh`; cross-shard
  top-k merge as ICI all-gather; aggregations as device-side partial
  reductions.

Package layout:
  common/    settings, xcontent parsing, versioned binary serialization
  ops/       device kernels: similarity, top-k, kNN, quantization
  parallel/  mesh management, shard_map-sharded kNN, collective merges
  vectors/   HBM-resident sharded vector store (delta blocks + compaction)
  index/     mappings, analysis, inverted index, engine, translog, seqno
  search/    query DSL, BM25, query-then-fetch phases, aggregations
  cluster/   cluster state, coordination, routing, allocation
  transport/ framed async RPC + in-memory test transport
  rest/      HTTP server + RestController + handlers
"""

from elasticsearch_tpu.version import __version__  # noqa: F401
