"""Vector codec registry: the ONE owner of every encoding recipe.

The reference stores dense vectors only as f32 BinaryDocValues
(`DenseVectorFieldMapper.java:184-226`); on TPU the whole edge is
density — how many doc vectors fit in 16 GB of HBM — so the storage
encoding is a first-class subsystem, not a dtype string scattered over
call sites. This module owns the quantization ladder:

    encoding   device matrix          per-row aux      bytes/row @768d
    f32        f32 [N, D]             —                3072
    bf16       bf16 [N, D]            —                1536
    int8       int8 [N, D]            scale f32        768 (+4)
    int4       uint8 [N, D/2]         scale f32        384 (+4)   packed nibbles
    binary     uint32 [N, D/32]       mean|x| f32      96  (+4)   sign bits

Every codec exposes a host (numpy) encoder, a device (jnp, traceable)
twin, and a host decode twin — the np/jnp pairs are BYTE-identical by
construction and pinned by tests/test_quant_codecs.py, so the host
build path, the device query-quantization path, and the bench harness
can never drift apart. The arithmetic (scale-divide-round-clip,
sign-bit packing) lives HERE and nowhere else: tpulint TPU013 fires on
hand-rolled copies outside `elasticsearch_tpu/quant/`.

Scoring contracts per rung:

* int8 / int4 — symmetric per-row scales; the matmul runs on the
  packed planes and scores de-scale after (`ops/knn._block_scores`,
  `ops/knn_ivf`, `ops/pallas_ivf_fused`).
* binary — sign-bit Hamming: for unit vectors,
  dot(sign q, sign v) = D - 2·ham(q, v), so the coarse score is the
  affine popcount form (a monotone proxy for cosine). Binary (and
  int4, by default) serve two-phase: coarse top-(k·oversample) on the
  packed encoding, exact f32 rescore of the window through the
  columnar RowSource gather (`quant/rescore.py`).
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple

import numpy as np

# chunk budget for host encoders: never materialize a second
# corpus-sized f32 temp (the 10M x 768 corpus is ~30 GB)
_CHUNK_BYTES = 64 << 20

# encoding name <-> device matrix dtype string (the reverse map the
# store and the segments re-encode selector read off a live corpus)
MATRIX_DTYPES = {
    "f32": "float32",
    "bf16": "bfloat16",
    "int8": "int8",
    "int4": "uint8",
    "binary": "uint32",
}
_ENCODING_BY_DTYPE = {v: k for k, v in MATRIX_DTYPES.items()}

# encodings whose device matrix is bit-packed (scored via the packed
# planes, served two-phase with exact rescore by default)
PACKED_ENCODINGS = ("int4", "binary")


def encoding_of(matrix_dtype) -> str:
    """Encoding name for a device matrix dtype (str or np/jnp dtype)."""
    return _ENCODING_BY_DTYPE.get(str(matrix_dtype), "f32")


class Encoded(NamedTuple):
    """One host-encoded row block: packed data + per-row aux scales."""

    data: np.ndarray     # [n, W] packed rows (dtype per codec)
    scales: np.ndarray   # [n] f32 per-row aux (ones when unused)


class VectorCodec:
    """One rung of the ladder. Subclasses own the arithmetic."""

    name = ""
    packed_np_dtype = np.float32

    def packed_width(self, dims: int) -> int:
        """Packed columns per row."""
        return dims

    def row_bytes(self, dims: int) -> int:
        """Packed matrix bytes per row."""
        return self.packed_width(dims) * np.dtype(self.packed_np_dtype).itemsize

    def aux_bytes(self) -> int:
        """Per-row aux bytes (scales)."""
        return 4

    def bytes_per_doc(self, dims: int) -> int:
        """Resident device bytes per doc: packed row + scales + the f32
        sq-norm every corpus carries — the number the density ladder
        bench and `_nodes/stats indices.knn` report."""
        return self.row_bytes(dims) + self.aux_bytes() + 4

    # -------------------------------------------------------------- host
    def encode_np(self, rows: np.ndarray) -> Encoded:  # pragma: no cover
        raise NotImplementedError

    def decode_np(self, data: np.ndarray,
                  scales: np.ndarray) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    # ------------------------------------------------------------ device
    def encode_jnp(self, rows):  # pragma: no cover
        """Traceable twin of encode_np: (data, scales) jnp arrays,
        byte-identical to the host encoder on identical input."""
        raise NotImplementedError


class _F32Codec(VectorCodec):
    name = "f32"

    def aux_bytes(self) -> int:
        return 0

    def encode_np(self, rows: np.ndarray) -> Encoded:
        rows = np.asarray(rows, dtype=np.float32)
        return Encoded(rows, np.ones(len(rows), dtype=np.float32))

    def decode_np(self, data, scales):
        return np.asarray(data, dtype=np.float32)

    def encode_jnp(self, rows):
        import jax.numpy as jnp
        rows = rows.astype(jnp.float32)
        return rows, jnp.ones((rows.shape[0],), dtype=jnp.float32)


class _BF16Codec(VectorCodec):
    name = "bf16"

    def packed_width(self, dims: int) -> int:
        return dims

    def row_bytes(self, dims: int) -> int:
        return dims * 2

    def aux_bytes(self) -> int:
        return 0

    def encode_np(self, rows: np.ndarray) -> Encoded:
        import ml_dtypes
        rows = np.asarray(rows, dtype=np.float32).astype(ml_dtypes.bfloat16)
        return Encoded(rows, np.ones(len(rows), dtype=np.float32))

    def decode_np(self, data, scales):
        return np.asarray(data, dtype=np.float32)

    def encode_jnp(self, rows):
        import jax.numpy as jnp
        rows = rows.astype(jnp.bfloat16)
        return rows, jnp.ones((rows.shape[0],), dtype=jnp.float32)


class _Int8Codec(VectorCodec):
    """Per-row symmetric int8: scale = max|row|/127 (1e-30 floor)."""

    name = "int8"
    packed_np_dtype = np.int8

    def encode_np(self, rows: np.ndarray) -> Encoded:
        rows = np.asarray(rows, dtype=np.float32)
        n = rows.shape[0]
        q8 = np.empty(rows.shape, dtype=np.int8)
        scales = np.empty((n,), dtype=np.float32)
        chunk = max(1, _CHUNK_BYTES // max(rows.shape[1] * 4, 1))
        for lo in range(0, n, chunk):
            hi = lo + chunk
            block = rows[lo:hi]
            s = np.maximum(np.abs(block).max(axis=-1), 1e-30) / 127.0
            scales[lo:hi] = s
            q8[lo:hi] = np.clip(np.round(block / s[:, None]),
                                -127, 127).astype(np.int8)
        return Encoded(q8, scales)

    def decode_np(self, data, scales):
        return data.astype(np.float32) * np.asarray(scales)[:, None]

    def encode_jnp(self, rows):
        import jax.numpy as jnp
        rows = rows.astype(jnp.float32)
        max_abs = jnp.max(jnp.abs(rows), axis=-1)
        scales = jnp.maximum(max_abs, 1e-30) / 127.0
        q = jnp.clip(jnp.round(rows / scales[:, None]),
                     -127, 127).astype(jnp.int8)
        return q, scales


class _Int4Codec(VectorCodec):
    """Packed-nibble symmetric int4: scale = max|row|/7, two dims per
    byte (even dim in the low nibble, odd in the high), levels in
    [-7, 7] stored offset-by-8 so every nibble is a valid level."""

    name = "int4"
    packed_np_dtype = np.uint8

    def packed_width(self, dims: int) -> int:
        if dims % 2:
            raise ValueError(f"int4 encoding requires even dims, got {dims}")
        return dims // 2

    def encode_np(self, rows: np.ndarray) -> Encoded:
        rows = np.asarray(rows, dtype=np.float32)
        n, d = rows.shape
        w = self.packed_width(d)
        packed = np.empty((n, w), dtype=np.uint8)
        scales = np.empty((n,), dtype=np.float32)
        chunk = max(1, _CHUNK_BYTES // max(d * 4, 1))
        for lo in range(0, n, chunk):
            hi = lo + chunk
            block = rows[lo:hi]
            s = np.maximum(np.abs(block).max(axis=-1), 1e-30) / 7.0
            scales[lo:hi] = s
            q = np.clip(np.round(block / s[:, None]), -7, 7).astype(np.int8)
            packed[lo:hi] = ((q[:, 0::2] + 8).astype(np.uint8)
                             | ((q[:, 1::2] + 8).astype(np.uint8) << 4))
        return Encoded(packed, scales)

    def decode_np(self, data, scales):
        data = np.asarray(data)
        lo = (data & 0x0F).astype(np.int8) - 8
        hi = (data >> 4).astype(np.int8) - 8
        n, w = data.shape
        out = np.empty((n, 2 * w), dtype=np.float32)
        out[:, 0::2] = lo
        out[:, 1::2] = hi
        return out * np.asarray(scales)[:, None]

    def encode_jnp(self, rows):
        import jax.numpy as jnp
        rows = rows.astype(jnp.float32)
        max_abs = jnp.max(jnp.abs(rows), axis=-1)
        scales = jnp.maximum(max_abs, 1e-30) / 7.0
        q = jnp.clip(jnp.round(rows / scales[:, None]), -7, 7)
        lo = (q[:, 0::2] + 8).astype(jnp.uint8)
        hi = (q[:, 1::2] + 8).astype(jnp.uint8)
        return lo | (hi << 4), scales


class _BinaryCodec(VectorCodec):
    """Sign-bit binary: bit j of word w is sign(x[32w + j] >= 0). The
    per-row aux is mean|x| — the optimal 1-bit reconstruction magnitude,
    so decode_np returns sign(x)·mean|x| rather than bare ±1."""

    name = "binary"
    packed_np_dtype = np.uint32

    def packed_width(self, dims: int) -> int:
        if dims % 32:
            raise ValueError(
                f"binary encoding requires dims % 32 == 0, got {dims}")
        return dims // 32

    def encode_np(self, rows: np.ndarray) -> Encoded:
        rows = np.asarray(rows, dtype=np.float32)
        n, d = rows.shape
        w = self.packed_width(d)
        bits = (rows >= 0).astype(np.uint32).reshape(n, w, 32)
        weights = (np.uint32(1) << np.arange(32, dtype=np.uint32))
        packed = (bits * weights[None, None, :]).sum(
            axis=-1, dtype=np.uint32)
        scales = np.abs(rows).mean(axis=-1).astype(np.float32)
        return Encoded(packed, scales)

    def decode_np(self, data, scales):
        data = np.asarray(data)
        n, w = data.shape
        shifts = np.arange(32, dtype=np.uint32)
        bits = ((data[:, :, None] >> shifts[None, None, :]) & 1)
        signs = bits.astype(np.float32).reshape(n, w * 32) * 2.0 - 1.0
        return signs * np.asarray(scales)[:, None]

    def encode_jnp(self, rows):
        import jax.numpy as jnp
        rows = rows.astype(jnp.float32)
        n, d = rows.shape
        w = self.packed_width(d)
        bits = (rows >= 0).astype(jnp.uint32).reshape(n, w, 32)
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        packed = jnp.sum(bits * weights[None, None, :], axis=-1,
                         dtype=jnp.uint32)
        scales = jnp.mean(jnp.abs(rows), axis=-1).astype(jnp.float32)
        return packed, scales


CODECS: Dict[str, VectorCodec] = {}
_REGISTRY_LOCK = threading.Lock()


def register(codec: VectorCodec) -> VectorCodec:
    with _REGISTRY_LOCK:
        CODECS[codec.name] = codec
    return codec


register(_F32Codec())
register(_BF16Codec())
register(_Int8Codec())
register(_Int4Codec())
register(_BinaryCodec())


def get(name: str) -> VectorCodec:
    codec = CODECS.get(name)
    if codec is None:
        raise KeyError(
            f"unknown vector encoding [{name}]; registered: "
            f"{sorted(CODECS)}")
    return codec


def bytes_per_doc(name: str, dims: int) -> int:
    return get(name).bytes_per_doc(dims)


def is_packed(name: str) -> bool:
    return name in PACKED_ENCODINGS


# ---------------------------------------------------------------------------
# Device-side scoring helpers (the unpack half of the packed recipes —
# kept here so the pack and unpack bit conventions can never diverge)
# ---------------------------------------------------------------------------

def quantize_queries_int8_jnp(queries):
    """Per-QUERY symmetric int8 (the binned Pallas kernel's in-trace
    query quantization): (q8 [Q, D] int8, qscale [Q, 1] f32)."""
    import jax.numpy as jnp
    qmax = jnp.max(jnp.abs(queries), axis=-1, keepdims=True)
    qscale = jnp.maximum(qmax, 1e-30) / 127.0
    q8 = jnp.clip(jnp.round(queries / qscale), -127, 127).astype(jnp.int8)
    return q8, qscale


def int4_planes_jnp(packed, dtype=None):
    """Unpack a packed-nibble matrix [..., W] into its (even, odd) level
    planes [..., W] (values in [-8, 7]; encoders only emit [-7, 7]).
    With `dtype` the planes are cast for the matmul."""
    import jax.numpy as jnp
    lo = (packed & jnp.uint8(0x0F)).astype(jnp.int32) - 8
    hi = (packed >> 4).astype(jnp.int32) - 8
    if dtype is not None:
        lo, hi = lo.astype(dtype), hi.astype(dtype)
    return lo, hi


def split_query_planes_jnp(queries):
    """Match a query batch [Q, D] to the int4 plane layout:
    (even dims [Q, D/2], odd dims [Q, D/2])."""
    return queries[:, 0::2], queries[:, 1::2]


def pack_sign_bits_jnp(queries):
    """Sign-bit pack a query batch [Q, D] into uint32 words [Q, D/32] —
    the in-trace twin of the binary codec's row encoder (bit layout is
    identical by construction)."""
    import jax.numpy as jnp
    nq, d = queries.shape
    w = d // 32
    bits = (queries >= 0).astype(jnp.uint32).reshape(nq, w, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(bits * weights[None, None, :], axis=-1,
                   dtype=jnp.uint32)


def hamming_pseudo_dots_jnp(qbits, words):
    """Coarse binary scores from packed sign bits.

    qbits [Q, W] uint32, words [N, W] uint32 → [Q, N] f32 in [-1, 1]:
    (D - 2·hamming)/D, the normalized sign-agreement — for
    cosine-normalized vectors this is the 1-bit estimate of the dot.
    Accumulates word-by-word so no [Q, N, W] popcount temp
    materializes (W is tiny — 24 words at 768 d — and the python loop
    unrolls into the trace)."""
    import jax
    import jax.numpy as jnp
    nq = qbits.shape[0]
    n, w = words.shape
    ham = jnp.zeros((nq, n), dtype=jnp.int32)
    for i in range(w):
        x = jnp.bitwise_xor(qbits[:, i:i + 1], words[None, :, i])
        ham = ham + jax.lax.population_count(x).astype(jnp.int32)
    d_bits = jnp.float32(w * 32)
    return (d_bits - 2.0 * ham.astype(jnp.float32)) / d_bits


def int4_blocked_dots_jnp(queries, blocks, dtype):
    """Un-descaled int4 dots for IVF probe tiles: queries [Q, D] f32,
    blocks [Q, C, W] packed uint8 → [Q, C] f32 — the one blocked-take
    scoring recipe shared by the single-device and mesh probe scorers
    (callers multiply the per-row scales in)."""
    import jax.numpy as jnp
    lo, hi = int4_planes_jnp(blocks, dtype)
    qe, qo = split_query_planes_jnp(queries)
    return (jnp.einsum("qd,qcd->qc", qe.astype(dtype), lo,
                       preferred_element_type=jnp.float32)
            + jnp.einsum("qd,qcd->qc", qo.astype(dtype), hi,
                         preferred_element_type=jnp.float32))


def hamming_pseudo_dots_blocked_jnp(qbits, blocks):
    """Blocked-take variant for IVF probe tiles: qbits [Q, W],
    blocks [Q, C, W] uint32 → [Q, C] f32 pseudo-dots."""
    import jax
    import jax.numpy as jnp
    w = blocks.shape[-1]
    ham = jnp.zeros(blocks.shape[:-1], dtype=jnp.int32)
    for i in range(w):
        x = jnp.bitwise_xor(qbits[:, None, i], blocks[:, :, i])
        ham = ham + jax.lax.population_count(x).astype(jnp.int32)
    d_bits = jnp.float32(w * 32)
    return (d_bits - 2.0 * ham.astype(jnp.float32)) / d_bits
