"""Vector codec subsystem: the quantization ladder + two-phase rescore.

`quant/codec.py` is the one owner of every storage-encoding recipe
(f32 / bf16 / int8 / int4 packed-nibble / binary sign-bit) — host and
device twins, per-row aux arrays, bytes-per-doc accounting. `quant/
tokens.py` is the token-block variant for late-interaction
(multi-vector) fields — metric prep, lane padding, per-token codec
rows, pooled coarse centroids. `quant/rescore.py` is the exact-rescore
half of two-phase serving. Everything that quantizes
(`ops/quantization`, `ops/pallas_knn_binned`'s query path,
`vectors/host_corpus`, the IVF partition upload, the sharded mesh
build, the token-block extraction) routes through here; tpulint TPU013
keeps it that way.
"""

from elasticsearch_tpu.quant import codec, rescore, tokens
from elasticsearch_tpu.quant.codec import (
    CODECS,
    PACKED_ENCODINGS,
    bytes_per_doc,
    encoding_of,
    get,
    is_packed,
)
from elasticsearch_tpu.quant.rescore import (
    DEFAULT_OVERSAMPLE,
    coarse_window,
    rescore_boards,
)

__all__ = [
    "CODECS", "PACKED_ENCODINGS", "bytes_per_doc", "codec", "encoding_of",
    "get", "is_packed", "rescore", "tokens", "DEFAULT_OVERSAMPLE",
    "coarse_window", "rescore_boards",
]
