"""Token-block packing for late-interaction (multi-vector) fields.

A `rank_vectors` doc stores a ragged [n_tokens, dims] token matrix
(ColBERT-style). This module is the ONE owner of how those matrices
become device blocks — metric prep, lane padding, and the codec
round-trip all live here (the same single-owner discipline
`quant/codec.py` keeps for single-vector rows; tpulint TPU013 fires on
hand-rolled token packing outside `elasticsearch_tpu/quant/`):

* tokens metric-prep FIRST (cosine → per-token unit norm), so MaxSim
  over encoded tokens approximates the mapped similarity and
  per-segment encoding equals whole-corpus encoding byte for byte;
* the feature dim zero-pads up to a LANE (128) multiple BEFORE
  encoding — the fused MaxSim kernel moves whole lane-aligned token
  rows, and zero tail columns add exactly 0.0 to every dot;
* rows then encode through the registered codec (`quant/codec.py`), so
  the int8/int4 density rungs apply to token blocks with the identical
  arithmetic the single-vector corpus uses (per-TOKEN scales here —
  each token row is an independent codec row).

The pooled per-doc centroid (mean of prepped tokens, re-normalized for
cosine) also comes from here: it is the single vector the coarse
retrieval phase indexes, so its math must be pinned next to the token
prep it summarizes.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from elasticsearch_tpu.quant import codec as quant_codec

LANE = 128


def pad_dim(dims: int) -> int:
    """Feature-dim pad target: the next LANE multiple (min one lane)."""
    return max(-(-dims // LANE) * LANE, LANE)


def prep_tokens(tokens: np.ndarray, metric: str) -> np.ndarray:
    """Metric-prep token rows: cosine normalizes per token (zero tokens
    stay zero), dot_product passes through — mirrors the single-vector
    prep in `columnar.extract_encoded_vector_block`."""
    mat = np.asarray(tokens, dtype=np.float32)
    if metric == "cosine" and mat.size:
        norms = np.linalg.norm(mat, axis=-1, keepdims=True)
        mat = mat / np.maximum(norms, 1e-30)
    return mat


def pool_doc(tokens_prepped: np.ndarray, metric: str) -> np.ndarray:
    """One doc's coarse-phase centroid: mean of its prepped tokens,
    re-normalized for cosine so the coarse corpus holds unit rows."""
    pooled = tokens_prepped.mean(axis=0).astype(np.float32)
    if metric == "cosine":
        pooled = pooled / max(float(np.linalg.norm(pooled)), 1e-30)
    return pooled


def encode_tokens(tokens_prepped: np.ndarray, encoding: str, dims: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Codec-encode prepped token rows at the lane-padded width:
    (data [T, W] packed, scales [T] f32). Tokens encode independently,
    so concatenating blocks is byte-identical to encoding the
    concatenation — the delta-refresh invariant."""
    d_pad = pad_dim(dims)
    mat = np.asarray(tokens_prepped, dtype=np.float32)
    if mat.ndim != 2:
        mat = mat.reshape(-1, dims)
    if d_pad != dims:
        mat = np.concatenate(
            [mat, np.zeros((mat.shape[0], d_pad - dims), dtype=np.float32)],
            axis=1)
    enc = quant_codec.get(encoding).encode_np(mat)
    return enc.data, enc.scales


def decode_tokens(data: np.ndarray, scales: np.ndarray, encoding: str,
                  dims: int) -> np.ndarray:
    """Host decode twin: [T, dims] f32 (lane padding stripped) — what
    the interpret-mode parity tests compare the kernel's operands to."""
    full = quant_codec.get(encoding).decode_np(data, scales)
    return np.asarray(full, dtype=np.float32)[:, :dims]


def packed_width(encoding: str, dims: int) -> int:
    """Packed columns per token row at the lane-padded width."""
    return quant_codec.get(encoding).packed_width(pad_dim(dims))


def bytes_per_doc(encoding: str, dims: int, avg_tokens: float) -> int:
    """Resident token-block bytes per doc at `avg_tokens` tokens: the
    encoded rows + per-token scales + the f32 pooled centroid — the
    number the README encodings table and `_nodes/stats` report."""
    codec = quant_codec.get(encoding)
    per_token = codec.row_bytes(pad_dim(dims)) + 4
    return int(round(avg_tokens * per_token)) + dims * 4
