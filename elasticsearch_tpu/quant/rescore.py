"""Two-phase serving: coarse packed top-k, exact rescore of the window.

The generalization of the `4r_north_star_int8_rescored` bench shape into
the serving path: a packed encoding (int4 / binary) answers the coarse
question — WHICH ~k·oversample rows are worth an exact look — and the
exact f32 rows, gathered through the columnar segment block store
(`columnar.RowSource`), answer the final ordering. Storage density comes
from the packed rung; the recall contract (recall@10 ≥ 0.95 vs exact
f32) comes from the rescore, because the window is a superset of the
true top-k with overwhelming probability at the default oversamples.

The rescore runs host-side in f32 numpy at response-assembly time (the
same place the store lands device boards): the candidate gather is
O(window) rows against the shared blocks — no corpus-sized copy, no
device round-trip — and the scores it produces are EXACT raw
similarities in the `ops/similarity` conventions, so `to_es_score` and
every downstream consumer are encoding-blind.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from elasticsearch_tpu.ops import similarity as sim

# default coarse-window oversampling per packed rung: int4 keeps ~4
# bits/dim of signal so a small window suffices; binary keeps one bit
# and needs a wider net — measured on the 768-d clustered bench shape,
# int4@4 holds recall@10 ≈ 0.96 and binary@16 ≈ 1.0 vs exact f32
# (binary@8 fell to ~0.84); the window is still ≤ a few hundred rows
DEFAULT_OVERSAMPLE = {"int4": 4, "binary": 16}

# score floor below which a coarse slot is padding, matching the device
# kernels' NEG_INF sentinel
_FLOOR = -1e37


def exact_scores(queries: np.ndarray, rows: np.ndarray,
                 metric: str) -> np.ndarray:
    """Raw similarity of `queries` [B, D] vs `rows` [B, C, D] (or
    [C, D] broadcast), f32, same conventions as the device kernels:
    cosine normalizes both sides, l2 returns 2q·v - |q|² - |v|²."""
    queries = np.asarray(queries, dtype=np.float32)
    rows = np.asarray(rows, dtype=np.float32)
    if metric == sim.COSINE:
        qn = np.linalg.norm(queries, axis=-1, keepdims=True)
        queries = queries / np.maximum(qn, 1e-30)
        rn = np.linalg.norm(rows, axis=-1, keepdims=True)
        rows = rows / np.maximum(rn, 1e-30)
        return np.einsum("bd,bcd->bc", queries, rows, dtype=np.float32)
    dots = np.einsum("bd,bcd->bc", queries, rows, dtype=np.float32)
    if metric == sim.L2_NORM:
        q_sq = (queries * queries).sum(axis=-1, keepdims=True)
        r_sq = (rows * rows).sum(axis=-1)
        return 2.0 * dots - q_sq - r_sq
    return dots


def rescore_boards(
    queries: np.ndarray,
    coarse_scores: np.ndarray,
    coarse_ids: np.ndarray,
    k: int,
    gather: Callable[[np.ndarray], np.ndarray],
    metric: str,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Exactly re-rank coarse boards and keep the top k.

    queries:       [B, D] f32 (UNPADDED real queries)
    coarse_scores: [B, W] coarse raw scores (-inf/NEG_INF padding)
    coarse_ids:    [B, W] int row ids in the gather space (-1 padding)
    gather:        ascending-unique row ids -> f32 rows [m, D] (the
                   columnar RowSource read)
    Returns (scores [B, k] f32, ids [B, k], stats): exact raw scores,
    -inf/-1 padded; stats = {"window", "promoted"} where `promoted`
    counts final-top-k slots whose coarse rank was >= k — the recall
    the rescore actually bought on this batch.
    """
    b, w = coarse_ids.shape
    out_s = np.full((b, k), -np.inf, dtype=np.float32)
    out_i = np.full((b, k), -1, dtype=np.int64)
    valid = (coarse_ids >= 0) & (coarse_scores > _FLOOR)
    flat = coarse_ids[valid]
    stats = {"window": int(w), "promoted": 0}
    if flat.size == 0:
        return out_s, out_i, stats
    uniq, inv = np.unique(flat.astype(np.int64), return_inverse=True)
    vecs = np.asarray(gather(uniq), dtype=np.float32)   # [m, D]
    promoted = 0
    pos = 0
    for qi in range(b):
        vq = valid[qi]
        n_c = int(vq.sum())
        if n_c == 0:
            continue
        cand_ids = coarse_ids[qi, vq].astype(np.int64)
        cand_vecs = vecs[inv[pos:pos + n_c]]
        pos += n_c
        raw = exact_scores(queries[qi:qi + 1], cand_vecs[None], metric)[0]
        kk = min(k, n_c)
        # argsort over (-score, candidate order): the coarse board is
        # score-descending, so equal exact scores tie-break by coarse
        # rank — deterministic across runs, like lax.top_k's
        # lower-index-wins
        order = np.argsort(-raw, kind="stable")[:kk]
        out_s[qi, :kk] = raw[order]
        out_i[qi, :kk] = cand_ids[order]
        promoted += int((order >= k).sum())
    stats["promoted"] = promoted
    return out_s, out_i, stats


def coarse_window(k: int, oversample: int, limit: Optional[int] = None
                  ) -> int:
    """Coarse-phase k for a final k at `oversample`, clamped to the
    corpus. Callers round the result up the dispatch k-ladder so the
    widened phase stays inside the closed compile grid."""
    w = max(int(k) * max(int(oversample), 1), int(k))
    if limit is not None:
        w = min(w, int(limit))
    return max(w, 1)
