"""ML job + datafeed services.

Reference: `x-pack/plugin/ml` — `MlConfigIndex`/`JobManager` (job configs in
an internal index), `AutodetectProcessManager` (one native process per open
job), `JobResultsPersister` (buckets/records into `.ml-anomalies-shared`),
`JobResultsProvider` (results queries), `DatafeedManager`/`DatafeedJob`
(search-driven extraction feeding the process), `JobDataCountsPersister`.

Here configs live in a JSON state file beside the node's other stores,
results are indexed into `.ml-anomalies-shared` through the normal document
path (so they're searchable with the full query DSL, like the reference),
and the analytics engine is the native sidecar in ml/process.py.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import threading
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
    ValidationError,
)
from elasticsearch_tpu.ml.process import AutodetectProcess

RESULTS_INDEX = ".ml-anomalies-shared"

_ALLOWED_FUNCTIONS = {
    "count", "low_count", "high_count", "mean", "low_mean", "high_mean",
    "min", "max", "sum", "low_sum", "high_sum", "metric", "rare",
    "distinct_count", "low_distinct_count", "high_distinct_count",
}


def _parse_time(value, time_format: Optional[str]) -> Optional[float]:
    """Record timestamp → epoch seconds. Supports epoch, epoch_ms, ISO8601."""
    if value is None:
        return None
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        v = float(value)
        if time_format == "epoch_ms" or v > 1e11:  # heuristics like the date mapper
            return v / 1000.0
        return v
    s = str(value)
    try:
        v = float(s)
        return v / 1000.0 if (time_format == "epoch_ms" or v > 1e11) else v
    except ValueError:
        pass
    try:
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        dt = _dt.datetime.fromisoformat(s)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        return dt.timestamp()
    except ValueError:
        return None


class _OpenJob:
    def __init__(self, process: AutodetectProcess):
        self.process = process
        self.results: List[dict] = []      # drained into the results index
        self.lock = threading.Lock()
        self.open_time = time.time()

    def on_result(self, msg: dict) -> None:
        with self.lock:
            self.results.append(msg)

    def take_results(self) -> List[dict]:
        with self.lock:
            out, self.results = self.results, []
        return out


class MlService:
    def __init__(self, node):
        self.node = node
        self._state_path = os.path.join(node.indices.data_path, "_state",
                                        "ml_jobs.json")
        self._model_state_dir = os.path.join(node.indices.data_path, "_state",
                                             "ml_model_state")
        self.jobs: Dict[str, dict] = {}
        self.data_counts: Dict[str, dict] = {}
        self._open: Dict[str, _OpenJob] = {}
        self._load()

    # -------------------------------------------------------------- storage
    def _load(self) -> None:
        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                data = json.load(f)
            self.jobs = data.get("jobs", {})
            self.data_counts = data.get("data_counts", {})
        except (OSError, ValueError):
            pass

    def _save(self) -> None:
        os.makedirs(os.path.dirname(self._state_path), exist_ok=True)
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"jobs": self.jobs, "data_counts": self.data_counts}, f)
        os.replace(tmp, self._state_path)

    def _model_state_path(self, job_id: str) -> str:
        return os.path.join(self._model_state_dir, f"{job_id}.json")

    # ------------------------------------------------------------ job CRUD
    def put_job(self, job_id: str, body: dict) -> dict:
        if job_id in self.jobs:
            raise ResourceAlreadyExistsError(
                f"The job cannot be created with the Id '{job_id}'. "
                f"The Id is already used.")
        ac = body.get("analysis_config")
        if not isinstance(ac, dict) or not ac.get("detectors"):
            raise ValidationError(
                "An analysis_config with at least one detector is required")
        for det in ac["detectors"]:
            fn = det.get("function", "count")
            if fn not in _ALLOWED_FUNCTIONS:
                raise ValidationError(f"Unknown function '{fn}'")
            if fn not in ("count", "low_count", "high_count", "rare") \
                    and not fn.endswith("distinct_count") \
                    and not det.get("field_name"):
                raise ValidationError(
                    f"Unless the function is 'count' one of field_name, "
                    f"by_field_name or over_field_name must be set: [{fn}]")
            if (fn == "rare" or fn.endswith("distinct_count")) \
                    and not det.get("by_field_name"):
                raise ValidationError(f"by_field_name must be set when the "
                                      f"'{fn}' function is used")
        job = dict(body)
        job["job_id"] = job_id
        job.setdefault("data_description", {"time_field": "time"})
        job["create_time"] = int(time.time() * 1000)
        job["job_type"] = "anomaly_detector"
        job["state"] = "closed"
        self.jobs[job_id] = job
        self.data_counts[job_id] = {
            "job_id": job_id, "processed_record_count": 0,
            "invalid_date_count": 0, "out_of_order_timestamp_count": 0,
            "earliest_record_timestamp": None, "latest_record_timestamp": None,
        }
        self._save()
        return job

    def get_jobs(self, job_id: Optional[str] = None) -> dict:
        if job_id and job_id not in ("_all", "*"):
            if job_id not in self.jobs:
                raise ResourceNotFoundError(
                    f"No known job with id '{job_id}'")
            jobs = [self.jobs[job_id]]
        else:
            jobs = [self.jobs[k] for k in sorted(self.jobs)]
        return {"count": len(jobs), "jobs": jobs}

    def delete_job(self, job_id: str, force: bool = False) -> None:
        if job_id not in self.jobs:
            raise ResourceNotFoundError(f"No known job with id '{job_id}'")
        if job_id in self._open:
            if not force:
                raise IllegalArgumentError(
                    f"Cannot delete job [{job_id}] because the job is opened")
            self._open.pop(job_id).process.kill()
        del self.jobs[job_id]
        self.data_counts.pop(job_id, None)
        try:
            os.remove(self._model_state_path(job_id))
        except OSError:
            pass
        self._save()

    # ------------------------------------------------------- open/close/data
    def open_job(self, job_id: str) -> dict:
        job = self._require(job_id)
        if job_id in self._open:
            return {"opened": True, "node": self.node.node_id}
        state = None
        try:
            with open(self._model_state_path(job_id), "r", encoding="utf-8") as f:
                state = json.load(f)
        except (OSError, ValueError):
            pass
        open_job: _OpenJob = None  # type: ignore[assignment]

        def handler(msg: dict) -> None:
            open_job.on_result(msg)

        open_job = _OpenJob(AutodetectProcess(job, handler, state=state))
        self._open[job_id] = open_job
        job["state"] = "opened"
        return {"opened": True, "node": self.node.node_id}

    def close_job(self, job_id: str, force: bool = False) -> dict:
        job = self._require(job_id)
        open_job = self._open.get(job_id)
        if open_job is None:
            return {"closed": True}
        if force:
            open_job.process.kill()
        else:
            try:
                state = open_job.process.persist_state()
                os.makedirs(self._model_state_dir, exist_ok=True)
                with open(self._model_state_path(job_id), "w",
                          encoding="utf-8") as f:
                    json.dump(state, f)
                open_job.process.close()
            except Exception:
                # dead/hung process: don't leave the job wedged in _open
                open_job.process.kill()
        del self._open[job_id]
        self._persist_results(job_id, open_job.take_results())
        job["state"] = "closed"
        self._save()
        return {"closed": True}

    def post_data(self, job_id: str, records: List[dict]) -> dict:
        self._require(job_id)
        open_job = self._open.get(job_id)
        if open_job is None:
            raise IllegalArgumentError(
                f"Cannot post data to job [{job_id}] because the job is "
                f"not open")
        dd = self.jobs[job_id].get("data_description", {}) or {}
        time_field = dd.get("time_field", "time")
        time_format = dd.get("time_format")
        counts = self.data_counts[job_id]
        for rec in records:
            t = _parse_time(rec.get(time_field), time_format)
            if t is None:
                counts["invalid_date_count"] += 1
                continue
            latest = counts["latest_record_timestamp"]
            if latest is not None and t * 1000 < latest:
                counts["out_of_order_timestamp_count"] += 1
                continue
            open_job.process.write_record(t, rec)
            counts["processed_record_count"] += 1
            ms = int(t * 1000)
            if counts["earliest_record_timestamp"] is None \
                    or ms < counts["earliest_record_timestamp"]:
                counts["earliest_record_timestamp"] = ms
            if latest is None or ms > latest:
                counts["latest_record_timestamp"] = ms
        self._save()
        return dict(counts)

    def flush_job(self, job_id: str, calc_interim: bool = False) -> dict:
        self._require(job_id)
        open_job = self._open.get(job_id)
        if open_job is None:
            raise IllegalArgumentError(
                f"Cannot flush because job [{job_id}] is not open")
        ack = open_job.process.flush()
        self._persist_results(job_id, open_job.take_results())
        return {"flushed": True,
                "last_finalized_bucket_end":
                    int(ack.get("last_finalized_bucket_end", 0))}

    def job_stats(self, job_id: Optional[str] = None) -> dict:
        out = []
        resp = self.get_jobs(job_id)
        for job in resp["jobs"]:
            jid = job["job_id"]
            out.append({
                "job_id": jid,
                "state": "opened" if jid in self._open else "closed",
                "data_counts": dict(self.data_counts.get(jid, {})),
                "model_size_stats": {"job_id": jid, "result_type":
                                     "model_size_stats"},
                "node": {"id": self.node.node_id} if jid in self._open else None,
            })
        return {"count": len(out), "jobs": out}

    # -------------------------------------------------------------- results
    def _ensure_results_index(self) -> None:
        """Reference: the ML results index template (keyword identity fields
        so term filters on hyphenated job ids match exactly)."""
        if self.node.indices.exists(RESULTS_INDEX):
            return
        self.node.create_index_with_templates(RESULTS_INDEX, mappings={
            "properties": {
                "job_id": {"type": "keyword"},
                "result_type": {"type": "keyword"},
                "function": {"type": "keyword"},
                "field_name": {"type": "keyword"},
                "partition_field_name": {"type": "keyword"},
                "partition_field_value": {"type": "keyword"},
                "by_field_name": {"type": "keyword"},
                "by_field_value": {"type": "keyword"},
                "timestamp": {"type": "date"},
                "anomaly_score": {"type": "double"},
                "record_score": {"type": "double"},
                "probability": {"type": "double"},
            }})

    def _persist_results(self, job_id: str, results: List[dict]) -> None:
        if not results:
            return
        self._ensure_results_index()
        for msg in results:
            doc = {k: v for k, v in msg.items() if k != "type"}
            self.node.index_doc(RESULTS_INDEX, None, doc)
        self.node.indices.get(RESULTS_INDEX).refresh()

    def get_buckets(self, job_id: str, body: Optional[dict] = None) -> dict:
        return self._results(job_id, "bucket", body or {},
                             "anomaly_score", "buckets")

    def get_records(self, job_id: str, body: Optional[dict] = None) -> dict:
        return self._results(job_id, "record", body or {},
                             "record_score", "records")

    def get_overall_buckets(self, job_id: str, body: Optional[dict] = None) -> dict:
        res = self._results(job_id, "bucket", body or {}, "anomaly_score",
                            "buckets")
        buckets = [{"timestamp": b["timestamp"], "bucket_span": b["bucket_span"],
                    "overall_score": b["anomaly_score"],
                    "jobs": [{"job_id": job_id,
                              "max_anomaly_score": b["anomaly_score"]}],
                    "is_interim": False, "result_type": "overall_bucket"}
                   for b in res["buckets"]]
        return {"count": len(buckets), "overall_buckets": buckets}

    def _results(self, job_id: str, result_type: str, body: dict,
                 score_field: str, key: str) -> dict:
        self._require(job_id)
        # drain anything pending so results are live without an explicit flush
        open_job = self._open.get(job_id)
        if open_job is not None:
            self._persist_results(job_id, open_job.take_results())
        must = [{"term": {"job_id": job_id}},
                {"term": {"result_type": result_type}}]
        threshold = body.get("anomaly_score" if result_type == "bucket"
                             else "record_score")
        if threshold is not None:
            must.append({"range": {score_field: {"gte": float(threshold)}}})
        if body.get("start") is not None:
            must.append({"range": {"timestamp": {"gte": body["start"]}}})
        if body.get("end") is not None:
            must.append({"range": {"timestamp": {"lt": body["end"]}}})
        desc = bool(body.get("desc", False))
        sort_field = body.get("sort", "timestamp")
        try:
            resp = self.node.search(RESULTS_INDEX, {
                "query": {"bool": {"filter": must}},
                "size": int(body.get("size", body.get("page", {})
                                     .get("size", 100) if isinstance(
                                         body.get("page"), dict) else 100)),
                "from": int(body.get("from", 0)),
                "sort": [{sort_field: {"order": "desc" if desc else "asc"}}],
            })
        except ResourceNotFoundError:
            return {"count": 0, key: []}
        hits = [h["_source"] for h in resp["hits"]["hits"]]
        return {"count": resp["hits"]["total"]["value"], key: hits}

    def _require(self, job_id: str) -> dict:
        if job_id not in self.jobs:
            raise ResourceNotFoundError(f"No known job with id '{job_id}'")
        return self.jobs[job_id]

    def usage(self) -> dict:
        from elasticsearch_tpu.ml.process import autodetect_binary
        return {"available": True, "enabled": True,
                "jobs": {"count": len(self.jobs), "opened": len(self._open)},
                "datafeeds": {"count": len(self.node.datafeeds.datafeeds)},
                "native": autodetect_binary() is not None}

    def close_all(self) -> None:
        for job_id in list(self._open):
            try:
                self.close_job(job_id)
            except Exception:
                self._open.pop(job_id, None)


class DatafeedService:
    """Search-driven extraction feeding an anomaly job.

    Reference: `x-pack/plugin/ml/.../datafeed/DatafeedManager.java`,
    `DatafeedJob.java` — pages over the source indices ordered by time and
    posts to the job, flushing at the end of each search window.
    """

    def __init__(self, node):
        self.node = node
        self.datafeeds: Dict[str, dict] = {}
        self.states: Dict[str, str] = {}

    def put(self, datafeed_id: str, body: dict) -> dict:
        if datafeed_id in self.datafeeds:
            raise ResourceAlreadyExistsError(
                f"A datafeed with id [{datafeed_id}] already exists")
        job_id = body.get("job_id")
        if not job_id or job_id not in self.node.ml.jobs:
            raise ResourceNotFoundError(
                f"No known job with id '{job_id}'")
        if not body.get("indices"):
            raise ValidationError("A datafeed must specify indices")
        df = dict(body)
        df["datafeed_id"] = datafeed_id
        self.datafeeds[datafeed_id] = df
        self.states[datafeed_id] = "stopped"
        return df

    def get(self, datafeed_id: Optional[str] = None) -> dict:
        if datafeed_id and datafeed_id not in ("_all", "*"):
            if datafeed_id not in self.datafeeds:
                raise ResourceNotFoundError(
                    f"No datafeed with id [{datafeed_id}] exists")
            feeds = [self.datafeeds[datafeed_id]]
        else:
            feeds = [self.datafeeds[k] for k in sorted(self.datafeeds)]
        return {"count": len(feeds), "datafeeds": feeds}

    def delete(self, datafeed_id: str) -> None:
        if datafeed_id not in self.datafeeds:
            raise ResourceNotFoundError(
                f"No datafeed with id [{datafeed_id}] exists")
        del self.datafeeds[datafeed_id]
        self.states.pop(datafeed_id, None)

    def preview(self, datafeed_id: str, size: int = 10) -> List[dict]:
        df = self._require(datafeed_id)
        resp = self._search(df, size=size)
        return [h["_source"] for h in resp["hits"]["hits"]]

    def start(self, datafeed_id: str, start=None, end=None) -> dict:
        """Run the extraction synchronously over [start, end) and stop.

        The reference runs datafeeds as persistent tasks on a real-time
        schedule; batch (bounded) datafeeds run to `end` and auto-stop,
        which is the mode implemented here.
        """
        df = self._require(datafeed_id)
        job_id = df["job_id"]
        if job_id not in self.node.ml._open:
            raise IllegalArgumentError(
                f"cannot start datafeed [{datafeed_id}] because job "
                f"[{job_id}] is not open")
        self.states[datafeed_id] = "started"
        job = self.node.ml.jobs[job_id]
        time_field = (job.get("data_description") or {}).get("time_field",
                                                             "time")
        search_after = None
        total = 0
        try:
            while True:
                resp = self._search(df, size=1000, time_field=time_field,
                                    start=start, end=end,
                                    search_after=search_after)
                hits = resp["hits"]["hits"]
                if not hits:
                    break
                self.node.ml.post_data(job_id,
                                       [h["_source"] for h in hits])
                total += len(hits)
                search_after = hits[-1]["sort"]
            self.node.ml.flush_job(job_id)
        finally:
            self.states[datafeed_id] = "stopped"
        return {"started": True, "processed": total}

    def stop(self, datafeed_id: str) -> dict:
        self._require(datafeed_id)
        self.states[datafeed_id] = "stopped"
        return {"stopped": True}

    def stats(self, datafeed_id: Optional[str] = None) -> dict:
        resp = self.get(datafeed_id)
        return {"count": resp["count"],
                "datafeeds": [{"datafeed_id": d["datafeed_id"],
                               "state": self.states.get(d["datafeed_id"],
                                                        "stopped")}
                              for d in resp["datafeeds"]]}

    def _search(self, df: dict, size: int, time_field: str = "time",
                start=None, end=None, search_after=None) -> dict:
        query = df.get("query", {"match_all": {}})
        if start is not None or end is not None:
            rng = {}
            if start is not None:
                rng["gte"] = start
            if end is not None:
                rng["lt"] = end
            query = {"bool": {"filter": [query,
                                         {"range": {time_field: rng}}]}}
        # _doc tiebreak: without it, search_after drops the remainder of a
        # run of documents sharing one timestamp at a page boundary
        body = {"query": query, "size": size,
                "sort": [{time_field: {"order": "asc"}},
                         {"_doc": {"order": "asc"}}]}
        if search_after is not None:
            body["search_after"] = search_after
        index_expr = ",".join(df["indices"]) if isinstance(df["indices"], list) \
            else df["indices"]
        return self.node.search(index_expr, body)

    def _require(self, datafeed_id: str) -> dict:
        if datafeed_id not in self.datafeeds:
            raise ResourceNotFoundError(
                f"No datafeed with id [{datafeed_id}] exists")
        return self.datafeeds[datafeed_id]
