"""Native process management for the ML sidecar.

Reference mapping:
- `bootstrap/Spawner.java:42` — spawns native controller daemons at startup.
- `x-pack/plugin/ml/.../process/NativeController.java:26-37` — singleton that
  starts per-job processes on request.
- `ProcessPipes.java` / `AbstractNativeProcess.java` — named-pipe I/O with the
  C++ process; results parsed from JSON (`IndexingStateProcessor.java`).

Protocol here: 4-byte big-endian length + JSON payload, both directions
(see native/ml_autodetect.cc header). A reader thread drains result frames
and hands them to a callback; a pure-Python model with identical semantics
is used when no C++ toolchain is available (same fallback discipline as
elasticsearch_tpu/native for the search kernels).
"""

from __future__ import annotations

import json
import math
import os
import queue
import struct
import subprocess
import threading
from typing import Callable, Dict, List, Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_BIN_PATH = os.path.join(_NATIVE_DIR, "ml_autodetect")

_build_lock = threading.Lock()


def autodetect_binary() -> Optional[str]:
    """Locate (building on demand) the ml_autodetect binary, or None."""
    src = os.path.join(_NATIVE_DIR, "ml_autodetect.cc")
    if not os.path.exists(src):
        return _BIN_PATH if os.path.exists(_BIN_PATH) else None
    with _build_lock:
        if (os.path.exists(_BIN_PATH)
                and os.path.getmtime(_BIN_PATH) >= os.path.getmtime(src)):
            return _BIN_PATH
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "ml_autodetect"],
                           check=True, capture_output=True, timeout=180)
        except Exception:
            return None
    return _BIN_PATH if os.path.exists(_BIN_PATH) else None


class AutodetectProcess:
    """One running analytics process for one open job.

    Reference: NativeAutodetectProcess.java — writes records, reads results
    asynchronously, supports flush (with ack id) and state persistence.
    """

    def __init__(self, job_config: dict, result_handler: Callable[[dict], None],
                 state: Optional[dict] = None):
        self.job_id = job_config.get("job_id", "")
        self._handler = result_handler
        self._flush_acks: "queue.Queue[dict]" = queue.Queue()
        self._state_frames: "queue.Queue[dict]" = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False

        binary = autodetect_binary()
        if binary is not None:
            self._proc: Optional[subprocess.Popen] = subprocess.Popen(
                [binary], stdin=subprocess.PIPE, stdout=subprocess.PIPE)
            self._py: Optional[PyAutodetect] = None
            self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                            name=f"ml-reader[{self.job_id}]")
            self._reader.start()
        else:  # pragma: no cover - exercised only without a C++ toolchain
            self._proc = None
            self._py = PyAutodetect(job_config, self._dispatch)
        self._send({"type": "config", "job": job_config,
                    **({"state": state} if state else {})})

    @property
    def is_native(self) -> bool:
        return self._proc is not None

    # ----------------------------------------------------------------- I/O
    def _send(self, msg: dict) -> None:
        if self._closed:
            return
        if self._proc is not None:
            payload = json.dumps(msg).encode("utf-8")
            with self._lock:
                assert self._proc.stdin is not None
                self._proc.stdin.write(struct.pack(">I", len(payload)) + payload)
                self._proc.stdin.flush()
        else:
            assert self._py is not None
            self._py.handle(msg)

    def _read_loop(self) -> None:
        assert self._proc is not None and self._proc.stdout is not None
        stream = self._proc.stdout
        while True:
            hdr = stream.read(4)
            if len(hdr) < 4:
                break
            (n,) = struct.unpack(">I", hdr)
            payload = stream.read(n)
            if len(payload) < n:
                break
            try:
                msg = json.loads(payload)
            except ValueError:
                continue
            self._dispatch(msg)

    def _dispatch(self, msg: dict) -> None:
        t = msg.get("type")
        if t == "flush_ack":
            self._flush_acks.put(msg)
        elif t == "state":
            self._state_frames.put(msg)
        else:
            self._handler(msg)

    # ------------------------------------------------------------- commands
    def write_record(self, epoch_seconds: float, fields: dict) -> None:
        self._send({"type": "record", "time": epoch_seconds, "fields": fields})

    def flush(self, flush_id: str = "f", timeout: float = 30.0) -> dict:
        self._send({"type": "flush", "id": flush_id})
        return self._flush_acks.get(timeout=timeout)

    def persist_state(self, timeout: float = 30.0) -> dict:
        self._send({"type": "persist"})
        return self._state_frames.get(timeout=timeout).get("state", {})

    def close(self) -> None:
        if self._closed:
            return
        self._send({"type": "quit"})
        self._closed = True
        if self._proc is not None:
            assert self._proc.stdin is not None
            self._proc.stdin.close()
            self._proc.wait(timeout=30)
            if self._reader.is_alive():
                self._reader.join(timeout=10)

    def kill(self) -> None:
        self._closed = True
        if self._proc is not None:
            self._proc.kill()
            self._proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Pure-Python fallback model — protocol- and semantics-identical to
# native/ml_autodetect.cc so tests/behavior don't depend on a compiler.
# ---------------------------------------------------------------------------

class _Welford:
    __slots__ = ("n", "mean", "m2")

    def __init__(self, n=0.0, mean=0.0, m2=0.0):
        self.n, self.mean, self.m2 = n, mean, m2

    def add(self, x: float) -> None:
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self.m2 += d * (x - self.mean)

    def probability(self, x: float, side: int) -> float:
        if self.n < 3:
            return 1.0
        var = self.m2 / (self.n - 1) if self.n > 1 else 0.0
        sd = math.sqrt(var) if var > 0 else abs(self.mean) * 0.01 + 1e-9
        z = (x - self.mean) / sd
        if side < 0 and z > 0:
            return 1.0
        if side > 0 and z < 0:
            return 1.0
        p = math.erfc(abs(z) / math.sqrt(2.0))
        return p if side == 0 else p / 2


def _score(p: float) -> float:
    if p >= 1:
        return 0.0
    p = max(p, 1e-308)
    return max(0.0, min(100.0, -10 * math.log10(p) - 13))


class PyAutodetect:
    """In-process twin of native/ml_autodetect.cc (see its header comment)."""

    def __init__(self, job_config: dict, emit: Callable[[dict], None]):
        self._emit = emit
        self.job_id = job_config.get("job_id", "")
        ac = job_config.get("analysis_config", {}) or {}
        self.bucket_span = _parse_span(ac.get("bucket_span", 300))
        self.detectors: List[dict] = []
        for d in ac.get("detectors", []) or [{"function": "count"}]:
            fn = d.get("function", "count")
            side = 0
            if fn.startswith("low_"):
                side, fn = -1, fn[4:]
            elif fn.startswith("high_"):
                side, fn = 1, fn[5:]
            self.detectors.append({
                "function": fn, "side": side,
                "field_name": d.get("field_name", ""),
                "by_field": d.get("by_field_name", ""),
                "partition_field": d.get("partition_field_name", ""),
                "models": {}, "rare": {},
            })
        if not self.detectors:
            self.detectors.append({"function": "count", "side": 0,
                                   "field_name": "", "by_field": "",
                                   "partition_field": "", "models": {},
                                   "rare": {}})
        self.bucket_start = -1.0
        self.latest_time = -1.0
        self.accum: Dict[tuple, dict] = {}

    def handle(self, msg: dict) -> None:
        t = msg.get("type")
        if t == "record":
            self._add(msg.get("time", 0), msg.get("fields", {}) or {})
        elif t == "flush":
            if self.accum:
                self._close_bucket()
            self._emit({"type": "flush_ack", "id": msg.get("id", ""),
                        "last_finalized_bucket_end":
                            self.bucket_start * 1000 if self.bucket_start > 0 else 0})
        elif t == "persist":
            self._emit({"type": "state", "state": self._state()})
        elif t == "config":
            st = msg.get("state")
            if st:
                self._restore(st)
        elif t == "quit":
            if self.accum:
                self._close_bucket()

    # ------------------------------------------------------------ modelling
    def _entity(self, det: dict, fields: dict) -> str:
        part = str(fields.get(det["partition_field"], "")) if det["partition_field"] else ""
        by = ""
        if det["by_field"] and det["function"] not in ("rare", "distinct_count"):
            by = str(fields.get(det["by_field"], ""))
        return part + "\x1e" + by

    def _add(self, t: float, fields: dict) -> None:
        if t < self.latest_time:
            return
        if self.bucket_start >= 0 and t < self.bucket_start:
            return  # bucket already finalized by flush
        self.latest_time = t
        bstart = math.floor(t / self.bucket_span) * self.bucket_span
        if self.bucket_start < 0:
            self.bucket_start = bstart
        while bstart >= self.bucket_start + self.bucket_span:
            self._close_bucket()
        for i, det in enumerate(self.detectors):
            key = (i, self._entity(det, fields))
            agg = self.accum.setdefault(
                key, {"count": 0.0, "sum": 0.0, "min": math.inf,
                      "max": -math.inf, "by": {}})
            agg["count"] += 1
            if det["field_name"]:
                v = fields.get(det["field_name"])
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    agg["sum"] += v
                    agg["min"] = min(agg["min"], v)
                    agg["max"] = max(agg["max"], v)
                else:
                    agg["count"] -= 1
            if det["by_field"] and det["function"] in ("rare", "distinct_count"):
                bv = fields.get(det["by_field"])
                if bv is not None and bv != "":
                    agg["by"][str(bv)] = agg["by"].get(str(bv), 0) + 1

    def _close_bucket(self) -> None:
        if self.bucket_start < 0:
            return
        max_score = 0.0
        records: List[dict] = []
        for i, det in enumerate(self.detectors):
            for (di, entity), agg in list(self.accum.items()):
                if di != i:
                    continue
                if det["function"] == "rare":
                    rm = det["rare"].setdefault(entity, {"counts": {}, "total": 0.0})
                    for bv, c in agg["by"].items():
                        if rm["total"] < 10:
                            p = 1.0
                        else:
                            p = (rm["counts"].get(bv, 0) + 1) / (rm["total"] + 1)
                        s = _score(p)
                        if s > 0.1:
                            records.append(self._record(det, entity, bv, s, p, c, 0))
                        max_score = max(max_score, s)
                    for bv, c in agg["by"].items():
                        rm["counts"][bv] = rm["counts"].get(bv, 0) + c
                        rm["total"] += c
                    continue
                fn = det["function"]
                if fn == "count":
                    actual = agg["count"]
                elif fn == "sum":
                    actual = agg["sum"]
                elif fn == "min":
                    actual = agg["min"] if agg["count"] else 0.0
                elif fn == "max":
                    actual = agg["max"] if agg["count"] else 0.0
                elif fn == "distinct_count":
                    actual = float(len(agg["by"]))
                else:
                    actual = agg["sum"] / agg["count"] if agg["count"] else 0.0
                m = det["models"].setdefault(entity, _Welford())
                p = m.probability(actual, det["side"])
                s = _score(p)
                if s > 0.1:
                    records.append(self._record(det, entity, "", s, p, actual, m.mean))
                max_score = max(max_score, s)
                m.add(actual)
        event_count = sum(a["count"] for (di, _), a in self.accum.items() if di == 0)
        self._emit({"type": "bucket", "job_id": self.job_id,
                    "timestamp": self.bucket_start * 1000,
                    "bucket_span": self.bucket_span,
                    "anomaly_score": max_score,
                    "initial_anomaly_score": max_score,
                    "event_count": event_count, "is_interim": False,
                    "result_type": "bucket"})
        for r in records:
            self._emit(r)
        self.accum.clear()
        self.bucket_start += self.bucket_span

    def _record(self, det, entity, by_value, score, prob, actual, typical) -> dict:
        part, _, byv = entity.partition("\x1e")
        prefix = {-1: "low_", 1: "high_", 0: ""}[det["side"]]
        r = {"type": "record", "job_id": self.job_id, "result_type": "record",
             "timestamp": self.bucket_start * 1000,
             "bucket_span": self.bucket_span, "record_score": score,
             "initial_record_score": score, "probability": prob,
             "function": prefix + det["function"], "actual": [actual],
             "is_interim": False}
        if det["field_name"]:
            r["field_name"] = det["field_name"]
        if det["partition_field"]:
            r["partition_field_name"] = det["partition_field"]
            r["partition_field_value"] = part
        if det["by_field"]:
            r["by_field_name"] = det["by_field"]
            r["by_field_value"] = by_value or byv
        if det["function"] != "rare":
            r["typical"] = [typical]
        return r

    # --------------------------------------------------------------- state
    def _state(self) -> dict:
        dets = []
        for det in self.detectors:
            dets.append({
                "models": {k: [m.n, m.mean, m.m2]
                           for k, m in det["models"].items()},
                "rare": {k: dict(v["counts"]) for k, v in det["rare"].items()},
            })
        return {"detectors": dets, "latest_time": self.latest_time}

    def _restore(self, st: dict) -> None:
        for i, d in enumerate(st.get("detectors", [])):
            if i >= len(self.detectors):
                break
            det = self.detectors[i]
            for k, (n, mean, m2) in (d.get("models") or {}).items():
                det["models"][k] = _Welford(n, mean, m2)
            for k, counts in (d.get("rare") or {}).items():
                det["rare"][k] = {"counts": dict(counts),
                                  "total": float(sum(counts.values()))}
        self.latest_time = st.get("latest_time", -1)


def _parse_span(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    if s and s[-1] in units:
        try:
            return float(s[:-1]) * units[s[-1]]
        except ValueError:
            pass
    try:
        return float(s)
    except ValueError:
        return 300.0
