"""Machine learning: anomaly-detection jobs backed by a native C++ sidecar.

Reference: `x-pack/plugin/ml` (56k LoC) + external `elastic/ml-cpp` processes
spawned by `bootstrap/Spawner.java:42` and driven through named pipes
(`x-pack/plugin/ml/.../process/NativeController.java:26-37`, `ProcessPipes.java`,
`AbstractNativeProcess.java`). Here the analytics engine is
`native/ml_autodetect.cc`, a standalone C++ process speaking length-prefixed
JSON over stdin/stdout, managed by :mod:`elasticsearch_tpu.ml.process`.
"""

from elasticsearch_tpu.ml.service import DatafeedService, MlService

__all__ = ["MlService", "DatafeedService"]
