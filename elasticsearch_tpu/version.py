"""Framework version.

Mirrors the role of `server/src/main/java/org/elasticsearch/Version.java:81`
(reference Version.CURRENT = V_8_0_0): a single version constant that also
participates in the wire protocol handshake (see common/serialization.py).
"""

__version__ = "0.1.0"

# Wire-format version id, monotonically increasing. Peers negotiate the
# minimum of their versions on connect (reference: TcpTransport.java:796).
WIRE_VERSION = 1
