"""Search profiler: per-shard timing breakdowns for `"profile": true`.

Reference: `search/profile/` — `QueryProfiler` wraps each Lucene query with
an `AbstractProfileBreakdown` of timed stages; `AggregationProfiler` does
the same for aggs. This engine executes a query as one vectorized pass, so
the breakdown reports that pass's phases (the per-doc advance/score split of
the reference collapses into `score` here — a faithful description of how
the device path actually spends its time).
"""

from __future__ import annotations

from typing import Any, Optional


def _describe_query(body: dict) -> tuple:
    q = (body or {}).get("query", {"match_all": {}})
    if not isinstance(q, dict) or not q:
        return "MatchAllDocsQuery", "*:*"
    kind = next(iter(q))
    import json
    return kind, json.dumps(q.get(kind), default=str)[:200]


def hybrid_profile(index_name: str, plan_nanos: int, score_nanos: int,
                   fuse_nanos: int, hydrate_nanos: int, plan_cache_hit: bool,
                   batch_size: int, legs: list,
                   dispatch_events: Optional[list] = None,
                   mesh: Optional[dict] = None,
                   queue_wait_nanos: Optional[int] = None,
                   device_dispatch_nanos: Optional[int] = None,
                   device_sync_nanos: Optional[int] = None,
                   scheduler: Optional[dict] = None) -> dict:
    """`profile` section for a fused hybrid (rank.rrf) search
    (search/hybrid_plan.py): the four plan phases — plan (parse/compile or
    cache hit), score (the batched leg dispatches), fuse (vectorized RRF),
    hydrate (fetch of the final window) — plus per-leg engine detail.

    score/fuse/hydrate are BATCH times: whole hybrid queries coalesce
    through the serving batcher, so the device work the timing describes
    was shared by `batch_size` queries (the per-query marginal cost is
    time/batch_size; reporting the honest batch figure keeps the profile
    additive with wall clock).

    Tail attribution (the closed-loop p99 split): `queue_wait_nanos` is
    how long the batch's longest-waiting member sat in the admission
    queue before being scheduled; `device_dispatch_nanos` /
    `device_sync_nanos` split score time into the launch share (the
    locked dispatch stage) and the deferred device wait at finalize —
    so a red p99/p50 gate is diagnosable as queueing vs device-launch
    vs device-wait vs hydrate directly from the profile. `scheduler`
    carries the continuous batcher's cumulative counters (topups,
    deadline_sheds, overlap_hits).

    dispatch_events: the per-kernel dispatch trace of this batch's score
    phase (`ops/dispatch.py` record_events) — which shape bucket each
    device dispatch hit, whether its executable was cached, and what any
    compile cost. A steady-state batch shows every event as a hit."""
    breakdown = {"plan_nanos": plan_nanos,
                 "score_nanos": score_nanos,
                 "fuse_nanos": fuse_nanos,
                 "hydrate_nanos": hydrate_nanos}
    if queue_wait_nanos is not None:
        breakdown["queue_wait_nanos"] = queue_wait_nanos
    if device_dispatch_nanos is not None:
        breakdown["device_dispatch_nanos"] = device_dispatch_nanos
    if device_sync_nanos is not None:
        breakdown["device_sync_nanos"] = device_sync_nanos
    out = {"hybrid": {
        "id": f"[{index_name}][0]",
        "plan_cache": "hit" if plan_cache_hit else "miss",
        "batch_size": batch_size,
        "breakdown": breakdown,
        "legs": legs}}
    if scheduler is not None:
        out["hybrid"]["scheduler"] = scheduler
    if dispatch_events is not None:
        out["hybrid"]["dispatch"] = dispatch_events
    if mesh is not None:
        # this batch's SPMD execution (parallel/policy.py counter deltas
        # captured around the score phase): which legs rode the mesh,
        # shard-local vs host-merge time, analytic all-gather bytes, and
        # the router's mesh-vs-single-device decisions. Batch-scoped like
        # score_nanos above — the device work was shared by batch_size
        # queries.
        out["hybrid"]["mesh"] = mesh
    return out


def mesh_stats_delta(before: dict, after: dict) -> Optional[dict]:
    """What one batch did on the serving mesh: the difference between two
    `parallel/policy.stats()` snapshots taken around the batch's score
    phase. Returns None when nothing routed to the mesh in between (the
    hybrid profile omits its `mesh` key for single-device batches)."""
    legs = {}
    for leg, a in (after.get("legs") or {}).items():
        b = (before.get("legs") or {}).get(leg, {})
        d = {key: a[key] - b.get(key, 0) for key in a}
        if d.get("dispatches", 0) > 0:
            legs[leg] = d
    ra, rb = after.get("router", {}), before.get("router", {})
    router = {
        "mesh": ra.get("mesh", 0) - rb.get("mesh", 0),
        "single_device": (ra.get("single_device", 0)
                          - rb.get("single_device", 0)),
        "reasons": {r: n - rb.get("reasons", {}).get(r, 0)
                    for r, n in ra.get("reasons", {}).items()
                    if n - rb.get("reasons", {}).get(r, 0)},
    }
    if not legs and not router["mesh"]:
        return None
    return {"shards": after.get("num_shards", 0), "legs": legs,
            "router": router}


def shard_profile(index_name: str, body: dict, query_nanos: int,
                  fetch_nanos: int, total_hits: int,
                  knn_phases: Optional[dict] = None,
                  dispatch_events: Optional[list] = None,
                  aggs_profile: Optional[dict] = None,
                  cache: Optional[dict] = None) -> dict:
    kind, description = _describe_query(body)
    breakdown = {
        "score": query_nanos * 7 // 10,
        "build_scorer": query_nanos * 2 // 10,
        "create_weight": query_nanos * 1 // 10,
        "next_doc": 0, "advance": 0, "match": 0,
        "compute_max_score": 0, "set_min_competitive_score": 0,
        "score_count": total_hits, "next_doc_count": 0, "advance_count": 0,
        "build_scorer_count": 1, "create_weight_count": 1, "match_count": 0,
        "compute_max_score_count": 0, "set_min_competitive_score_count": 0,
        "shallow_advance": 0, "shallow_advance_count": 0,
    }
    profile = {
        "id": f"[{index_name}][0]",
        "searches": [{
            "query": [{
                "type": kind,
                "description": description,
                "time_in_nanos": query_nanos,
                "breakdown": breakdown,
            }],
            "rewrite_time": 0,
            "collector": [{
                "name": "SimpleTopScoreDocCollector",
                "reason": "search_top_hits",
                "time_in_nanos": query_nanos,
            }],
        }],
        "fetch": {
            "type": "fetch",
            "description": "",
            "time_in_nanos": fetch_nanos,
            "breakdown": {"load_stored_fields": fetch_nanos,
                          "load_stored_fields_count": total_hits,
                          "next_reader": 0, "next_reader_count": 0},
        },
        "aggregations": [],
    }
    if knn_phases:
        # per-phase kNN engine breakdown (tpu_ivf: route = centroid
        # matmul + probe selection, score = pruned partition matmuls +
        # device top-k, merge = row-map join / result shaping; exhaustive
        # fallbacks report engine + reason only)
        profile["knn"] = {
            "engine": knn_phases.get("engine"),
            **{key: knn_phases[key]
               for key in ("nprobe", "nlist", "scored_rows",
                           "fallback_reason",
                           # generational-corpus annotations
                           # (segments/): how many device generations
                           # this search fanned over and what it masked
                           "generations", "l0_generations",
                           "tombstoned_rows", "legs",
                           # columnar segment-block-store ledger: the
                           # field's last refresh composition (cached /
                           # delta / full extraction counts)
                           "columnar",
                           # quant-subsystem legs: did the IVF probes
                           # run the fused Pallas gather+score kernel,
                           # and the two-phase exact-rescore window
                           # (size / promotions / nanos)
                           "fused_probe", "rescore")
               if key in knn_phases},
            "breakdown": {
                key: knn_phases[key]
                for key in ("route_nanos", "score_nanos", "merge_nanos")
                if key in knn_phases},
        }
    if knn_phases and "mesh_shards" in knn_phases:
        # SPMD execution detail (`profile.mesh`): the kNN leg ran as one
        # shard_map program over the serving mesh — shard count, the
        # in-program local work vs host-side merge split, and the
        # analytic ICI all-gather payload of the candidate merge
        profile["mesh"] = {
            "shards": knn_phases["mesh_shards"],
            "collective_bytes": knn_phases.get("collective_bytes", 0),
            "breakdown": {
                "local_nanos": knn_phases.get("score_nanos", 0),
                "merge_nanos": knn_phases.get("merge_nanos", 0)},
        }
    if dispatch_events:
        # shape-bucket trace of this shard's device dispatches (see
        # ops/dispatch.py): bucket key, executable-cache hit/miss, compile
        # cost. Steady-state searches report hits only. The trace is
        # thread-local: a query coalesced into ANOTHER request's device
        # batch reports its dispatches in that batch leader's trace, so a
        # profiled search under concurrency may show an empty list even
        # though kernels ran — `_nodes/stats indices.dispatch` is the
        # authoritative counter. Events a profiled LEADER executed on
        # behalf of a coalesced batch carry `coalesced_batch: N`
        # (serving/batcher.py annotates them), so a leader's trace is
        # explicit about which device work was shared with N-1 followers
        # rather than silently claiming it as its own.
        profile["dispatch"] = dispatch_events
    if (body or {}).get("aggs") or (body or {}).get("aggregations"):
        aggs = body.get("aggs") or body.get("aggregations")
        # the device-agg engine (search/agg_plan.py) reports which nodes
        # reduced on device vs fell through to the host walkers; `collect`
        # carries the device-dispatch time, `build_aggregation` the host
        # assembly time (both whole-request figures attributed to each
        # device node — the engine times the fused pass, not per node)
        engines = {n["name"]: n
                   for n in (aggs_profile or {}).get("nodes", [])}
        entries = []
        for name, spec in aggs.items():
            info = engines.get(name, {})
            on_device = str(info.get("engine", "")).startswith("device")
            device_ns = (aggs_profile or {}).get("device_nanos", 0) \
                if on_device else 0
            assemble_ns = (aggs_profile or {}).get("assemble_nanos", 0) \
                if on_device else 0
            entry = {
                "type": next(iter(spec.keys()
                                  - {"aggs", "aggregations", "meta"}),
                             "unknown"),
                "description": name,
                "time_in_nanos": device_ns + assemble_ns,
                "breakdown": {"collect": device_ns,
                              "collect_count": total_hits,
                              "build_aggregation": assemble_ns,
                              "build_aggregation_count": 1,
                              "initialize": 0, "initialize_count": 1,
                              "reduce": 0, "reduce_count": 0}}
            if info:
                entry["engine"] = info["engine"]
                if "fallback_reason" in info:
                    entry["fallback_reason"] = info["fallback_reason"]
            entries.append(entry)
        profile["aggregations"] = entries
        if (aggs_profile or {}).get("columnar"):
            # segment-block-store ledger for the agg columns this
            # request read (per field: blocks, cached vs extracted,
            # composition mode) — the profile half of
            # `_nodes/stats indices.columnar`
            profile["columnar"] = aggs_profile["columnar"]
    if cache is not None:
        # shard request-cache state of THIS execution: which rung the
        # body was eligible for and whether the query phase was served
        # from it (a hit's query_nanos covers only the fetch re-run)
        profile["cache"] = cache
    return profile


def trace_profile(trace) -> dict:
    """`profile.trace` section: the request's telemetry trace id plus its
    longest spans so far — the bridge from the opt-in per-request profile
    to the always-on trace ring (`GET _nodes/traces` serves the full span
    tree under this id, including remote segments a cross-node search
    absorbed)."""
    return {"trace_id": trace.trace_id, "top_spans": trace.top_spans(5)}


def fanout_profile(phases: dict) -> dict:
    """`profile.fanout` section for a cross-node search (serving/
    fanout.py): per-phase fan-out counts, budgets, elapsed time, and the
    partial-result attribution — how many shards answered, failed, timed
    out on the coordinator's per-shard timer, or were shed by the REMOTE
    node's own admission layer on the propagated deadline. A red
    `timed_out: true` response is diagnosable from this section alone:
    `shed` says the deadline traveled and the remote enforced it;
    `timed_out` says a node went silent and the backstop timer fired."""
    out = {}
    for phase, summary in phases.items():
        out[phase] = {
            "targets": summary.get("launched", 0),
            "budget_ms": summary.get("budget_ms", 0),
            "elapsed_ms": summary.get("elapsed_ms", 0),
            "ok": summary.get("ok", 0),
            "failed": summary.get("failed", 0),
            "coordinator_timeouts": summary.get("timed_out", 0),
            "remote_sheds": summary.get("shed", 0),
            "timed_out": bool(summary.get("any_timed_out", False)),
        }
    return out
