"""Aggregations: bucket, metric, and pipeline aggs over candidate rows.

Re-design of `search/aggregations/` (SURVEY.md §2.5, ~45k LoC): instead of
per-doc collector trees, every aggregation reduces **vectorized** over the
matching row set (numpy today; the partial-reduction shape is chosen so
per-shard partials can later batch onto the device and merge cross-shard
like `InternalAggregation.reduce`).

Buckets carry their row subsets so sub-aggregations recurse naturally.
Pipeline aggs post-process sibling/parent bucket outputs, mirroring
`search/aggregations/pipeline/`.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    ArrayIndexOutOfBoundsError, IllegalArgumentError, ParsingError,
)
from elasticsearch_tpu.index.mapping import parse_date_millis
from elasticsearch_tpu.search.queries import SearchContext, parse_query

# ---------------------------------------------------------------------------
# value source helpers
# ---------------------------------------------------------------------------
#
# The hot path used to be a per-row `reader.get_doc_value` loop — a Python
# call plus a linear segment scan (`ShardReader.resolve`) per row, so a
# terms agg over 100k matched rows cost 100k interpreter round-trips. The
# columnar fast path below concatenates each segment's DocValuesColumn
# once per reader snapshot (cached on the reader instance; a refresh makes
# a new reader, invalidating implicitly) and turns every lookup into a
# vectorized searchsorted + gather. The device agg store
# (`ops/aggs.AggFieldStore`) builds its resident columns from the same
# per-segment columns.


def _reader_columnar(reader, field: str):
    """Dense numeric column over the reader's max_doc space (segment-major
    concat): (bases, sizes, offsets, vals f64, present bool) — or None
    when any segment's column isn't numeric (the caller loops)."""
    cache = reader.__dict__.setdefault("_agg_columnar", {})
    key = ("num", field)
    if key in cache:
        return cache[key]
    bases, sizes, offsets = [], [], []
    vals_parts, pres_parts = [], []
    total = 0
    ent = None
    numeric_ok = True
    for view in reader.views:
        seg = view.segment
        bases.append(seg.base)
        sizes.append(seg.num_docs)
        offsets.append(total)
        col = seg.doc_values.get(field)
        if col is None:
            vals_parts.append(np.full(seg.num_docs, np.nan,
                                      dtype=np.float64))
            pres_parts.append(np.zeros(seg.num_docs, dtype=bool))
        elif col.numeric is not None:
            v = col.numeric.copy()
            v[~col.present] = np.nan  # the documented absent-value shape
            vals_parts.append(v)
            pres_parts.append(col.present)
        else:
            numeric_ok = False
            break
        total += seg.num_docs
    if numeric_ok:
        ent = (np.asarray(bases, dtype=np.int64),
               np.asarray(sizes, dtype=np.int64),
               np.asarray(offsets, dtype=np.int64),
               np.concatenate(vals_parts) if vals_parts
               else np.zeros(0, dtype=np.float64),
               np.concatenate(pres_parts) if pres_parts
               else np.zeros(0, dtype=bool))
    cache[key] = ent
    return ent


def _reader_objects(reader, field: str):
    """Dense raw-value object column (same layout as _reader_columnar);
    always available — replaces the per-row resolve() scan."""
    cache = reader.__dict__.setdefault("_agg_columnar", {})
    key = ("obj", field)
    if key in cache:
        return cache[key]
    bases, sizes, offsets = [], [], []
    parts = []
    total = 0
    for view in reader.views:
        seg = view.segment
        bases.append(seg.base)
        sizes.append(seg.num_docs)
        offsets.append(total)
        col = seg.doc_values.get(field)
        arr = np.empty(seg.num_docs, dtype=object)
        if col is not None:
            for i, v in enumerate(col.values):
                arr[i] = v
        parts.append(arr)
        total += seg.num_docs
    ent = (np.asarray(bases, dtype=np.int64),
           np.asarray(sizes, dtype=np.int64),
           np.asarray(offsets, dtype=np.int64),
           np.concatenate(parts) if parts
           else np.zeros(0, dtype=object))
    cache[key] = ent
    return ent


def _gather_positions(bases, sizes, offsets, rows):
    """rows (engine global) -> (dense positions, in-bounds mask)."""
    vi = np.searchsorted(bases, rows, side="right") - 1
    vi = np.clip(vi, 0, max(len(bases) - 1, 0))
    loc = rows - bases[vi]
    ok = (loc >= 0) & (loc < sizes[vi])
    return offsets[vi] + np.where(ok, loc, 0), ok


def numeric_values(ctx: SearchContext, rows: np.ndarray, field: str,
                   missing: Optional[float] = None) -> Tuple[np.ndarray, np.ndarray]:
    """(values float64[], present bool[]) for one field over rows.

    Multi-valued docs contribute their first value here; use all_values for
    per-value expansion (terms/cardinality need it).
    """
    field = ctx.mapper_service.resolve_field(field)
    rows = np.asarray(rows, dtype=np.int64)
    ent = _reader_columnar(ctx.reader, field) if len(rows) else None
    if ent is not None and len(ent[0]):
        bases, sizes, offsets, dvals, dpres = ent
        t, ok = _gather_positions(bases, sizes, offsets, rows)
        vals = np.where(ok, dvals[t], np.nan)
        present = ok & dpres[t]
        if missing is not None:
            vals[~present] = missing
            present = np.ones(len(rows), dtype=bool)
        return vals, present
    vals = np.full(len(rows), np.nan, dtype=np.float64)
    present = np.zeros(len(rows), dtype=bool)
    for i, row in enumerate(rows):
        v = ctx.reader.get_doc_value(field, int(row))
        if isinstance(v, list):
            v = v[0] if v else None
        if v is None:
            continue
        if isinstance(v, bool):
            v = 1.0 if v else 0.0
        if isinstance(v, (int, float)):
            vals[i] = float(v)
            present[i] = True
        elif isinstance(v, tuple):  # geo_point
            continue
    if missing is not None:
        vals[~present] = missing
        present[:] = True
    return vals, present


def all_values(ctx: SearchContext, rows: np.ndarray, field: str) -> List[Tuple[int, Any]]:
    """[(row_index, value)] expanded over multi-valued fields."""
    if field == "_index":
        name = getattr(ctx, "index_name", "index")
        return [(i, name) for i in range(len(rows))]
    field = ctx.mapper_service.resolve_field(field)
    rows = np.asarray(rows, dtype=np.int64)
    out: List[Tuple[int, Any]] = []
    ent = _reader_objects(ctx.reader, field) if len(rows) else None
    if ent is not None and len(ent[0]):
        bases, sizes, offsets, dobjs = ent
        t, ok = _gather_positions(bases, sizes, offsets, rows)
        taken = dobjs[t]
        for i in range(len(rows)):
            if not ok[i]:
                continue
            v = taken[i]
            if v is None:
                continue
            if isinstance(v, list):
                for item in v:
                    if item is not None:
                        out.append((i, item))
            else:
                out.append((i, v))
        return out
    for i, row in enumerate(rows):
        v = ctx.reader.get_doc_value(field, int(row))
        if v is None:
            continue
        if isinstance(v, list):
            for item in v:
                if item is not None:
                    out.append((i, item))
        else:
            out.append((i, v))
    return out


# ---------------------------------------------------------------------------
# metric aggregations
# ---------------------------------------------------------------------------

def _es_percentile(v_sorted: np.ndarray, p: float):
    """TDigest singleton-centroid quantile (TDigestState): centroid i sits at
    cumulative position i+0.5, extremes clamp to min/max — NOT numpy's
    linear-between-order-statistics interpolation."""
    n = len(v_sorted)
    if n == 0:
        return None
    if n == 1:
        return float(v_sorted[0])
    idx = p / 100.0 * n
    return float(np.interp(idx, np.arange(n) + 0.5, v_sorted))


def _metric_stats(vals: np.ndarray, present: np.ndarray) -> dict:
    v = vals[present]
    n = len(v)
    if n == 0:
        return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
    return {"count": int(n), "min": float(v.min()), "max": float(v.max()),
            "avg": float(v.mean()), "sum": float(v.sum())}


def _extended_stats(vals: np.ndarray, present: np.ndarray, sigma: float = 2.0) -> dict:
    base = _metric_stats(vals, present)
    v = vals[present]
    if len(v) == 0:
        base.update({"sum_of_squares": None, "variance": None, "std_deviation": None,
                     "std_deviation_bounds": {"upper": None, "lower": None}})
        return base
    ss = float((v ** 2).sum())
    var = float(v.var())
    std = float(v.std())
    mean = base["avg"]
    base.update({
        "sum_of_squares": ss, "variance": var,
        "variance_population": var, "variance_sampling":
            float(v.var(ddof=1)) if len(v) > 1 else 0.0,
        "std_deviation": std,
        "std_deviation_bounds": {"upper": mean + sigma * std, "lower": mean - sigma * std},
    })
    return base


_NUMERIC_ONLY_METRICS = {
    "sum", "avg", "min", "max", "stats", "extended_stats", "percentiles",
    "percentile_ranks", "median_absolute_deviation", "weighted_avg",
}


def compute_metric(ctx: SearchContext, rows: np.ndarray, kind: str, spec: dict,
                   name: str = "") -> Any:
    if kind in _NUMERIC_ONLY_METRICS:
        mapper = ctx.mapper_service.get(spec.get("field", "")) \
            if spec.get("field") else None
        tname = getattr(mapper, "type_name", None)
        if tname in ("keyword", "text"):
            raise IllegalArgumentError(
                f"Field [{spec.get('field')}] of type [{tname}] is not "
                f"supported for aggregation [{kind}]")
    field = spec.get("field")
    missing = spec.get("missing")
    script = spec.get("script")

    if kind == "string_stats":
        return compute_string_stats(ctx, rows, spec)
    if kind == "top_metrics":
        return compute_top_metrics(ctx, rows, spec)
    if kind == "matrix_stats":
        return compute_matrix_stats(ctx, rows, spec)
    if kind == "scripted_metric":
        state = scripted_metric_map_combine(ctx, rows, spec)
        return {"value": scripted_metric_reduce(spec, [state])}

    if kind == "top_hits":
        return _top_hits(ctx, rows, spec)

    if kind == "value_count":
        if field is None:
            return {"value": len(rows)}
        values = all_values(ctx, rows, field)
        count = len(values)
        if missing is not None:
            count += len(rows) - len({i for i, _ in values})
        return {"value": count}

    if kind in ("geo_bounds", "geo_centroid"):
        pts = _gather_geo_points(ctx, rows, field)
        if not pts:
            return ({"bounds": None} if kind == "geo_bounds"
                    else {"count": 0})
        lats = np.asarray([p[1] for p in pts])
        lons = np.asarray([p[2] for p in pts])
        if kind == "geo_bounds":
            return {"bounds": {
                "top_left": {"lat": float(lats.max()), "lon": float(lons.min())},
                "bottom_right": {"lat": float(lats.min()),
                                 "lon": float(lons.max())}}}
        return {"location": {"lat": float(lats.mean()),
                             "lon": float(lons.mean())},
                "count": len(pts)}

    if kind == "cardinality":
        pt = spec.get("precision_threshold")
        if pt is not None and int(pt) < 0:
            raise IllegalArgumentError(
                f"[precisionThreshold] must be greater than or equal to 0. "
                f"Found [{int(pt)}] in [{name}]")
        values = all_values(ctx, rows, field)
        distinct = {_hashable(v) for _, v in values}
        if missing is not None and len({i for i, _ in values}) < len(rows):
            distinct.add(_hashable(missing))
        return {"value": len(distinct)}

    if script is not None and field is None:
        from elasticsearch_tpu.search.script_score import Script
        s = Script(script)
        vals = s.evaluate(ctx, rows, np.zeros(len(rows), dtype=np.float32)).astype(np.float64)
        present = np.ones(len(rows), dtype=bool)
    else:
        vals, present = numeric_values(ctx, rows, field, missing)

    if kind == "avg":
        v = vals[present]
        out = {"value": float(v.mean()) if len(v) else None}
        tname = getattr(ctx.mapper_service.get(field), "type_name", None) \
            if field else None
        if out["value"] is not None and tname in ("date", "date_nanos"):
            ms = out["value"] / 1e6 if tname == "date_nanos" \
                else out["value"]
            out["value_as_string"] = _millis_to_iso(int(round(ms)))
        return out
    if kind == "sum":
        return {"value": float(vals[present].sum())}
    if kind == "min":
        v = vals[present]
        return {"value": float(v.min()) if len(v) else None}
    if kind == "max":
        v = vals[present]
        return {"value": float(v.max()) if len(v) else None}
    if kind == "stats":
        return _metric_stats(vals, present)
    if kind == "extended_stats":
        sigma = float(spec.get("sigma", 2.0))
        if sigma < 0:
            raise IllegalArgumentError(
                f"[sigma] must be greater than or equal to 0. "
                f"Found [{sigma}] in [{name}]")
        return _extended_stats(vals, present, sigma)
    if kind == "median_absolute_deviation":
        v = vals[present]
        if len(v) == 0:
            return {"value": None}
        med = np.median(v)
        return {"value": float(np.median(np.abs(v - med)))}
    if kind == "percentiles":
        pcts = spec.get("percents", [1, 5, 25, 50, 75, 95, 99])
        tdigest = spec.get("tdigest")
        if tdigest is not None and "compression" in tdigest:
            comp = float(tdigest["compression"] or 0)
            if comp < 0:
                raise IllegalArgumentError(
                    f"[compression] must be greater than or equal to 0. "
                    f"Found [{comp}] in [{name}]")
        v = np.sort(vals[present])
        hdr = spec.get("hdr")
        if hdr is not None:
            if v.size and v[0] < 0:
                # DoubleHistogram cannot record negatives: the reference
                # fails the whole shard (AIOOBE out of the aggregator), so
                # the same query returns the same hits with or without the
                # hdr agg attached — never a silently filtered result set
                raise ArrayIndexOutOfBoundsError("out of covered value range")
            raw_digits = hdr.get("number_of_significant_value_digits", 3)
            try:
                digits = int(raw_digits)
            except (TypeError, ValueError):
                raise IllegalArgumentError(
                    "[numberOfSignificantValueDigits] must be between 0 and 5")
            if not 0 <= digits <= 5:
                raise IllegalArgumentError(
                    "[numberOfSignificantValueDigits] must be between 0 and 5")

        def _hdr_quantize(x: float) -> float:
            """DoubleHistogram highestEquivalentValue: the reported value is
            the top of x's equivalent bucket at the configured precision
            (sub-bucket count 2^ceil(log2(10^digits)); base unit auto-ranged
            from the smallest recorded magnitude)."""
            if x <= 0 or len(v) == 0:
                return float(x)
            sub = 1 << math.ceil(math.log2(10 ** max(digits, 1)))
            vmin = float(v[v > 0][0]) if (v > 0).any() else 1.0
            unit = 2.0 ** math.floor(math.log2(vmin)) / sub
            erange = max(2.0 ** math.floor(math.log2(x)) / sub, unit)
            lowest = math.floor(x / erange) * erange
            return lowest + erange - unit

        def one(p):
            if len(v) == 0:
                return None
            if hdr is not None:
                # HDRHistogram.getValueAtPercentile: highest equivalent
                # value of the bucket at the rank (round-half-up, no
                # interpolation)
                rank = max(int(math.floor(p / 100.0 * len(v) + 0.5)), 1)
                rank = min(rank, len(v))
                return _hdr_quantize(float(v[rank - 1]))
            return _es_percentile(v, float(p))

        if spec.get("keyed", True) is False:
            return {"values": [{"key": float(p), "value": one(float(p))}
                               for p in pcts]}
        return {"values": {f"{float(p)}": one(float(p)) for p in pcts}}
    if kind == "percentile_ranks":
        targets = spec.get("values", [])
        v = np.sort(vals[present])
        out = {}
        for t in targets:
            if len(v) == 0:
                out[f"{float(t)}"] = None
            else:
                out[f"{float(t)}"] = float(100.0 * np.searchsorted(v, t, side="right") / len(v))
        return {"values": out}
    if kind == "weighted_avg":
        vspec = spec.get("value", {})
        wspec = spec.get("weight", {})
        vv, vp = numeric_values(ctx, rows, vspec.get("field"), vspec.get("missing"))
        wv, wp = numeric_values(ctx, rows, wspec.get("field"), wspec.get("missing", 1.0))
        both = vp & wp
        den = wv[both].sum()
        return {"value": float((vv[both] * wv[both]).sum() / den) if den else None}
    if kind == "boxplot":
        # reference: x-pack/plugin/analytics BoxplotAggregator
        v = vals[present]
        if len(v) == 0:
            return {"min": None, "max": None, "q1": None, "q2": None,
                    "q3": None, "lower": None, "upper": None}
        q1, q2, q3 = (float(np.percentile(v, p)) for p in (25, 50, 75))
        iqr = q3 - q1
        inside = v[(v >= q1 - 1.5 * iqr) & (v <= q3 + 1.5 * iqr)]
        return {"min": float(v.min()), "max": float(v.max()),
                "q1": q1, "q2": q2, "q3": q3,
                "lower": float(inside.min()) if len(inside) else q1,
                "upper": float(inside.max()) if len(inside) else q3}
    raise ParsingError(f"unknown metric aggregation [{kind}]")


def _script_source(s) -> str:
    if isinstance(s, dict):
        return s.get("source") or s.get("inline") or ""
    return s or ""


def scripted_metric_map_combine(ctx: SearchContext, rows: np.ndarray,
                                spec: dict):
    """One shard's init → map → combine, returning the shippable state
    (reference ScriptedMetricAggregator.java:38: init_script seeds
    `state`, map_script runs per matched doc with `doc` values, and
    combine_script folds the shard state into whatever crosses the wire
    to the coordinator). Scripts run on the sandboxed Painless
    interpreter (script/painless.py) with the same `doc[...]` bindings as
    script_score."""
    from elasticsearch_tpu.script.painless import (
        FrozenParams, compile_painless, execute,
    )
    from elasticsearch_tpu.search.script_score import _ScalarDoc

    params = FrozenParams(spec.get("params") or {})
    state: Dict[str, Any] = {}
    bindings = {"state": state, "params": params}
    init = _script_source(spec.get("init_script"))
    if init:
        execute(compile_painless(init), dict(bindings))
    map_src = _script_source(spec.get("map_script"))
    if not map_src:
        raise IllegalArgumentError(
            "[map_script] must be provided in [scripted_metric]")
    prog = compile_painless(map_src)
    score_of = None
    if "_score" in map_src:
        # the reference's map_script sees each doc's real score; the query
        # phase stashes agg-scope scores on the context (service.py)
        srows = getattr(ctx, "agg_score_rows", None)
        if srows is not None:
            score_of = {int(r): float(s)
                        for r, s in zip(srows, ctx.agg_scores)}.get
    for row in rows:
        execute(prog, {**bindings, "doc": _ScalarDoc(ctx, int(row)),
                       "_score": score_of(int(row), 0.0)
                       if score_of else 0.0})
    combine = _script_source(spec.get("combine_script"))
    if combine:
        return execute(compile_painless(combine), dict(bindings))
    return state


def scripted_metric_reduce(spec: dict, states: list):
    """Coordinator reduce over every shard's combined state. Without a
    reduce_script the reference returns the raw states list."""
    from elasticsearch_tpu.script.painless import (
        FrozenParams, compile_painless, execute,
    )

    reduce_src = _script_source(spec.get("reduce_script"))
    if not reduce_src:
        return list(states)
    return execute(compile_painless(reduce_src),
                   {"states": list(states),
                    "params": FrozenParams(spec.get("params") or {})})


def compute_string_stats(ctx: SearchContext, rows: np.ndarray,
                         spec: dict) -> dict:
    """reference: x-pack/plugin/analytics StringStatsAggregator."""
    values = [str(v) for _, v in all_values(ctx, rows, spec.get("field"))]
    if not values:
        return {"count": 0, "min_length": None, "max_length": None,
                "avg_length": None, "entropy": 0.0}
    lengths = [len(v) for v in values]
    freq: Dict[str, int] = {}
    total_chars = 0
    for v in values:
        for ch in v:
            freq[ch] = freq.get(ch, 0) + 1
            total_chars += 1
    entropy = 0.0
    for c in freq.values():
        p = c / total_chars
        entropy -= p * math.log2(p)
    out = {"count": len(values), "min_length": min(lengths),
           "max_length": max(lengths),
           "avg_length": sum(lengths) / len(lengths),
           "entropy": round(entropy, 10)}
    if spec.get("show_distribution"):
        out["distribution"] = {ch: c / total_chars
                               for ch, c in sorted(freq.items())}
    return out


def compute_top_metrics(ctx: SearchContext, rows: np.ndarray,
                        spec: dict) -> dict:
    """reference: x-pack/plugin/analytics TopMetricsAggregator — the metric
    values of the top-N docs by a sort key."""
    metrics = spec.get("metrics", [])
    if isinstance(metrics, dict):
        metrics = [metrics]
    sort_spec = spec.get("sort", [{"_doc": "asc"}])
    if isinstance(sort_spec, (str, dict)):
        sort_spec = [sort_spec]
    size = int(spec.get("size", 1))
    entry = sort_spec[0]
    if isinstance(entry, str):
        sort_field, order = entry, "asc"
    else:
        sort_field, order = next(iter(entry.items()))
        if isinstance(order, dict):
            order = order.get("order", "asc")
    if sort_field == "_doc":
        keys = rows.astype(np.float64)
        kp = np.ones(len(rows), dtype=bool)
    else:
        keys, kp = numeric_values(ctx, rows, sort_field)
    idx = np.nonzero(kp)[0]
    idx = idx[np.argsort(keys[idx], kind="stable")]
    if order == "desc":
        idx = idx[::-1]
    top = []
    for i in idx[:size]:
        row = int(rows[i])
        mvals = {}
        for m in metrics:
            mf = m.get("field")
            v = ctx.reader.get_doc_value(ctx.mapper_service.resolve_field(mf),
                                         row)
            if isinstance(v, list):
                v = v[0] if v else None
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                mvals[mf] = float(v)
            else:
                mvals[mf] = v
        top.append({"sort": [float(keys[i])], "metrics": mvals})
    return {"top": top}


def compute_matrix_stats(ctx: SearchContext, rows: np.ndarray,
                         spec: dict) -> dict:
    """reference: modules/aggs-matrix-stats MatrixStatsAggregator —
    per-field moments + pairwise covariance/correlation."""
    fields = spec.get("fields", [])
    cols = {}
    presents = {}
    for f in fields:
        cols[f], presents[f] = numeric_values(ctx, rows, f)
    # rows where every field is present (reference: listwise deletion)
    if fields:
        mask = np.logical_and.reduce([presents[f] for f in fields])
    else:
        mask = np.zeros(0, dtype=bool)
    n = int(mask.sum())
    if n == 0:
        return {"doc_count": 0, "fields": []}
    # one pass of per-field moments, then symmetric pairwise products
    stats = {}
    for f in fields:
        v = cols[f][mask]
        mean = float(v.mean())
        centered = v - mean
        var = float((centered ** 2).sum() / (n - 1)) if n > 1 else 0.0
        stats[f] = (mean, centered, var, math.sqrt(var))
    cov: Dict[str, Dict[str, float]] = {f: {} for f in fields}
    for i, f in enumerate(fields):
        for g in fields[i:]:
            c = float((stats[f][1] * stats[g][1]).sum() / (n - 1)) \
                if n > 1 else 0.0
            cov[f][g] = cov[g][f] = c
    out_fields = []
    for f in fields:
        mean, centered, var, sd = stats[f]
        skew = float(((centered / sd) ** 3).mean()) if sd else 0.0
        kurt = float(((centered / sd) ** 4).mean()) if sd else 0.0
        corr = {}
        for g in fields:
            sd_g = stats[g][3]
            corr[g] = (cov[f][g] / (sd * sd_g)) if sd and sd_g else (
                1.0 if f == g else 0.0)
        out_fields.append({"name": f, "count": n, "mean": mean,
                           "variance": var, "skewness": skew,
                           "kurtosis": kurt, "covariance": cov[f],
                           "correlation": corr})
    return {"doc_count": n, "fields": out_fields}


def _mix64(k: int) -> int:
    """hppc BitMixer.mix64 (David Stafford mix13 variant) — the
    reference's PartitionedLongFilter hash; returns a SIGNED 64-bit value
    so that Python's % matches Java's Math.floorMod."""
    m = 0xFFFFFFFFFFFFFFFF
    k &= m
    k = ((k ^ (k >> 32)) * 0x4CD6944C5CC20B6D) & m
    k = ((k ^ (k >> 29)) * 0xFC12C5B19D3259E9) & m
    k = k ^ (k >> 32)
    return k - (1 << 64) if k >= (1 << 63) else k


def _murmur3_x86_32(data: bytes, seed: int) -> int:
    """Lucene StringHelper.murmurhash3_x86_32 (signed int32 result) — the
    reference's PartitionedStringFilter hash (IncludeExclude seed 31)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & 0xFFFFFFFF
    rounded = len(data) & ~3
    for i in range(0, rounded, 4):
        k1 = (data[i] | (data[i + 1] << 8) | (data[i + 2] << 16)
              | (data[i + 3] << 24))
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
        h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
        h1 = (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF
    k1 = 0
    tail = len(data) & 3
    if tail == 3:
        k1 ^= data[rounded + 2] << 16
    if tail >= 2:
        k1 ^= data[rounded + 1] << 8
    if tail >= 1:
        k1 ^= data[rounded]
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
    h1 ^= len(data)
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


def _hashable(v):
    return tuple(v) if isinstance(v, (list, tuple)) else v


# ---------------------------------------------------------------------------
# bucket aggregations
# ---------------------------------------------------------------------------

BUCKET_AGGS = {"terms", "histogram", "date_histogram", "range", "date_range",
               "filters", "filter", "missing", "global", "composite",
               "significant_terms", "significant_text", "rare_terms",
               "sampler", "ip_range",
               "auto_date_histogram", "adjacency_matrix", "geohash_grid",
               "geotile_grid"}
METRIC_AGGS = {"avg", "sum", "min", "max", "stats", "extended_stats", "value_count",
               "cardinality", "percentiles", "percentile_ranks", "top_hits",
               "weighted_avg", "median_absolute_deviation", "geo_bounds",
               "geo_centroid", "boxplot", "string_stats", "top_metrics",
               "matrix_stats", "scripted_metric"}
PIPELINE_AGGS = {"avg_bucket", "max_bucket", "min_bucket", "sum_bucket",
                 "stats_bucket", "extended_stats_bucket", "percentiles_bucket",
                 "derivative", "cumulative_sum", "bucket_script",
                 "bucket_selector", "bucket_sort", "serial_diff", "moving_fn"}


def _parse_float_param(spec: dict, key: str, default: float,
                       agg_name: str) -> float:
    raw = spec.get(key, default)
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise ParsingError(
            f"x_content_parse_exception: [{key}] failed to parse value "
            f"[{raw}] in [{agg_name}]")


def _parse_int_param(spec: dict, key: str, default: int,
                     agg_name: str) -> int:
    raw = spec.get(key, default)
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise ParsingError(
            f"x_content_parse_exception: [{key}] failed to parse value "
            f"[{raw}] in [{agg_name}]")


def validate_aggs(aggs_spec: dict, field_type=None) -> None:
    """Builder-time parameter validation, applied before any shard work
    (reference: each AggregationBuilder validates in its constructor /
    parse, so errors surface even for zero-shard searches).
    `field_type(field) -> type_name or None` enables mapper-aware checks."""
    for name, spec in (aggs_spec or {}).items():
        if not isinstance(spec, dict):
            raise ParsingError(f"aggregation [{name}] must be an object")
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        for kind, body in spec.items():
            if kind in ("aggs", "aggregations", "meta") \
                    or not isinstance(body, dict):
                continue
            if kind == "extended_stats":
                sigma = _parse_float_param(body, "sigma", 2.0, name)
                if sigma < 0:
                    raise IllegalArgumentError(
                        f"[sigma] must be greater than or equal to 0. "
                        f"Found [{sigma}] in [{name}]")
            if kind == "cardinality" and "precision_threshold" in body:
                pt = _parse_int_param(body, "precision_threshold", 0, name)
                if pt < 0:
                    raise IllegalArgumentError(
                        f"[precisionThreshold] must be greater than or "
                        f"equal to 0. Found [{pt}] in [{name}]")
            if kind == "percentiles":
                td = body.get("tdigest")
                if isinstance(td, dict) and "compression" in td:
                    comp = _parse_float_param(td, "compression", 100.0, name)
                    if comp < 0:
                        raise IllegalArgumentError(
                            f"[compression] must be greater than or equal "
                            f"to 0. Found [{comp}] in [{name}]")
                if "percents" in body:
                    pc = body["percents"]
                    if not isinstance(pc, list) or not pc:
                        raise IllegalArgumentError(
                            "[percents] must not be empty")
                    for p in pc:
                        try:
                            fp = float(p)
                        except (TypeError, ValueError):
                            raise ParsingError(
                                f"x_content_parse_exception: [percents] "
                                f"failed to parse [{p}]")
                        if not 0.0 <= fp <= 100.0:
                            raise IllegalArgumentError(
                                f"percent must be in [0,100], got [{fp}]")
                hdr = body.get("hdr")
                if isinstance(hdr, dict):
                    raw = hdr.get("number_of_significant_value_digits", 3)
                    try:
                        digits = int(raw)
                    except (TypeError, ValueError):
                        raise IllegalArgumentError(
                            "[numberOfSignificantValueDigits] must be "
                            "between 0 and 5")
                    if not 0 <= digits <= 5:
                        raise IllegalArgumentError(
                            "[numberOfSignificantValueDigits] must be "
                            "between 0 and 5")
            if kind == "median_absolute_deviation" \
                    and "compression" in body:
                comp = _parse_float_param(body, "compression", 1000.0, name)
                if comp <= 0:
                    raise IllegalArgumentError(
                        f"[compression] must be greater than 0. "
                        f"Found [{comp}] in [{name}]")
            if kind == "moving_fn":
                window = _parse_int_param(body, "window", 5, name) \
                    if body.get("window") is not None else 5
                if window <= 0:
                    raise IllegalArgumentError(
                        "[window] must be a positive, non-zero integer.")
            if kind == "filters" and not body.get("filters"):
                raise IllegalArgumentError("[filters] cannot be empty")
            if kind in ("significant_terms", "significant_text"):
                import difflib
                for k in body:
                    if k not in _SIG_KNOWN_FIELDS:
                        close = difflib.get_close_matches(
                            k, _SIG_KNOWN_FIELDS, n=1)
                        hint = f" did you mean [{close[0]}]?" if close else ""
                        raise ParsingError(
                            f"[{kind}] unknown field [{k}]{hint}")
            if kind in ("terms", "significant_terms", "significant_text",
                        "rare_terms"):
                inc, exc = body.get("include"), body.get("exclude")
                field = body.get("field", "")
                # regex include/exclude only applies to string fields; the
                # non-string check here mirrors ValuesSourceType guards for
                # the obvious field-name cases (ip/date/numeric suites)
                if isinstance(inc, str) or isinstance(exc, str):
                    tname = field_type(field) if field_type else None
                    if tname is not None and tname not in (
                            "keyword", "text", "wildcard",
                            "constant_keyword"):
                        raise IllegalArgumentError(
                            f"Aggregation [{name}] cannot support regular "
                            f"expression style include/exclude settings as "
                            f"they can only be applied to string fields. "
                            f"Use an array of values for include/exclude "
                            f"clauses")
        if sub:
            validate_aggs(sub, field_type)


def compute_aggs(ctx: SearchContext, rows: np.ndarray, aggs_spec: dict) -> dict:
    """Compute an aggregation tree over candidate rows."""
    out: Dict[str, Any] = {}
    pipelines: List[Tuple[str, str, dict]] = []
    for name, spec in (aggs_spec or {}).items():
        if not isinstance(spec, dict):
            raise ParsingError(f"aggregation [{name}] must be an object")
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise ParsingError(f"aggregation [{name}] must define exactly one type")
        kind = kinds[0]
        if kind in PIPELINE_AGGS:
            pipelines.append((name, kind, spec[kind]))
            continue
        if kind in METRIC_AGGS:
            out[name] = compute_metric(ctx, rows, kind, spec[kind], name=name)
        elif kind in BUCKET_AGGS or kind in ("nested", "reverse_nested"):
            # parent pipelines (cumulative_sum/derivative/... declared as
            # sub-aggs) run over the parent's bucket list after it's built
            sub_normal, sub_pipes = {}, []
            for sname, sspec in sub.items():
                skinds = [k for k in sspec if k not in ("aggs", "aggregations", "meta")]
                if len(skinds) == 1 and skinds[0] in PIPELINE_AGGS:
                    sub_pipes.append((sname, skinds[0], sspec[skinds[0]]))
                else:
                    sub_normal[sname] = sspec
            out[name] = _compute_bucket(ctx, rows, kind, spec[kind], sub_normal)
            for pname, pkind, pspec in sub_pipes:
                wrapper = {"__parent__": out[name]}
                pspec2 = dict(pspec)
                bp = pspec2.get("buckets_path")
                if isinstance(bp, str):
                    pspec2["buckets_path"] = "__parent__>" + bp
                elif isinstance(bp, dict):
                    pspec2["buckets_path"] = {k: "__parent__>" + v for k, v in bp.items()}
                res = _compute_pipeline(wrapper, pkind, pspec2, pname)
                if not (isinstance(res, dict) and "_applied" in res):
                    out[name].setdefault("__pipeline_results__", {})[pname] = res
        else:
            raise ParsingError(f"unknown aggregation type [{kind}]")
        if isinstance(spec.get("meta"), dict) and isinstance(out.get(name), dict):
            out[name]["meta"] = spec["meta"]
    for name, kind, spec in pipelines:
        res = _compute_pipeline(out, kind, spec, name)
        # in-place pipelines (derivative, cumulative_sum, bucket_script/
        # selector/sort) mutate parent buckets and emit no sibling output
        if not (isinstance(res, dict) and "_applied" in res):
            out[name] = res
    return out


def _bucketize(ctx, rows, sub_aggs, buckets: List[Tuple[Any, np.ndarray]],
               key_name: str = "key", recurse=None) -> List[dict]:
    recurse = recurse or compute_aggs
    out = []
    for key, brows in buckets:
        b = {key_name: key, "doc_count": int(len(brows))}
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        out.append(b)
    return out


def _geohash_encode(lat: float, lon: float, precision: int) -> str:
    """Classic base-32 geohash (reference: Lucene Geohash/`geogrid` aggs)."""
    base32 = "0123456789bcdefghjkmnpqrstuvwxyz"
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    bits = []
    even = True
    while len(bits) < precision * 5:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                bits.append(1)
                lon_lo = mid
            else:
                bits.append(0)
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                bits.append(1)
                lat_lo = mid
            else:
                bits.append(0)
                lat_hi = mid
        even = not even
    out = []
    for i in range(0, len(bits), 5):
        out.append(base32[int("".join(map(str, bits[i:i + 5])), 2)])
    return "".join(out)


def _geotile_encode(lat: float, lon: float, precision: int) -> str:
    """z/x/y map-tile key (reference: GeoTileUtils.longEncode)."""
    import math as _m
    n = 2 ** precision
    x = int((lon + 180.0) / 360.0 * n)
    lat_r = _m.radians(max(min(lat, 85.05112878), -85.05112878))
    y = int((1.0 - _m.log(_m.tan(lat_r) + 1 / _m.cos(lat_r)) / _m.pi) / 2.0 * n)
    return f"{precision}/{min(max(x, 0), n - 1)}/{min(max(y, 0), n - 1)}"


def _gather_geo_points(ctx: SearchContext, rows: np.ndarray, field: str):
    pts = []
    for row in rows:
        v = ctx.reader.get_doc_value(field, int(row))
        if v is None:
            continue
        if isinstance(v, list) and v and isinstance(v[0], (list, tuple)):
            for p in v:
                pts.append((int(row), float(p[0]), float(p[1])))
        elif isinstance(v, (list, tuple)) and len(v) == 2:
            pts.append((int(row), float(v[0]), float(v[1])))
    return pts


def _compute_bucket(ctx: SearchContext, rows: np.ndarray, kind: str,
                    spec: dict, sub_aggs: dict, recurse=None) -> dict:
    """One bucket agg. `recurse` computes sub-agg trees over bucket rows —
    `compute_aggs` for final output, or the partial-mode walker
    (`agg_partials.compute_partial_aggs`) for the distributed reduce."""
    recurse = recurse or compute_aggs
    field = spec.get("field")

    # composite may only nest under `nested` (CompositeAggregationBuilder
    # rejects every other parent)
    if kind != "nested":
        for sname, sspec in (sub_aggs or {}).items():
            if isinstance(sspec, dict) and "composite" in sspec:
                raise IllegalArgumentError(
                    f"[composite] aggregation cannot be used with a parent "
                    f"aggregation of type: [{kind}]")

    if kind in ("geohash_grid", "geotile_grid"):
        default_prec = 5 if kind == "geohash_grid" else 7
        precision = int(spec.get("precision", default_prec))
        encode = _geohash_encode if kind == "geohash_grid" else _geotile_encode
        cells: Dict[str, List[int]] = {}
        for row, lat, lon in _gather_geo_points(ctx, rows, field):
            cells.setdefault(encode(lat, lon, precision), []).append(row)
        size = int(spec.get("size", 10000))
        buckets = []
        for key in sorted(cells, key=lambda k: (-len(cells[k]), k))[:size]:
            brows = np.asarray(sorted(set(cells[key])), dtype=np.int64)
            b = {"key": key, "doc_count": int(len(brows))}
            if sub_aggs:
                b.update(recurse(ctx, brows, sub_aggs))
            buckets.append(b)
        return {"buckets": buckets}

    if kind == "filter" or (kind == "filters" and False):
        q = parse_query(spec) if kind == "filter" else None
        match = q.execute(ctx).rows
        brows = rows[np.isin(rows, match)]
        b = {"doc_count": int(len(brows))}
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        return b

    if kind == "filters":
        filters = spec.get("filters", {})
        if not filters:
            raise IllegalArgumentError("[filters] cannot be empty")
        named = isinstance(filters, dict)
        items = filters.items() if named else enumerate(filters)
        buckets = {} if named else []
        for key, qspec in items:
            match = parse_query(qspec).execute(ctx).rows
            brows = rows[np.isin(rows, match)]
            b = {"doc_count": int(len(brows))}
            if sub_aggs:
                b.update(recurse(ctx, brows, sub_aggs))
            if named:
                buckets[key] = b
            else:
                buckets.append(b)
        return {"buckets": buckets}

    if kind == "global":
        grows = ctx.all_rows()
        b = {"doc_count": int(len(grows))}
        if sub_aggs:
            b.update(recurse(ctx, grows, sub_aggs))
        return b

    if kind == "missing":
        if spec.get("missing") is not None:
            # a missing-value substitute means no doc is ever missing
            brows = rows[:0]
        else:
            vals = [ctx.reader.get_doc_value(field, int(r)) for r in rows]
            brows = rows[[v is None for v in vals]]
        b = {"doc_count": int(len(brows))}
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        return b

    if kind in ("significant_terms", "significant_text"):
        return _compute_significant(ctx, rows, kind, spec, sub_aggs,
                                    recurse)

    if kind in ("terms", "rare_terms"):
        size = int(spec.get("size", 10))
        tname = getattr(ctx.mapper_service.get(field), "type_name", None) \
            if field else None
        # an unmapped field aggregates under the caller-declared value_type
        # (ValuesSourceConfig.resolve with a user value type)
        tname = tname or spec.get("value_type")

        def fmt_key(k):
            if tname == "ip":
                from elasticsearch_tpu.index.mapping import IpFieldMapper
                try:
                    return IpFieldMapper.format_value(int(k))
                except (ValueError, TypeError):
                    return k
            return k

        values = all_values(ctx, rows, field)
        missing_val = spec.get("missing")
        if missing_val is not None:
            # docs without the field bucket under the missing key, coerced
            # per the effective type (terms `missing` param)
            mv = missing_val
            if tname in ("date", "date_nanos") and isinstance(mv, str):
                try:
                    mv = parse_date_millis(mv)
                except Exception:
                    pass
            elif tname in ("long", "integer", "short", "byte"):
                try:
                    mv = int(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a long")
            elif tname in ("double", "float", "half_float"):
                try:
                    mv = float(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a double")
            have = {i for i, _ in values}
            values = values + [(i, mv) for i in range(len(rows))
                               if i not in have]
        groups: Dict[Any, List[int]] = {}
        for idx, v in values:
            groups.setdefault(_hashable(v), []).append(idx)
        mapper_t = ctx.mapper_service.get(field) if field else None
        _tn = getattr(mapper_t, "type_name", None)
        if (_tn == "keyword" or (_tn == "text"
                                 and (mapper_t.params or {})
                                 .get("fielddata"))) \
                and spec.get("execution_hint") != "map":
            # loading global ordinals materializes fielddata (the map hint
            # iterates values without building it)
            ctx.mapper_service.mark_fielddata_loaded(field)
        # include/exclude term filtering (IncludeExclude): exact-value lists,
        # a regex, or a {partition, num_partitions} hash partition
        inc, exc = spec.get("include"), spec.get("exclude")
        if isinstance(inc, dict):
            if exc is not None:
                raise IllegalArgumentError(
                    "Cannot specify any excludes when using a "
                    "partition-based include")
            part = int(inc.get("partition", 0))
            n_part = int(inc.get("num_partitions", 1))

            def _in_partition(k):
                if isinstance(k, bool):
                    h = _mix64(1 if k else 0)
                elif isinstance(k, (int, float)) and not isinstance(k, bool):
                    h = _mix64(int(k))
                else:
                    h = _murmur3_x86_32(str(k).encode("utf-8"), 31)
                return h % n_part == part  # Math.floorMod semantics
            groups = {k: i for k, i in groups.items() if _in_partition(k)}
            inc = None
        if inc is not None or exc is not None:
            def _coerce_list(entries):
                # list entries compare in the field's keyspace: date
                # strings parse to millis (DocValueFormat round-trip)
                out = set()
                for x in entries:
                    if tname in ("date", "date_nanos"):
                        try:
                            out.add(str(parse_date_millis(x)))
                            continue
                        except Exception:
                            pass
                    out.add(str(x))
                return out
            inc_set = _coerce_list(inc) if isinstance(inc, list) else None
            exc_set = _coerce_list(exc) if isinstance(exc, list) else None

            def _passes(k):
                ks = str(fmt_key(k))
                if isinstance(k, float) and k == int(k):
                    ks = str(int(k))
                if inc_set is not None and ks not in inc_set:
                    return False
                if isinstance(inc, str) and not re.fullmatch(inc, ks):
                    return False
                if exc_set is not None and ks in exc_set:
                    return False
                if isinstance(exc, str) and re.fullmatch(exc, ks):
                    return False
                return True
            groups = {k: i for k, i in groups.items() if _passes(k)}
        # min_doc_count: 0 surfaces zero-count terms from the whole index
        # (TermsAggregator#buildEmptyAggregation path)
        if kind == "terms" and int(spec.get("min_doc_count", 1)) == 0:
            if field == "_index":
                universe = {getattr(ctx, "index_name", "index")}
            else:
                universe = {_hashable(v2) for _i2, v2 in
                            all_values(ctx, ctx.all_rows(), field)}
            for t in universe:
                groups.setdefault(t, [])
        # under a nested scope each VALUE OCCURRENCE is one nested doc:
        # bucket doc_count counts nested docs (NestedAggregator semantics,
        # consistent with the enclosing nested agg's doc_count) while
        # sub-aggs still aggregate over the unique parent rows the
        # flattened store addresses — which is exactly what makes a
        # reverse_nested sub-agg meaningful (nested-doc count above,
        # parent-doc count inside)
        nested_scope = getattr(ctx, "nested_path", None)
        occ = None
        if nested_scope and isinstance(field, str) \
                and field.startswith(nested_scope + "."):
            occ = {k: len(i_list) for k, i_list in groups.items()}
        # sort: doc_count desc then key asc (reference terms agg default)
        order_spec = spec.get("order")
        items = [(k, np.asarray(sorted(set(i_list)), dtype=np.int64))
                 for k, i_list in groups.items()]
        cnt = (lambda k, i: occ[k]) if occ is not None \
            else (lambda k, i: len(i))
        if kind == "rare_terms":
            max_count = int(spec.get("max_doc_count", 1))
            items = [(k, i) for k, i in items if cnt(k, i) <= max_count]
            items.sort(key=lambda kv: (cnt(*kv), _sort_key(kv[0])))
        elif order_spec and isinstance(order_spec, dict):
            ((okey, odir),) = order_spec.items()
            reverse = odir == "desc"
            if okey == "_key":
                items.sort(key=lambda kv: _sort_key(kv[0]), reverse=reverse)
            elif okey == "_count":
                items.sort(key=lambda kv: (cnt(*kv),), reverse=reverse)
            else:
                def metric_val(kv):
                    sub_out = recurse(ctx, rows[kv[1]], sub_aggs)
                    node = sub_out
                    for part in okey.split("."):
                        node = node[part] if isinstance(node, dict) else None
                    return node if isinstance(node, (int, float)) else (node or {}).get("value", 0)
                items.sort(key=metric_val, reverse=reverse)
        else:
            items.sort(key=lambda kv: (-cnt(*kv), _sort_key(kv[0])))
        total_other = sum(cnt(k, i) for k, i in items[size:])
        _check_max_buckets(ctx, min(len(items), size))
        buckets = _bucketize(ctx, rows, sub_aggs,
                             [(k, rows[i]) for k, i in items[:size]],
                             recurse=recurse)
        if occ is not None:
            for b, (k, _i) in zip(buckets, items[:size]):
                b["doc_count"] = int(occ[k])
        # mapper-typed key rendering (DocValueFormat): ip ints back to
        # addresses, booleans to 1/0 + key_as_string, dates to ISO strings
        # (fmt_key is the same transform include/exclude matched against)
        if tname == "ip":
            for b in buckets:
                b["key"] = fmt_key(b["key"])
        elif tname == "boolean":
            for b in buckets:
                truthy = bool(b["key"])
                b["key"] = 1 if truthy else 0
                b["key_as_string"] = "true" if truthy else "false"
        elif tname == "date":
            for b in buckets:
                if isinstance(b["key"], (int, float)):
                    b["key_as_string"] = _millis_to_iso(int(b["key"]))
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(total_other), "buckets": buckets}

    if kind == "histogram":
        interval = float(spec["interval"])
        offset = float(spec.get("offset", 0.0))
        min_count = int(spec.get("min_doc_count", 0))
        vals, present = numeric_values(ctx, rows, field, spec.get("missing"))
        keys = np.floor((vals - offset) / interval) * interval + offset
        out = _histo_buckets(ctx, rows, sub_aggs, keys, present, min_count,
                             spec.get("extended_bounds"), interval,
                             recurse=recurse)
        fmt = spec.get("format")
        if fmt:
            for b in out["buckets"]:
                b["key_as_string"] = _decimal_format(b["key"], fmt)
        return out

    if kind == "date_histogram":
        interval_ms, calendar = _date_interval(spec)
        min_count = int(spec.get("min_doc_count", 0))
        mapper = ctx.mapper_service.get(field)
        from elasticsearch_tpu.index.mapping import RangeFieldMapperBase
        if isinstance(mapper, RangeFieldMapperBase):
            return _range_field_histo(ctx, rows, sub_aggs, spec, field,
                                      recurse=recurse)
        vals, present = numeric_values(ctx, rows, field)
        if getattr(mapper, "type_name", None) == "date_nanos":
            vals = vals / 1e6  # stored nanos; histogram buckets in millis
        offset_ms = _date_offset_ms(spec.get("offset"))
        tz = _resolve_tz(spec.get("time_zone"))
        if calendar:
            keys = np.asarray(
                [_calendar_floor(int(v - offset_ms), calendar, tz) + offset_ms
                 if p else np.nan
                 for v, p in zip(vals, present)], dtype=np.float64)
        else:
            keys = np.floor((vals - offset_ms) / interval_ms) * interval_ms \
                + offset_ms
        return _histo_buckets(ctx, rows, sub_aggs, keys, present, min_count,
                              None, interval_ms, date=True, recurse=recurse,
                              fmt=spec.get("format"), tz=tz)

    if kind == "auto_date_histogram":
        target = int(spec.get("buckets", 10))
        vals, present = numeric_values(ctx, rows, field)
        v = vals[present]
        if len(v) == 0:
            return {"buckets": [], "interval": "1ms"}
        span = max(v.max() - v.min(), 1.0)
        interval_ms = max(span / target, 1.0)
        # snap to a sane unit
        for unit in (1, 1000, 60_000, 3_600_000, 86_400_000, 2_592_000_000, 31_536_000_000):
            if interval_ms <= unit:
                interval_ms = unit
                break
        keys = np.floor(vals / interval_ms) * interval_ms
        out = _histo_buckets(ctx, rows, sub_aggs, keys, present, 0, None,
                             interval_ms, date=True, recurse=recurse)
        out["interval"] = f"{int(interval_ms)}ms"
        return out

    if kind in ("range", "date_range", "ip_range"):
        ranges = spec.get("ranges", [])
        vals, present = numeric_values(ctx, rows, field, spec.get("missing"))
        mapper = ctx.mapper_service.get(field) if field else None
        date_fmt = (mapper.params.get("format", "")
                    if mapper is not None else "")
        if kind == "date_range":
            def conv(x):
                if x is None:
                    return None
                if "epoch_second" in str(date_fmt):
                    # bounds parse with the field's format: numbers (and
                    # numeric strings) are seconds
                    try:
                        return float(x) * 1000.0
                    except (TypeError, ValueError):
                        pass
                return float(parse_date_millis(x))
        elif kind == "ip_range":
            def conv(x):
                from elasticsearch_tpu.index.mapping import IpFieldMapper
                return float(IpFieldMapper.parse_ip(x)) if x is not None else None
        else:
            def conv(x):
                return float(x) if x is not None else None

        def render_bound(x, numeric):
            # key/from/to rendering per value source (RangeAggregator's
            # DocValueFormat): doubles as "50.0", ips as addresses, dates
            # keep the caller's raw input in the key
            if kind == "ip_range":
                from elasticsearch_tpu.index.mapping import IpFieldMapper
                return IpFieldMapper.format_value(int(numeric))
            if kind == "date_range":
                return numeric
            return float(numeric)

        buckets = []
        for r in ranges:
            cidr = r.get("mask")
            if cidr is not None and kind == "ip_range":
                import ipaddress
                net = ipaddress.ip_network(cidr, strict=False)
                lo = net.network_address
                if lo.version == 4:
                    lo = ipaddress.IPv6Address("::ffff:" + str(lo))
                frm = float(int(lo))
                to = frm + float(net.num_addresses)
            else:
                frm = conv(r.get("from"))
                to = conv(r.get("to"))
            mask = present.copy()
            if frm is not None:
                mask &= vals >= frm
            if to is not None:
                mask &= vals < to
            brows = rows[mask]
            key = r.get("key")
            if key is None and cidr is not None:
                key = cidr
            if key is None:
                lo_s = "*" if frm is None else \
                    (str(r.get("from")) if kind == "date_range"
                     else render_bound(r.get("from"), frm))
                hi_s = "*" if to is None else \
                    (str(r.get("to")) if kind == "date_range"
                     else render_bound(r.get("to"), to))
                key = f"{lo_s}-{hi_s}"
            b = {"key": key, "doc_count": int(len(brows))}
            if frm is not None:
                b["from"] = render_bound(r.get("from"), frm)
            if to is not None:
                b["to"] = render_bound(r.get("to"), to)
            if sub_aggs:
                b.update(recurse(ctx, brows, sub_aggs))
            b["_sort"] = (frm if frm is not None else -np.inf,
                          to if to is not None else np.inf)
            buckets.append(b)
        # RangeAggregator emits buckets ordered by (from, to), not in the
        # order the caller listed them
        buckets.sort(key=lambda b: b.pop("_sort"))
        return {"buckets": buckets}

    if kind == "sampler":
        shard_size = int(spec.get("shard_size", 100))
        brows = rows[:shard_size]
        b = {"doc_count": int(len(brows))}
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        return b

    if kind == "composite":
        import itertools as _it
        sources = spec.get("sources", [])
        if not sources:
            raise IllegalArgumentError(
                "Required [sources]: Composite [sources] cannot be null "
                "or empty")
        size = int(spec.get("size", 10))
        max_b = getattr(ctx, "max_buckets", None) or 65536
        if size > max_b:
            from elasticsearch_tpu.common.errors import TooManyBucketsError
            raise TooManyBucketsError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{max_b}] but was [{size}]. This limit can be "
                f"set by changing the [search.max_buckets] cluster level "
                f"setting.")
        after = spec.get("after")
        names = []
        formats = []
        source_tzs: Dict[int, Any] = {}
        per_source_vals: List[Dict[int, list]] = []
        for src in sources:
            ((sname, sdef),) = src.items()
            if sname in names:
                raise IllegalArgumentError(
                    f"Composite source names must be unique, found "
                    f"duplicates: [{sname}]")
            names.append(sname)
            ((stype, sspec),) = sdef.items()
            # a multi-valued doc contributes ONE composite key per value
            # (CompositeValuesSourceBuilder cartesian semantics)
            col: Dict[int, list] = {}
            fmt = None
            if stype == "terms":
                is_ip = getattr(ctx.mapper_service.get(sspec["field"]),
                                "type_name", None) == "ip"
                for idx, v in all_values(ctx, rows, sspec["field"]):
                    if is_ip and isinstance(v, (int, float)):
                        from elasticsearch_tpu.index.mapping import (
                            IpFieldMapper)
                        v = IpFieldMapper.format_value(int(v))
                    col.setdefault(idx, []).append(v)
            elif stype == "histogram":
                vals, present = numeric_values(ctx, rows, sspec["field"])
                interval = float(sspec["interval"])
                for idx in np.nonzero(present)[0]:
                    col[int(idx)] = [float(np.floor(vals[idx] / interval)
                                           * interval)]
            elif stype == "date_histogram":
                vals, present = numeric_values(ctx, rows, sspec["field"])
                if getattr(ctx.mapper_service.get(sspec["field"]),
                           "type_name", None) == "date_nanos":
                    vals = vals / 1e6
                ims, cal = _date_interval(sspec)
                off = _date_offset_ms(sspec.get("offset"))
                fmt = sspec.get("format")
                tz = _resolve_tz(sspec.get("time_zone"))
                if tz is not None:
                    source_tzs[len(names) - 1] = tz
                for idx in np.nonzero(present)[0]:
                    v = int(vals[idx])
                    key = (_calendar_floor(v - off, cal, tz) + off if cal
                           else float(np.floor((v - off) / ims) * ims + off))
                    col[int(idx)] = [key]
            elif stype == "geotile_grid":
                precision = int(sspec.get("precision", 7))
                row_pos = {int(r): i for i, r in enumerate(rows)}
                for row, lat, lon in _gather_geo_points(
                        ctx, rows, sspec["field"]):
                    i = row_pos.get(int(row))
                    if i is not None:
                        col.setdefault(i, []).append(
                            _geotile_encode(lat, lon, precision))
            else:
                raise IllegalArgumentError(
                    f"unknown composite source type [{stype}]")
            if sspec.get("missing_bucket"):
                for i in range(len(rows)):
                    col.setdefault(i, [None])
            per_source_vals.append(col)
            formats.append(fmt)
        source_types = [next(iter(next(iter(s.values())))) for s in sources]

        def src_sort_key(value, pos):
            # geotile "z/x/y" orders by tile coordinates, not string order
            if source_types[pos] == "geotile_grid" and isinstance(value, str):
                try:
                    return (0,) + tuple(int(p) for p in value.split("/"))
                except ValueError:
                    pass
            return _sort_key(value)

        keyed: Dict[tuple, List[int]] = {}
        for i in range(len(rows)):
            value_lists = [col.get(i) for col in per_source_vals]
            if any(not vl for vl in value_lists):
                continue
            for key in _it.product(*value_lists):
                keyed.setdefault(key, []).append(i)
        items = sorted(keyed.items(),
                       key=lambda kv: tuple(src_sort_key(k, p)
                                            for p, k in enumerate(kv[0])))
        if after is not None:
            after_vals = []
            for p, n in enumerate(names):
                v = after.get(n)
                if formats[p] and isinstance(v, str):
                    # a formatted after_key round-trips: parse it back into
                    # the internal millis domain before comparing; bare
                    # local datetimes read in the source's time_zone
                    try:
                        raw = v
                        v = float(parse_date_millis(v))
                        tz = source_tzs.get(p)
                        has_offset = raw.endswith("Z") or bool(
                            __import__("re").search(
                                r"[+-]\d\d:?\d\d$", raw))
                        if tz is not None and not has_offset:
                            import datetime as _dt
                            # offset AT the parsed instant (DST-correct)
                            at = _dt.datetime.fromtimestamp(
                                v / 1000.0, _dt.timezone.utc)
                            off = tz.utcoffset(at)
                            v -= off.total_seconds() * 1000.0
                    except Exception:
                        pass
                after_vals.append(v)
            after_rank = tuple(src_sort_key(v, p)
                               for p, v in enumerate(after_vals))
            items = [it for it in items
                     if tuple(src_sort_key(k, p)
                              for p, k in enumerate(it[0])) > after_rank]
        items = items[:size]

        def render(key):
            out_key = {}
            for p, (n, k, fmt) in enumerate(zip(names, key, formats)):
                if fmt and isinstance(k, (int, float)):
                    out_key[n] = _format_date_key(int(k), fmt,
                                                  tz=source_tzs.get(p))
                elif isinstance(k, float) and k.is_integer():
                    out_key[n] = int(k)
                else:
                    out_key[n] = k
            return out_key

        buckets = []
        for key, idxs in items:
            b = {"key": render(key), "doc_count": len(set(idxs))}
            if sub_aggs:
                b.update(recurse(ctx, rows[np.asarray(sorted(set(idxs)),
                                                      dtype=np.int64)],
                                 sub_aggs))
            buckets.append(b)
        out = {"buckets": buckets}
        if buckets:
            out["after_key"] = buckets[-1]["key"]
        return out

    if kind == "adjacency_matrix":
        filters = spec.get("filters", {})
        matches = {name: parse_query(q).execute(ctx).rows for name, q in filters.items()}
        names = sorted(matches)
        buckets = []
        for i, a in enumerate(names):
            ra = rows[np.isin(rows, matches[a])]
            if len(ra):
                b = {"key": a, "doc_count": int(len(ra))}
                if sub_aggs:
                    b.update(recurse(ctx, ra, sub_aggs))
                buckets.append(b)
            for bname in names[i + 1:]:
                rb = ra[np.isin(ra, matches[bname])]
                if len(rb):
                    b = {"key": f"{a}&{bname}", "doc_count": int(len(rb))}
                    if sub_aggs:
                        b.update(recurse(ctx, rb, sub_aggs))
                    buckets.append(b)
        return {"buckets": buckets}

    if kind == "nested":
        # nested docs are stored flattened; nested agg scopes to docs having
        # the path, and descendants (top_hits) may expand per nested doc.
        # doc_count counts NESTED documents, not parents (NestedAggregator
        # collects one bucket entry per child doc under each matched root)
        path = spec.get("path")
        b = {"doc_count": _count_nested_docs(ctx, rows, path)}
        if sub_aggs:
            prev = getattr(ctx, "nested_path", None)
            ctx.nested_path = path
            try:
                b.update(recurse(ctx, rows, sub_aggs))
            finally:
                ctx.nested_path = prev
        return b

    if kind == "reverse_nested":
        # ReverseNestedAggregator.java:48 — joins from the nested context
        # back to the parent docs (or an outer nested level via `path`).
        # Rows are already parent rows in the flattened design, so the
        # bucket is the parent-doc count and sub-aggs recurse with the
        # nested scope popped to the target level.
        cur = getattr(ctx, "nested_path", None)
        if cur is None:
            raise ParsingError(
                "Reverse nested aggregation must be used inside a [nested] "
                "aggregation")
        target = spec.get("path")
        if target is not None and not cur.startswith(target + "."):
            # equality is invalid too: reverse_nested must step OUT of the
            # current scope, to a strict ancestor level
            raise ParsingError(
                f"Invalid path [{target}] for reverse_nested aggregation: "
                f"not an ancestor of the current nested scope [{cur}]")
        b = {"doc_count": int(len(rows))} if target is None else \
            {"doc_count": _count_nested_docs(ctx, rows, target)}
        if sub_aggs:
            ctx.nested_path = target
            try:
                b.update(recurse(ctx, rows, sub_aggs))
            finally:
                ctx.nested_path = cur
        return b

    raise ParsingError(f"unknown bucket aggregation [{kind}]")


def _count_nested_docs(ctx, rows, path: Optional[str]) -> int:
    """Number of nested documents at `path` across `rows` (source walk —
    the flattened store keeps nested objects inside the parent doc).
    List-aware at every level, so multi-level paths like
    `comments.replies` count the leaves. Memoized per (reader gen, path)
    row count so repeated buckets in one request don't re-parse sources."""
    if not path:
        return int(len(rows))
    from elasticsearch_tpu.search.queries_ext import _values_at
    cache = getattr(ctx, "_nested_count_cache", None)
    if cache is None:
        cache = ctx._nested_count_cache = {}
    total = 0
    for row in rows:
        key = (path, int(row))
        n = cache.get(key)
        if n is None:
            src = ctx.reader.get_source(int(row)) or {}
            n = sum(1 for it in _values_at(src, path) if it is not None)
            cache[key] = n
        total += n
    return total


_SIG_KNOWN_FIELDS = ["field", "size", "shard_size", "min_doc_count",
                     "shard_min_doc_count", "background_filter", "include",
                     "exclude", "execution_hint", "jlh", "gnd", "chi_square",
                     "mutual_information", "percentage", "script_heuristic",
                     "filter_duplicate_text", "source_fields", "missing"]


def _compute_significant(ctx, rows, kind, spec, sub_aggs, recurse) -> dict:
    """significant_terms / significant_text (reference:
    SignificantTermsAggregatorFactory + SignificantTextAggregator): JLH
    scoring of foreground vs background term frequencies; significant_text
    re-analyzes _source with optional duplicate-sequence filtering
    (DeDuplicatingTokenFilter)."""
    field = spec.get("field")
    size = int(spec.get("size", 10))
    min_count = int(spec.get("min_doc_count", 3))
    mapper = ctx.mapper_service.get(field)
    analyzed = kind == "significant_text" \
        or getattr(mapper, "type_name", None) == "text"
    dedup = bool(spec.get("filter_duplicate_text"))

    def _terms_per_doc(doc_rows, use_dedup=False):
        """row -> set(terms), with cross-doc 6-gram dedup when asked."""
        seen_shingles: set = set()
        out = {}
        for row in doc_rows:
            if analyzed:
                src = ctx.reader.get_source(int(row)) or {}
                node = src
                for part in str(field).split("."):
                    node = node.get(part) if isinstance(node, dict) else None
                vals = node if isinstance(node, list) else [node]
                tokens: List[str] = []
                for v in vals:
                    if v is None:
                        continue
                    if mapper is not None and hasattr(mapper, "analyze"):
                        tokens.extend(mapper.analyze(str(v)))
                    else:
                        tokens.extend(str(v).lower().split())
                if use_dedup and len(tokens) >= 6:
                    dup = [False] * len(tokens)
                    for p in range(len(tokens) - 5):
                        if tuple(tokens[p:p + 6]) in seen_shingles:
                            for q in range(p, p + 6):
                                dup[q] = True
                    for p in range(len(tokens) - 5):
                        seen_shingles.add(tuple(tokens[p:p + 6]))
                    tokens = [t for t, d in zip(tokens, dup) if not d]
                out[int(row)] = set(tokens)
            else:
                v = ctx.reader.get_doc_value(field, int(row))
                vals = v if isinstance(v, list) else ([v] if v is not None else [])
                out[int(row)] = {_hashable(x) for x in vals}
        return out

    fg_terms = _terms_per_doc([int(r) for r in rows], use_dedup=dedup)
    fg_total = len(rows)
    fg_count: Dict[Any, int] = {}
    fg_rows_by_term: Dict[Any, List[int]] = {}
    for row, terms in fg_terms.items():
        for t in terms:
            fg_count[t] = fg_count.get(t, 0) + 1
            fg_rows_by_term.setdefault(t, []).append(row)
    # background frequencies depend only on the index, not the bucket:
    # memoize per (field, analyzed) so nesting under a terms agg doesn't
    # re-analyze the whole index once per parent bucket
    bg_cache = ctx.__dict__.setdefault("_sig_bg_cache", {})
    bg_key = (str(field), analyzed)
    if bg_key in bg_cache:
        bg_count, bg_total = bg_cache[bg_key]
    else:
        bg_rows = ctx.all_rows()
        bg_total = len(bg_rows)
        bg_count = {}
        for terms in _terms_per_doc([int(r) for r in bg_rows]).values():
            for t in terms:
                bg_count[t] = bg_count.get(t, 0) + 1
        bg_cache[bg_key] = (bg_count, bg_total)
    scored = []
    for t, fg in fg_count.items():
        if fg < min_count:
            continue
        bg = bg_count.get(t, fg)
        fg_freq = fg / fg_total if fg_total else 0.0
        bg_freq = bg / bg_total if bg_total else 0.0
        if fg_freq <= bg_freq or bg_freq == 0:
            continue
        score = (fg_freq - bg_freq) * (fg_freq / bg_freq)  # JLH
        scored.append((score, t, fg, bg))
    scored.sort(key=lambda x: (-x[0], _sort_key(x[1])))
    tname = getattr(mapper, "type_name", None)
    inc, exc = spec.get("include"), spec.get("exclude")
    import re as _re
    buckets = []
    for score, t, fg, bg in scored:
        if len(buckets) >= size:
            break
        key = t
        if tname == "ip" and isinstance(t, (int, float)):
            from elasticsearch_tpu.index.mapping import IpFieldMapper
            key = IpFieldMapper.format_value(int(t))
        ks = str(key)
        if isinstance(inc, list) and ks not in {str(x) for x in inc}:
            continue
        if isinstance(exc, list) and ks in {str(x) for x in exc}:
            continue
        if isinstance(inc, str) and not _re.fullmatch(inc, ks):
            continue
        if isinstance(exc, str) and _re.fullmatch(exc, ks):
            continue
        b = {"key": key, "doc_count": fg, "score": score, "bg_count": bg}
        if tname == "date" and isinstance(t, (int, float)):
            b["key_as_string"] = _millis_to_iso(int(t))
        if sub_aggs:
            brows = np.asarray(sorted(set(fg_rows_by_term[t])),
                               dtype=np.int64)
            b.update(recurse(ctx, brows, sub_aggs))
        buckets.append(b)
    return {"doc_count": fg_total, "bg_count": bg_total, "buckets": buckets}


def _range_field_histo(ctx, rows, sub_aggs, spec, field, recurse=None) -> dict:
    """date_histogram over a date_range field: every doc counts in EVERY
    bucket its range overlaps (reference: RangeHistogramAggregator)."""
    recurse = recurse or compute_aggs
    interval_ms, calendar = _date_interval(spec)
    offset_ms = _date_offset_ms(spec.get("offset"))
    tz = _resolve_tz(spec.get("time_zone"))
    fmt = spec.get("format")
    groups: Dict[float, List[int]] = {}
    for i, row in enumerate(rows):
        v = ctx.reader.get_doc_value(field, int(row))
        if isinstance(v, list):
            v = v[0] if v else None
        if not isinstance(v, dict):
            continue
        lo = float(v.get("gte", np.nan))
        hi = float(v.get("lte", np.nan))
        if not (np.isfinite(lo) and np.isfinite(hi)):
            continue

        def floor_of(ms):
            if calendar:
                return _calendar_floor(int(ms - offset_ms), calendar) \
                    + offset_ms
            return float(np.floor((ms - offset_ms) / interval_ms)
                         * interval_ms + offset_ms)
        cur = floor_of(lo)
        end = floor_of(hi)
        guard = 0
        while cur <= end and guard < 100_000:
            groups.setdefault(float(cur), []).append(int(row))
            guard += 1
            if calendar:
                # advance to the next calendar bucket: probe forward until
                # the floor moves (calendar units are variable-length)
                step = cur + interval_ms / 2
                while floor_of(step) <= cur and guard < 100_000:
                    step += 86_400_000
                    guard += 1
                cur = floor_of(step)
            else:
                cur += interval_ms
    buckets = []
    _check_max_buckets(ctx, len(groups))
    for key in sorted(groups):
        brows = np.asarray(sorted(set(groups[key])), dtype=np.int64)
        b = {"key": int(key), "doc_count": int(len(brows)),
             "key_as_string": _format_date_key(int(key), fmt, tz) if fmt
             else _millis_to_iso_tz(int(key), tz)}
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        buckets.append(b)
    return {"buckets": buckets}


def _decimal_format(value, pattern: str) -> str:
    """Minimal Java DecimalFormat: literal prefix/suffix around a #/0 run
    with optional fraction digits ("Value is ##0.0" -> "Value is 51.0")."""
    m = re.search(r"[#0][#0,.]*", pattern)
    if not m:
        return pattern
    num = m.group(0)
    prefix, suffix = pattern[:m.start()], pattern[m.end():]
    if "." in num:
        frac = num.split(".", 1)[1]
        min_frac, max_frac = frac.count("0"), len(frac)
    else:
        min_frac = max_frac = 0
    v = float(value)
    if max_frac == 0:
        s = str(int(round(v)))
    else:
        s = f"{v:.{max_frac}f}"
        int_part, frac_part = s.split(".")
        frac_part = frac_part.rstrip("0").ljust(min_frac, "0")
        s = int_part + ("." + frac_part if frac_part else "")
    return prefix + s + suffix


def _check_max_buckets(ctx, n: int) -> None:
    """search.max_buckets guard (MultiBucketConsumerService)."""
    mx = getattr(ctx, "max_buckets", None)
    if mx is not None and n > mx:
        from elasticsearch_tpu.common.errors import TooManyBucketsError
        raise TooManyBucketsError(
            f"Trying to create too many buckets. Must be less than or "
            f"equal to: [{mx}] but was [{n}]. This limit can be set by "
            f"changing the [search.max_buckets] cluster level setting.")


def _sort_key(v):
    if v is None:
        return (2, "")
    if isinstance(v, bool):
        return (1, str(v))
    if isinstance(v, (int, float)):
        return (0, float(v))
    return (1, str(v))


MAX_BUCKETS = 65536  # reference: search.max_buckets default


def _histo_buckets(ctx, rows, sub_aggs, keys, present, min_count,
                   extended_bounds, interval, date=False, recurse=None,
                   fmt=None, tz=None) -> dict:
    recurse = recurse or compute_aggs
    groups: Dict[float, np.ndarray] = {}
    valid = present & ~np.isnan(keys)
    for key in np.unique(keys[valid]):
        groups[float(key)] = rows[valid & (keys == key)]
    all_keys = sorted(groups)

    def _guard_span(lo_key, hi_key):
        # reference: search.max_buckets / MultiBucketConsumer
        if interval and (hi_key - lo_key) / interval > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"Trying to create too many buckets. Must be less than or "
                f"equal to: [{MAX_BUCKETS}].")

    if extended_bounds and interval:
        lo, hi = float(extended_bounds.get("min", np.inf)), float(extended_bounds.get("max", -np.inf))
        k = min([lo] + all_keys) if all_keys or lo != np.inf else lo
        top = max([hi] + all_keys) if all_keys or hi != -np.inf else hi
        _guard_span(k, top)
        cur = k
        full = []
        while cur <= top + 1e-9:
            full.append(round(cur, 10))
            cur += interval
        all_keys = full
    elif min_count == 0 and all_keys and interval:
        _guard_span(all_keys[0], all_keys[-1])
        full = []
        cur = all_keys[0]
        while cur <= all_keys[-1] + 1e-9:
            full.append(round(cur, 10))
            cur += interval
        all_keys = full
    _check_max_buckets(ctx, len(all_keys))
    buckets = []
    for key in all_keys:
        brows = groups.get(key, np.zeros(0, dtype=np.int64))
        if len(brows) < min_count and min_count > 0:
            continue
        b = {"key": int(key) if date else key, "doc_count": int(len(brows))}
        if date:
            b["key_as_string"] = _format_date_key(int(key), fmt, tz) if fmt \
                else _millis_to_iso_tz(int(key), tz)
        if sub_aggs:
            b.update(recurse(ctx, brows, sub_aggs))
        buckets.append(b)
    return {"buckets": buckets}


_CAL_UNITS = {"minute": "T", "1m": "T", "hour": "H", "1h": "H", "day": "D", "1d": "D",
              "week": "W", "1w": "W", "month": "M", "1M": "M", "quarter": "Q",
              "1q": "Q", "year": "Y", "1y": "Y"}
_FIXED_RE = re.compile(r"^(\d+)(ms|s|m|h|d)$")
_FIXED_FACTORS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}


def _date_interval(spec: dict) -> Tuple[float, Optional[str]]:
    cal = spec.get("calendar_interval")
    if cal:
        unit = _CAL_UNITS.get(cal)
        if unit is None:
            raise ParsingError(f"unknown calendar interval [{cal}]")
        return 0.0, unit
    fixed = spec.get("fixed_interval") or spec.get("interval")
    if fixed is None:
        raise ParsingError("date_histogram requires calendar_interval or fixed_interval")
    if isinstance(fixed, (int, float)):
        return float(fixed), None
    m = _FIXED_RE.match(str(fixed))
    if m:
        return float(int(m.group(1)) * _FIXED_FACTORS[m.group(2)]), None
    unit = _CAL_UNITS.get(str(fixed))
    if unit:
        return 0.0, unit
    raise ParsingError(f"unknown interval [{fixed}]")


def _resolve_tz(tz_spec):
    """time_zone param -> tzinfo: fixed offsets ("-07:00") or IANA names
    (America/Phoenix) via zoneinfo."""
    import datetime as dt
    if not tz_spec:
        return None
    s = str(tz_spec)
    m = re.fullmatch(r"([+-])(\d{2}):?(\d{2})", s)
    if m:
        sign = 1 if m.group(1) == "+" else -1
        return dt.timezone(sign * dt.timedelta(hours=int(m.group(2)),
                                               minutes=int(m.group(3))))
    try:
        import zoneinfo
        return zoneinfo.ZoneInfo(s)
    except Exception:
        return None


def _millis_to_iso_tz(millis: int, tz) -> str:
    """ISO rendering in a zone with its offset suffix
    ("2015-12-31T17:00:00.000-07:00"); UTC renders with Z."""
    import datetime as dt
    if tz is None:
        return _millis_to_iso(millis)
    d = dt.datetime.fromtimestamp(millis / 1000.0, tz=tz)
    base = d.strftime("%Y-%m-%dT%H:%M:%S") + f".{d.microsecond // 1000:03d}"
    off = d.utcoffset() or dt.timedelta(0)
    if off == dt.timedelta(0):
        return base + "Z"
    total = int(off.total_seconds())
    sign = "+" if total >= 0 else "-"
    total = abs(total)
    return base + f"{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"


def _format_date_key(millis: int, fmt: str, tz=None) -> str:
    """Joda-pattern-lite date rendering for agg keys ("yyyy-MM-dd",
    "iso8601", "strict_date_time", epoch_millis, "e" day-of-week)."""
    if fmt in ("iso8601", "strict_date_time", "date_time"):
        return _millis_to_iso_tz(millis, tz) if tz else _millis_to_iso(millis)
    if fmt == "epoch_millis":
        return str(millis)
    import datetime as dt
    try:
        d = dt.datetime.fromtimestamp(millis / 1000.0,
                                      tz=tz or dt.timezone.utc)
    except (OverflowError, OSError, ValueError):
        return str(millis)
    if fmt == "e":
        # Joda dayOfWeek number (ISO: Monday=1 .. Sunday=7)
        return str(d.isoweekday())
    strf = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
            .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
            .replace("ss", "%S"))
    out = d.strftime(strf)
    if "SSS" in out:
        out = out.replace("SSS", f"{d.microsecond // 1000:03d}")
    return out


def _date_offset_ms(offset) -> float:
    """date_histogram `offset` like "+6h"/"-1d" → millis."""
    if not offset:
        return 0.0
    s = str(offset)
    sign = -1.0 if s.startswith("-") else 1.0
    s = s.lstrip("+-")
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            return sign * float(s[:-len(suffix)]) * units[suffix]
    try:
        return sign * float(s)
    except ValueError:
        return 0.0


def _calendar_floor(millis: int, unit: str, tz=None) -> float:
    """Floor to a calendar unit, in `tz`'s local wall time when given
    (Rounding.Builder timeZone semantics — buckets align to local
    midnight/month starts, not UTC)."""
    import datetime as dt
    d = dt.datetime.fromtimestamp(millis / 1000.0, tz=tz or dt.timezone.utc)
    if unit == "T":
        d = d.replace(second=0, microsecond=0)
    elif unit == "H":
        d = d.replace(minute=0, second=0, microsecond=0)
    elif unit == "D":
        d = d.replace(hour=0, minute=0, second=0, microsecond=0)
    elif unit == "W":
        d = (d - dt.timedelta(days=d.weekday())).replace(hour=0, minute=0, second=0, microsecond=0)
    elif unit == "M":
        d = d.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    elif unit == "Q":
        d = d.replace(month=((d.month - 1) // 3) * 3 + 1, day=1, hour=0, minute=0,
                      second=0, microsecond=0)
    elif unit == "Y":
        d = d.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    return float(int(d.timestamp() * 1000))


def _millis_to_iso(millis: int) -> str:
    import datetime as dt
    try:
        d = dt.datetime.fromtimestamp(millis / 1000.0, tz=dt.timezone.utc)
    except (OverflowError, OSError, ValueError):
        # out-of-range epoch (e.g. nanos mistakenly fed as millis): render
        # the raw number instead of 500ing the whole response
        return str(millis)
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{d.microsecond // 1000:03d}Z"


# ---------------------------------------------------------------------------
# pipeline aggregations
# ---------------------------------------------------------------------------

def _top_hits(ctx, rows, spec) -> dict:
    """top_hits metric (TopHitsAggregator): source hits per bucket with
    optional sort (incl. nested sort paths) and seq_no/primary_term.
    Directly under a `nested` agg the hits are the NESTED documents, each
    carrying its parent _id and a _nested {field, offset} locator."""
    size = int(spec.get("size", 3))
    want_seq = bool(spec.get("seq_no_primary_term"))
    index_name = getattr(ctx, "index_name", "index")
    nested_ctx = getattr(ctx, "nested_path", None)

    def parse_sort(ss):
        if isinstance(ss, str):
            return ss, "asc", None
        if isinstance(ss, list) and ss:
            return parse_sort(ss[0])
        if isinstance(ss, dict) and ss:
            ((f, o),) = list(ss.items())[:1]
            if isinstance(o, dict):
                return f, o.get("order", "asc"), \
                    (o.get("nested") or {}).get("path")
            return f, str(o), None
        return None, "asc", None

    sfield, sorder, sort_nested = parse_sort(spec.get("sort"))
    if sfield and sfield.endswith(".keyword"):
        sfield = sfield[: -len(".keyword")]

    def walk(obj, path):
        cur = obj
        for p in path.split("."):
            cur = cur.get(p) if isinstance(cur, dict) else None
        return cur

    reverse = sorder == "desc"
    if nested_ctx and sfield and sfield.startswith(nested_ctx + "."):
        rel = sfield[len(nested_ctx) + 1:]
        entries = []
        for row in rows:
            src = ctx.reader.get_source(int(row)) or {}
            items = walk(src, nested_ctx)
            if isinstance(items, dict):
                items = [items]
            for off, item in enumerate(items or []):
                if isinstance(item, dict):
                    entries.append((walk(item, rel), int(row), off, item))
        present_e = [e for e in entries if e[0] is not None]
        absent_e = [e for e in entries if e[0] is None]
        present_e.sort(key=lambda e: (isinstance(e[0], str), e[0]),
                       reverse=reverse)
        entries = present_e + absent_e
        hits = []
        for val, row, off, item in entries[:size]:
            hits.append({"_index": index_name,
                         "_id": ctx.reader.get_id(row),
                         "_nested": {"field": nested_ctx, "offset": off},
                         "_source": item, "_score": None, "sort": [val]})
        return {"hits": {"total": {"value": len(entries), "relation": "eq"},
                         "max_score": None, "hits": hits}}

    entries = []
    for row in rows:
        key = None
        if sfield:
            npath = sort_nested
            if not npath:
                dv = ctx.reader.get_doc_value(sfield, int(row))
                if dv is not None:
                    key = dv[0] if isinstance(dv, list) and dv else dv
                    entries.append((key, int(row)))
                    continue
            src = ctx.reader.get_source(int(row)) or {}
            if npath and sfield.startswith(npath + "."):
                items = walk(src, npath)
                if isinstance(items, dict):
                    items = [items]
                vals = [walk(it, sfield[len(npath) + 1:])
                        for it in items or [] if isinstance(it, dict)]
                vals = [v for v in vals if v is not None]
                key = (max(vals) if reverse else min(vals)) if vals else None
            else:
                key = walk(src, sfield)
                if isinstance(key, list):
                    key = key[0] if key else None
        entries.append((key, int(row)))
    if sfield:
        present_e = [e for e in entries if e[0] is not None]
        absent_e = [e for e in entries if e[0] is None]
        present_e.sort(key=lambda e: (isinstance(e[0], str), e[0]),
                       reverse=reverse)
        entries = present_e + absent_e
    hits = []
    for key, row in entries[:size]:
        h = {"_index": index_name, "_id": ctx.reader.get_id(row),
             "_source": ctx.reader.get_source(row), "_score": None}
        if sfield:
            h["sort"] = [key]
        if want_seq:
            sq = ctx.reader.get_seq_no(row)
            h["_seq_no"] = int(sq) if sq is not None else 0
            h["_primary_term"] = 1
        hits.append(h)
    return {"hits": {"total": {"value": len(rows), "relation": "eq"},
                     "max_score": None, "hits": hits}}


def _resolve_buckets_path(sibling_outputs: dict, path: str):
    """Resolve 'agg>metric' / 'agg.value' buckets_path over computed outputs.

    Sibling pipelines may only step INTO one multi-bucket aggregation; a
    second multi-bucket agg mid-path (or as the terminal element) is the
    reference's AggregationPath validation error."""
    agg_path, _, metric = path.partition(">")
    node = sibling_outputs.get(agg_path)
    if node is None:
        raise ParsingError(f"buckets_path [{path}] references unknown aggregation")
    buckets = node.get("buckets")
    if buckets is None:
        raise ParsingError(f"buckets_path [{path}] target has no buckets")
    head = metric.split(">", 1)[0].split(".")[0] if metric else ""
    sample = next(iter(buckets.values() if isinstance(buckets, dict)
                       else buckets), None)
    if head and isinstance(sample, dict):
        inner = sample.get(head)
        if isinstance(inner, dict) and "buckets" in inner:
            if ">" in metric:
                # a multi-bucket agg mid-path: the reference renders the
                # owning agg's Java bucket type in the message
                raise IllegalArgumentError(
                    f"buckets_path must reference either a number value or "
                    f"a single value numeric metric aggregation, got: "
                    f"[Object[]] at aggregation [{head}]")
            raise IllegalArgumentError(
                f"buckets_path must reference either a number value or a "
                f"single value numeric metric aggregation, got: "
                f"[LongTerms] at aggregation [{head}]")
        if isinstance(inner, dict) and "values" in inner \
                and "." not in metric:
            raise IllegalArgumentError(
                f"buckets_path must reference either a number value or a "
                f"single value numeric metric aggregation, but [{head}] "
                f"contains multiple values. Please specify which to use.")
    values = []
    for b in (buckets.values() if isinstance(buckets, dict) else buckets):
        if not metric or metric == "_count":
            values.append(float(b["doc_count"]))
        else:
            m = b
            for part in metric.split("."):
                m = m.get(part) if isinstance(m, dict) else None
            if isinstance(m, dict):
                m = m.get("value")
            values.append(float(m) if m is not None else None)
    return node, buckets, values


def _compute_pipeline(outputs: dict, kind: str, spec: dict, name: str = "") -> Any:
    if kind in ("bucket_script", "bucket_selector", "bucket_sort"):
        return _compute_bucket_pipeline(outputs, kind, spec, name)
    path = spec.get("buckets_path")
    node, buckets, values = _resolve_buckets_path(outputs, path)
    present = [v for v in values if v is not None]
    if kind == "avg_bucket":
        return {"value": sum(present) / len(present) if present else None}
    if kind == "sum_bucket":
        return {"value": sum(present) if present else 0.0}
    if kind == "max_bucket":
        if not present:
            return {"value": None, "keys": []}
        mx = max(present)
        keys = [str(b.get("key")) for b, v in zip(buckets, values) if v == mx]
        return {"value": mx, "keys": keys}
    if kind == "min_bucket":
        if not present:
            return {"value": None, "keys": []}
        mn = min(present)
        keys = [str(b.get("key")) for b, v in zip(buckets, values) if v == mn]
        return {"value": mn, "keys": keys}
    if kind == "stats_bucket":
        if not present:
            return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
        return {"count": len(present), "min": min(present), "max": max(present),
                "avg": sum(present) / len(present), "sum": sum(present)}
    if kind == "extended_stats_bucket":
        arr = np.asarray(present, dtype=np.float64)
        return _extended_stats(arr, np.ones(len(arr), dtype=bool),
                               float(spec.get("sigma", 2.0)))
    if kind == "percentiles_bucket":
        pcts = spec.get("percents", [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0])
        arr = np.asarray(present, dtype=np.float64)
        return {"values": {f"{float(p)}":
                           (float(np.percentile(arr, p)) if len(arr) else None)
                           for p in pcts}}
    if kind == "cumulative_sum":
        total = 0.0
        for b, v in zip(buckets, values):
            total += v or 0.0
            b.setdefault(name, {})["value"] = total
        return {"_applied": True}
    if kind == "derivative":
        prev = None
        for b, v in zip(buckets, values):
            if prev is not None and v is not None:
                b.setdefault(name, {})["value"] = v - prev
            prev = v
        return {"_applied": True}
    if kind == "serial_diff":
        lag = int(spec.get("lag", 1))
        for i, b in enumerate(buckets):
            if i >= lag and values[i] is not None and values[i - lag] is not None:
                b.setdefault(name, {})["value"] = values[i] - values[i - lag]
        return {"_applied": True}
    if kind == "moving_fn":
        window = int(spec.get("window", 5))
        if window <= 0:
            raise IllegalArgumentError(
                "[window] must be a positive, non-zero integer.")
        for i, b in enumerate(buckets):
            win = [v for v in values[max(0, i - window):i] if v is not None]
            b.setdefault(name, {})["value"] = (sum(win) / len(win)) if win else None
        return {"_applied": True}
    raise ParsingError(f"unknown pipeline aggregation [{kind}]")


def _compute_bucket_pipeline(outputs: dict, kind: str, spec: dict, name: str = "") -> Any:
    paths: Dict[str, str] = spec.get("buckets_path", {})
    # all paths must target the same parent agg buckets
    parents = set()
    series: Dict[str, List[Optional[float]]] = {}
    buckets_ref = None
    for var, path in paths.items():
        agg_path = path.partition(">")[0]
        parents.add(agg_path)
        _, buckets_ref, values = _resolve_buckets_path(outputs, path)
        series[var] = values
    if buckets_ref is None:
        return {"_applied": False}
    script = spec.get("script", "")
    source = script["source"] if isinstance(script, dict) else script
    import ast as _ast

    def eval_for(i: int):
        env = {var: vals[i] for var, vals in series.items()}
        if any(v is None for v in env.values()):
            return None
        tree = _ast.parse(source.replace("params.", ""), mode="eval")

        def ev(node):
            if isinstance(node, _ast.Expression):
                return ev(node.body)
            if isinstance(node, _ast.Constant):
                return node.value
            if isinstance(node, _ast.Name):
                if node.id in env:
                    return env[node.id]
                raise ParsingError(f"unknown variable [{node.id}] in bucket script")
            if isinstance(node, _ast.BinOp):
                ops = {_ast.Add: lambda a, b: a + b, _ast.Sub: lambda a, b: a - b,
                       _ast.Mult: lambda a, b: a * b, _ast.Div: lambda a, b: a / b}
                return ops[type(node.op)](ev(node.left), ev(node.right))
            if isinstance(node, _ast.Compare):
                left = ev(node.left)
                right = ev(node.comparators[0])
                ops = {_ast.Gt: left > right, _ast.GtE: left >= right,
                       _ast.Lt: left < right, _ast.LtE: left <= right,
                       _ast.Eq: left == right, _ast.NotEq: left != right}
                return ops[type(node.ops[0])]
            if isinstance(node, _ast.UnaryOp) and isinstance(node.op, _ast.USub):
                return -ev(node.operand)
            raise ParsingError("unsupported bucket script construct")

        return ev(tree)

    bl = buckets_ref if isinstance(buckets_ref, list) else list(buckets_ref.values())
    if kind == "bucket_script":
        name = spec.get("_name", "bucket_script")
        for i, b in enumerate(bl):
            v = eval_for(i)
            if v is not None:
                b.setdefault(name, {})["value"] = float(v)
        return {"_applied": True}
    if kind == "bucket_selector":
        keep = [bool(eval_for(i)) for i in range(len(bl))]
        bl[:] = [b for b, k in zip(bl, keep) if k]
        return {"_applied": True}
    if kind == "bucket_sort":
        sort_spec = spec.get("sort", [])
        size = spec.get("size")
        frm = int(spec.get("from", 0))
        for s in reversed(sort_spec):
            if isinstance(s, dict):
                ((path, order),) = s.items()
                direction = order.get("order", "asc") if isinstance(order, dict) else order
                def keyfn(b, p=path):
                    node = b
                    for part in p.split("."):
                        node = node.get(part) if isinstance(node, dict) else None
                    if isinstance(node, dict):
                        node = node.get("value")
                    return node if node is not None else -math.inf
                bl.sort(key=keyfn, reverse=direction == "desc")
        end = frm + size if size is not None else None
        bl[:] = bl[frm:end]
        return {"_applied": True}
    return {"_applied": False}

