"""Search-path caches + can_match shard pre-filtering.

Re-design of the reference's three search accelerators (SURVEY.md §2.5):

- **Shard request cache** (`indices/IndicesRequestCache.java`): caches whole
  shard-level query-phase results, keyed on the request body. Like the
  reference, only hits-free requests (size=0: aggs/counts) are cacheable by
  default — full hit payloads are cheap to recompute and expensive to hold —
  and an explicit `request_cache=true` opts in. Entries key on the reader
  generation, so a refresh that actually changed the shard naturally
  invalidates (the reference invalidates by reader identity the same way).
- **Node query cache** (`indices/IndicesQueryCache.java`): caches filter-
  context DocSet row arrays keyed (reader generation, filter source).
  Filters are score-free, so a cached row array is exact; scoring clauses
  are never cached (same as Lucene's UsageTrackingQueryCachingPolicy caching
  only filters).
- **can_match** (`CanMatchPreFilterSearchPhase.java:57`): a lightweight
  per-shard test — do the query's range constraints overlap the shard's
  field min/max? — that lets the coordinator skip shards before the query
  phase fans out.

Caches are node-level singletons shared by all shards (the reference sizes
them as a fraction of heap; here entry-count LRU bounds them).
"""

from __future__ import annotations

import hashlib
import json
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _approx_bytes(value: Any, _depth: int = 0) -> int:
    """Approximate resident size of a cached entry: array payloads by
    nbytes, strings/bytes by length, containers by shallow recursion
    (bounded — a pathological deep value degrades to the flat estimate,
    which is fine for a stats gauge)."""
    if value is None or isinstance(value, (bool, int, float)):
        return 8
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (str, bytes)):
        return len(value)
    if _depth >= 6:
        return 64
    if isinstance(value, dict):
        return 64 + sum(_approx_bytes(k, _depth + 1)
                        + _approx_bytes(v, _depth + 1)
                        for k, v in value.items())
    if isinstance(value, (list, tuple, set, frozenset)):
        return 64 + sum(_approx_bytes(v, _depth + 1) for v in value)
    nb = getattr(value, "nbytes", None)
    if isinstance(nb, (int, np.integer)):
        return int(nb)
    # opaque objects (ShardSearchResult, ...): sum their array slots
    d = getattr(value, "__dict__", None)
    if isinstance(d, dict) and _depth < 6:
        return 64 + sum(_approx_bytes(v, _depth + 1) for v in d.values())
    return 64


class LruCache:
    """Entry-count-bounded LRU with hit/miss/eviction/byte stats.

    Byte accounting is approximate (`_approx_bytes` at put time) but
    real: `memory_size_in_bytes` in `_nodes/stats` reports this gauge
    instead of the hardcoded 0 it used to."""

    def __init__(self, max_entries: int = 1024):
        import threading
        self.max_entries = max_entries
        self._map: "OrderedDict[Any, Any]" = OrderedDict()
        self._entry_bytes: Dict[Any, int] = {}
        # get/put race from client threads (node.search) and finalize
        # threads (hybrid executor); byte accounting + LRU eviction need
        # a consistent view
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bytes = 0

    def get(self, key):
        with self._lock:
            try:
                value = self._map[key]
            except KeyError:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key, value) -> None:
        nb = _approx_bytes(key) + _approx_bytes(value)
        with self._lock:
            if key in self._map:
                self.bytes -= self._entry_bytes.get(key, 0)
            self._map[key] = value
            self._entry_bytes[key] = nb
            self.bytes += nb
            self._map.move_to_end(key)
            while len(self._map) > self.max_entries:
                old_key, _ = self._map.popitem(last=False)
                self.bytes -= self._entry_bytes.pop(old_key, 0)
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
            self._entry_bytes.clear()
            self.bytes = 0

    def __len__(self):
        return len(self._map)

    def stats(self) -> dict:
        return {"entries": len(self._map), "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions,
                "memory_size_in_bytes": self.bytes}


def _canonical(body: Any) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)


class RequestCache(LruCache):
    """Shard request cache: (shard key, reader epoch, body) -> query result.

    `cacheable(body)` mirrors `IndicesRequestCache` policy: size==0 requests
    cache by default; `request_cache` in the body forces either way; requests
    with non-deterministic parts (scripts, "now"-relative ranges) never cache.

    `skipped_uncacheable` counts requests that explicitly opted IN
    (`request_cache: true`) but were refused for being non-deterministic —
    without it those refusals read as ordinary misses and the stats-side
    hit-rate math overstates cold traffic.
    """

    def __init__(self, max_entries: int = 1024):
        super().__init__(max_entries)
        self.skipped_uncacheable = 0

    @staticmethod
    def deterministic(body: dict) -> bool:
        """False for bodies whose results can differ between identical
        requests (scripts, "now"-relative ranges) — never cacheable."""
        src = _canonical(body)
        return '"script' not in src and '"now' not in src.lower()

    @staticmethod
    def cacheable(body: dict) -> bool:
        flag = body.get("request_cache")
        if flag is False:
            return False
        if flag is not True and body.get("size", None) != 0:
            return False  # before _canonical: don't serialize large bodies
        return RequestCache.deterministic(body)

    def cacheable_tracked(self, body: dict) -> bool:
        """`cacheable` plus the opt-in bookkeeping: a body that asked
        for caching (`request_cache: true`) but is non-deterministic
        counts as `skipped_uncacheable`, not as a plain miss."""
        flag = body.get("request_cache")
        if flag is True and not self.deterministic(body):
            self.skipped_uncacheable += 1
            return False
        return self.cacheable(body)

    def device_cacheable(self, body: dict) -> bool:
        """Device-path extension: bodies whose query phase runs a device
        kNN dispatch cache by default even with size > 0 — the query
        phase (the matmul + top-k) is the expensive part and its result
        is small; the fetch phase re-runs per request against the same
        reader the fingerprint pinned. `request_cache: false` still
        opts out; non-deterministic parts still refuse."""
        flag = body.get("request_cache")
        if flag is False:
            return False
        q = body.get("query")
        has_knn = "knn" in body or (isinstance(q, dict) and "knn" in q)
        if not has_knn:
            # non-kNN bodies belong to the host rung's policy
            return False
        if not self.deterministic(body):
            if flag is True:
                self.skipped_uncacheable += 1
            return False
        return True

    def key(self, shard_key: Any, reader_epoch, body: dict) -> tuple:
        """`reader_epoch` is either the legacy reader generation or a
        content fingerprint tuple (`reader_fingerprint`) — the latter
        keeps entries valid across refreshes that changed nothing."""
        return (shard_key, reader_epoch, _canonical(
            {k: v for k, v in body.items() if k != "request_cache"}))

    def stats(self) -> dict:
        out = super().stats()
        out["skipped_uncacheable"] = self.skipped_uncacheable
        return out


# ---------------------------------------------------------------------------
# device-path request-cache keys
# ---------------------------------------------------------------------------

def reader_fingerprint(reader) -> tuple:
    """Content fingerprint of a point-in-time reader: per-segment
    (seg_id, num_docs, live_count) — the same identity the columnar
    block store keys its arrays on (`columnar/blocks.fingerprint`).

    Keying request-cache entries on this instead of `reader.gen` keeps
    them valid across refreshes that changed nothing (an idle index
    refreshing on its interval rotates gens but not content), while any
    ingest, delete, or merge rotates at least one component. Memoized on
    the reader: views snapshot their live bitmaps at construction, so a
    reader's fingerprint never changes after the first call."""
    fp = getattr(reader, "_content_fingerprint", None)
    if fp is None:
        from elasticsearch_tpu.columnar.blocks import fingerprint
        fp = reader._content_fingerprint = tuple(
            fingerprint(v) for v in reader.views)
    return fp


def value_fingerprint(body: Any) -> str:
    """Digest of a request body's VALUE slots, the complement of
    `plan_cache_key`'s shape normalization: where the plan key scrubs
    query vectors to dims and match text to placeholders (so plans
    dedupe), the request cache must distinguish those values — but
    without storing a 768-float JSON string per key. Vectors hash as
    raw f32 bytes; everything else as canonical JSON."""
    h = hashlib.blake2b(digest_size=16)

    def walk(node):
        if isinstance(node, dict):
            for k in sorted(node):
                v = node[k]
                h.update(k.encode())
                if k == "query_vector":
                    try:
                        arr = np.asarray(v, dtype=np.float32)
                        h.update(repr(arr.shape).encode())
                        h.update(arr.tobytes())
                    except (ValueError, TypeError):
                        walk(v)  # malformed vector: hash as plain JSON
                else:
                    walk(v)
        elif isinstance(node, (list, tuple)):
            h.update(b"[")
            for v in node:
                walk(v)
            h.update(b"]")
        else:
            h.update(_canonical(node).encode())

    walk(body)
    return h.hexdigest()


def request_cache_key(plan_key, body: dict, *, fingerprint, epoch=()) -> tuple:
    """Sanctioned device-path request-cache key (tpulint TPU005):

    - `plan_key`: the normalized shape key (`hybrid_plan.plan_cache_key` /
      `agg_plan.plan_cache_key`) — values already scrubbed;
    - `body`: hashed through `value_fingerprint` so distinct vectors /
      texts with the same shape stay distinct keys;
    - `fingerprint`: REQUIRED reader content fingerprint
      (`reader_fingerprint`) — refresh-driven invalidation lives here;
      a key without it serves stale bytes across refresh;
    - `epoch`: live settings the response depends on (max_buckets,
      allow-expensive, ...) so a settings change misses instead of
      serving results computed under the old limits."""
    body = {k: v for k, v in body.items()
            if k not in ("request_cache", "profile")}
    return (plan_key, value_fingerprint(body), tuple(fingerprint),
            tuple(epoch))


def has_range_clauses(query: Optional[dict]) -> bool:
    """True when the query carries at least one must/filter range clause —
    the coordinator's trigger for running the can_match pre-filter phase
    below the shard-count threshold (a time-range dashboard body over
    time-partitioned indices is exactly this shape)."""
    return next(_iter_range_clauses(query), None) is not None


class QueryCache(LruCache):
    """Node query cache: (reader gen, filter source) -> matching row array."""

    def get_rows(self, reader_gen: int, filter_source: str) -> Optional[np.ndarray]:
        return self.get((reader_gen, filter_source))

    def put_rows(self, reader_gen: int, filter_source: str,
                 rows: np.ndarray) -> None:
        self.put((reader_gen, filter_source), rows)


# ---------------------------------------------------------------------------
# can_match
# ---------------------------------------------------------------------------

def _iter_range_clauses(query: Optional[dict]):
    """Yield (field, spec) for every range clause that constrains the whole
    query (top-level range, or range inside bool.must / bool.filter — a
    `should` range does not constrain, matching the conservative skipping in
    the reference's coordinator rewrite)."""
    if not isinstance(query, dict):
        return
    for kind, spec in query.items():
        if kind == "range" and isinstance(spec, dict):
            for field, bounds in spec.items():
                if isinstance(bounds, dict):
                    yield field, bounds
        elif kind == "bool" and isinstance(spec, dict):
            for clause in ("must", "filter"):
                items = spec.get(clause, [])
                if isinstance(items, dict):
                    items = [items]
                for sub in items:
                    yield from _iter_range_clauses(sub)
        elif kind == "constant_score" and isinstance(spec, dict):
            yield from _iter_range_clauses(spec.get("filter"))


def _to_number(value, mapper_service, field, round_up: bool = False) -> Optional[float]:
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        mapper = mapper_service.get(field) if mapper_service else None
        type_name = getattr(mapper, "type_name", None)
        if type_name == "date":
            try:
                from elasticsearch_tpu.index.mapping import parse_date_millis
                # mirror RangeQuery's rounding (queries.py _coerce_bound):
                # upper bounds round UP to unit end so can_match never
                # skips a shard the real query would hit
                return float(parse_date_millis(value, round_up=round_up))
            except Exception:
                return None
        try:
            return float(value)
        except ValueError:
            return None
    return None


def can_match(reader, mapper_service, body: dict) -> bool:
    """True unless a must/filter range clause provably excludes every live
    doc in this shard (field max < gte, or field min > lte). Conservative:
    anything unparseable means "might match"."""
    query = body.get("query")
    for field, bounds in _iter_range_clauses(query):
        stats = field_stats(reader, field)
        if stats is None:
            # field absent from the shard entirely: a required range on it
            # cannot match any doc
            if reader.num_docs > 0 and not _shard_has_field(reader, field):
                return False
            continue
        fmin, fmax = stats
        gte = _to_number(bounds.get("gte", bounds.get("gt")), mapper_service, field,
                         round_up="gte" not in bounds and "gt" in bounds)
        lte = _to_number(bounds.get("lte", bounds.get("lt")), mapper_service, field,
                         round_up="lte" in bounds)
        if gte is not None:
            if "gt" in bounds and "gte" not in bounds:
                if fmax <= gte:
                    return False
            elif fmax < gte:
                return False
        if lte is not None:
            if "lt" in bounds and "lte" not in bounds:
                if fmin >= lte:
                    return False
            elif fmin > lte:
                return False
    return True


def _shard_has_field(reader, field: str) -> bool:
    for v in reader.views:
        if field in v.segment.doc_values or field in v.segment.postings:
            return True
    return False


def field_stats(reader, field: str) -> Optional[Tuple[float, float]]:
    """(min, max) of a numeric/date field over live docs, cached per reader
    (the per-shard PointValues min/max the reference's can_match reads)."""
    cache: Dict[str, Optional[Tuple[float, float]]] = getattr(
        reader, "_field_stats_cache", None)
    if cache is None:
        cache = reader._field_stats_cache = {}
    if field in cache:
        return cache[field]
    fmin = fmax = None
    for v in reader.views:
        col = v.segment.doc_values.get(field)
        if col is None or col.numeric is None:
            continue
        mask = v.live & col.present
        if not mask.any():
            continue
        vals = col.numeric[mask]
        lo, hi = float(vals.min()), float(vals.max())
        fmin = lo if fmin is None else min(fmin, lo)
        fmax = hi if fmax is None else max(fmax, hi)
    result = None if fmin is None else (fmin, fmax)
    cache[field] = result
    return result


class NodeCaches:
    """Node-level cache singletons (the reference wires both caches into
    IndicesService and shares them across shards).

    `device_request` is the device-path rung of the shard request cache:
    fused hybrid responses and kNN query-phase results, keyed through
    `request_cache_key` (plan key + value digest + reader fingerprint +
    settings epoch). A separate instance from the legacy host `request`
    cache so each rung's hit-rate math stays honest in stats."""

    def __init__(self, request_entries: int = 1024, query_entries: int = 2048,
                 device_request_entries: int = 512):
        self.request = RequestCache(request_entries)
        self.device_request = RequestCache(device_request_entries)
        self.query = QueryCache(query_entries)

    def stats(self) -> dict:
        return {"request_cache": self.request.stats(),
                "device_request_cache": self.device_request.stats(),
                "query_cache": self.query.stats()}
