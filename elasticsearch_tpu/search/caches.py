"""Search-path caches + can_match shard pre-filtering.

Re-design of the reference's three search accelerators (SURVEY.md §2.5):

- **Shard request cache** (`indices/IndicesRequestCache.java`): caches whole
  shard-level query-phase results, keyed on the request body. Like the
  reference, only hits-free requests (size=0: aggs/counts) are cacheable by
  default — full hit payloads are cheap to recompute and expensive to hold —
  and an explicit `request_cache=true` opts in. Entries key on the reader
  generation, so a refresh that actually changed the shard naturally
  invalidates (the reference invalidates by reader identity the same way).
- **Node query cache** (`indices/IndicesQueryCache.java`): caches filter-
  context DocSet row arrays keyed (reader generation, filter source).
  Filters are score-free, so a cached row array is exact; scoring clauses
  are never cached (same as Lucene's UsageTrackingQueryCachingPolicy caching
  only filters).
- **can_match** (`CanMatchPreFilterSearchPhase.java:57`): a lightweight
  per-shard test — do the query's range constraints overlap the shard's
  field min/max? — that lets the coordinator skip shards before the query
  phase fans out.

Caches are node-level singletons shared by all shards (the reference sizes
them as a fraction of heap; here entry-count LRU bounds them).
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np


class LruCache:
    """Entry-count-bounded LRU with hit/miss/eviction stats."""

    def __init__(self, max_entries: int = 1024):
        self.max_entries = max_entries
        self._map: "OrderedDict[Any, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        try:
            value = self._map[key]
        except KeyError:
            self.misses += 1
            return None
        self._map.move_to_end(key)
        self.hits += 1
        return value

    def put(self, key, value) -> None:
        self._map[key] = value
        self._map.move_to_end(key)
        while len(self._map) > self.max_entries:
            self._map.popitem(last=False)
            self.evictions += 1

    def clear(self) -> None:
        self._map.clear()

    def __len__(self):
        return len(self._map)

    def stats(self) -> dict:
        return {"entries": len(self._map), "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions}


def _canonical(body: Any) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)


class RequestCache(LruCache):
    """Shard request cache: (shard key, reader gen, body) -> query result.

    `cacheable(body)` mirrors `IndicesRequestCache` policy: size==0 requests
    cache by default; `request_cache` in the body forces either way; requests
    with non-deterministic parts (scripts, "now"-relative ranges) never cache.
    """

    @staticmethod
    def cacheable(body: dict) -> bool:
        flag = body.get("request_cache")
        if flag is False:
            return False
        if flag is not True and body.get("size", None) != 0:
            return False  # before _canonical: don't serialize large bodies
        src = _canonical(body)
        if '"script' in src or '"now' in src.lower():
            return False
        return True

    def key(self, shard_key: Any, reader_gen: int, body: dict) -> tuple:
        return (shard_key, reader_gen, _canonical(
            {k: v for k, v in body.items() if k != "request_cache"}))


class QueryCache(LruCache):
    """Node query cache: (reader gen, filter source) -> matching row array."""

    def get_rows(self, reader_gen: int, filter_source: str) -> Optional[np.ndarray]:
        return self.get((reader_gen, filter_source))

    def put_rows(self, reader_gen: int, filter_source: str,
                 rows: np.ndarray) -> None:
        self.put((reader_gen, filter_source), rows)


# ---------------------------------------------------------------------------
# can_match
# ---------------------------------------------------------------------------

def _iter_range_clauses(query: Optional[dict]):
    """Yield (field, spec) for every range clause that constrains the whole
    query (top-level range, or range inside bool.must / bool.filter — a
    `should` range does not constrain, matching the conservative skipping in
    the reference's coordinator rewrite)."""
    if not isinstance(query, dict):
        return
    for kind, spec in query.items():
        if kind == "range" and isinstance(spec, dict):
            for field, bounds in spec.items():
                if isinstance(bounds, dict):
                    yield field, bounds
        elif kind == "bool" and isinstance(spec, dict):
            for clause in ("must", "filter"):
                items = spec.get(clause, [])
                if isinstance(items, dict):
                    items = [items]
                for sub in items:
                    yield from _iter_range_clauses(sub)
        elif kind == "constant_score" and isinstance(spec, dict):
            yield from _iter_range_clauses(spec.get("filter"))


def _to_number(value, mapper_service, field, round_up: bool = False) -> Optional[float]:
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        mapper = mapper_service.get(field) if mapper_service else None
        type_name = getattr(mapper, "type_name", None)
        if type_name == "date":
            try:
                from elasticsearch_tpu.index.mapping import parse_date_millis
                # mirror RangeQuery's rounding (queries.py _coerce_bound):
                # upper bounds round UP to unit end so can_match never
                # skips a shard the real query would hit
                return float(parse_date_millis(value, round_up=round_up))
            except Exception:
                return None
        try:
            return float(value)
        except ValueError:
            return None
    return None


def can_match(reader, mapper_service, body: dict) -> bool:
    """True unless a must/filter range clause provably excludes every live
    doc in this shard (field max < gte, or field min > lte). Conservative:
    anything unparseable means "might match"."""
    query = body.get("query")
    for field, bounds in _iter_range_clauses(query):
        stats = field_stats(reader, field)
        if stats is None:
            # field absent from the shard entirely: a required range on it
            # cannot match any doc
            if reader.num_docs > 0 and not _shard_has_field(reader, field):
                return False
            continue
        fmin, fmax = stats
        gte = _to_number(bounds.get("gte", bounds.get("gt")), mapper_service, field,
                         round_up="gte" not in bounds and "gt" in bounds)
        lte = _to_number(bounds.get("lte", bounds.get("lt")), mapper_service, field,
                         round_up="lte" in bounds)
        if gte is not None:
            if "gt" in bounds and "gte" not in bounds:
                if fmax <= gte:
                    return False
            elif fmax < gte:
                return False
        if lte is not None:
            if "lt" in bounds and "lte" not in bounds:
                if fmin >= lte:
                    return False
            elif fmin > lte:
                return False
    return True


def _shard_has_field(reader, field: str) -> bool:
    for v in reader.views:
        if field in v.segment.doc_values or field in v.segment.postings:
            return True
    return False


def field_stats(reader, field: str) -> Optional[Tuple[float, float]]:
    """(min, max) of a numeric/date field over live docs, cached per reader
    (the per-shard PointValues min/max the reference's can_match reads)."""
    cache: Dict[str, Optional[Tuple[float, float]]] = getattr(
        reader, "_field_stats_cache", None)
    if cache is None:
        cache = reader._field_stats_cache = {}
    if field in cache:
        return cache[field]
    fmin = fmax = None
    for v in reader.views:
        col = v.segment.doc_values.get(field)
        if col is None or col.numeric is None:
            continue
        mask = v.live & col.present
        if not mask.any():
            continue
        vals = col.numeric[mask]
        lo, hi = float(vals.min()), float(vals.max())
        fmin = lo if fmin is None else min(fmin, lo)
        fmax = hi if fmax is None else max(fmax, hi)
    result = None if fmin is None else (fmin, fmax)
    cache[field] = result
    return result


class NodeCaches:
    """Node-level cache singleton pair (the reference wires both caches into
    IndicesService and shares them across shards)."""

    def __init__(self, request_entries: int = 1024, query_entries: int = 2048):
        self.request = RequestCache(request_entries)
        self.query = QueryCache(query_entries)

    def stats(self) -> dict:
        return {"request_cache": self.request.stats(),
                "query_cache": self.query.stats()}
