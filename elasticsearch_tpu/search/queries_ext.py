"""Extended query types: geo, rank features, MLT, terms_set, nested,
parent-join, percolate, span, intervals, wrapper, pinned, distance_feature.

Reference directories: `index/query/` (geo_*, more_like_this, terms_set,
distance_feature, span_*, intervals, wrapper), `modules/percolator`,
`modules/parent-join`, `modules/mapper-extras` (rank_feature),
`x-pack/plugin/search-business-rules` (pinned).

Geo distance math runs batched in numpy over the doc-value columns — the
device analog of the per-doc Lucene loop, and the shape a Pallas kernel
takes over when candidate sets are large.
"""

from __future__ import annotations

import base64
import json
import math
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError
from elasticsearch_tpu.search.queries import (
    BoolQuery,
    DocSet,
    Query,
    SearchContext,
    _check_expensive,
    parse_query,
)

EARTH_RADIUS_M = 6371008.8


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _gather_geo(ctx: SearchContext, rows: np.ndarray,
                field: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(lat[], lon[], present[]) for the rows."""
    lat = np.zeros(len(rows))
    lon = np.zeros(len(rows))
    present = np.zeros(len(rows), dtype=bool)
    for i, row in enumerate(rows):
        v = ctx.reader.get_doc_value(field, int(row))
        if v is None:
            continue
        if isinstance(v, list) and v and isinstance(v[0], (list, tuple)):
            v = v[0]   # multi-valued: first point (reference: MultiGeoPointValues min)
        if isinstance(v, (list, tuple)) and len(v) == 2:
            lat[i], lon[i] = float(v[0]), float(v[1])
            present[i] = True
    return lat, lon, present


def haversine_m(lat1, lon1, lat2, lon2):
    """Great-circle distance in meters, vectorized (reference: Lucene
    SloppyMath.haversinMeters — exact form here; batch-friendly for MXU)."""
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lon2) - np.radians(lon1)
    a = np.sin(dp / 2) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


_DIST_UNITS = {"m": 1.0, "meters": 1.0, "km": 1000.0, "kilometers": 1000.0,
               "mi": 1609.344, "miles": 1609.344, "yd": 0.9144, "ft": 0.3048,
               "in": 0.0254, "cm": 0.01, "mm": 0.001, "nmi": 1852.0,
               "nauticalmiles": 1852.0}


def parse_distance(v: Any) -> float:
    """'12km' → meters (reference: DistanceUnit.parse)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    for unit in sorted(_DIST_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return float(s[: -len(unit)]) * _DIST_UNITS[unit]
    return float(s)


def parse_geo_point(v: Any) -> Tuple[float, float]:
    """Accepts {lat, lon}, [lon, lat], 'lat,lon' — returns (lat, lon)."""
    if isinstance(v, dict):
        return float(v["lat"]), float(v["lon"])
    if isinstance(v, (list, tuple)) and len(v) == 2:
        return float(v[1]), float(v[0])
    if isinstance(v, str):
        a, b = v.split(",")
        return float(a), float(b)
    raise ParsingError(f"failed to parse geo point [{v}]")


def _id_to_row(ctx: SearchContext) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for view in ctx.reader.views:
        seg = view.segment
        for local in range(seg.num_docs):
            if view.live[local]:
                out[seg.ids[local]] = seg.base + local
    return out


# ---------------------------------------------------------------------------
# geo queries
# ---------------------------------------------------------------------------

class GeoDistanceQuery(Query):
    def __init__(self, field: str, lat: float, lon: float, distance_m: float):
        self.field = field
        self.lat = lat
        self.lon = lon
        self.distance_m = distance_m

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        lat, lon, present = _gather_geo(ctx, rows, self.field)
        d = haversine_m(lat, lon, self.lat, self.lon)
        mask = present & (d <= self.distance_m)
        return DocSet(rows[mask], np.ones(int(mask.sum()), dtype=np.float32))

    def to_dict(self):
        return {"geo_distance": {"distance": f"{self.distance_m}m",
                                 self.field: {"lat": self.lat, "lon": self.lon}}}


class GeoBoundingBoxQuery(Query):
    def __init__(self, field: str, top: float, left: float,
                 bottom: float, right: float):
        self.field = field
        self.top, self.left, self.bottom, self.right = top, left, bottom, right

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        lat, lon, present = _gather_geo(ctx, rows, self.field)
        in_lat = (lat <= self.top) & (lat >= self.bottom)
        if self.left <= self.right:
            in_lon = (lon >= self.left) & (lon <= self.right)
        else:   # crossing the dateline
            in_lon = (lon >= self.left) | (lon <= self.right)
        mask = present & in_lat & in_lon
        return DocSet(rows[mask], np.ones(int(mask.sum()), dtype=np.float32))

    def to_dict(self):
        return {"geo_bounding_box": {self.field: {
            "top_left": {"lat": self.top, "lon": self.left},
            "bottom_right": {"lat": self.bottom, "lon": self.right}}}}


class GeoShapeQuery(Query):
    """`geo_shape` (reference: index/query/GeoShapeQueryBuilder.java).

    Executes envelope relations against the indexed shapes' bounding boxes
    (geo_shape fields store {shape, envelope} doc values — see
    GeoShapeFieldMapper). Point-typed fields also match via their position.
    """

    def __init__(self, field: str, shape: dict, relation: str = "intersects"):
        from elasticsearch_tpu.index.mapping import GeoShapeFieldMapper
        self.field = field
        self.relation = relation
        self.query_env = GeoShapeFieldMapper("_query").coerce(shape)["envelope"]

    def _relates(self, env) -> bool:
        qmin_lon, qmin_lat, qmax_lon, qmax_lat = self.query_env
        smin_lon, smin_lat, smax_lon, smax_lat = env
        if self.relation == "within":
            return (smin_lon >= qmin_lon and smax_lon <= qmax_lon
                    and smin_lat >= qmin_lat and smax_lat <= qmax_lat)
        if self.relation == "contains":
            return (smin_lon <= qmin_lon and smax_lon >= qmax_lon
                    and smin_lat <= qmin_lat and smax_lat >= qmax_lat)
        intersects = (smin_lon <= qmax_lon and smax_lon >= qmin_lon
                      and smin_lat <= qmax_lat and smax_lat >= qmin_lat)
        if self.relation == "disjoint":
            return not intersects
        return intersects

    def execute(self, ctx: SearchContext) -> DocSet:
        from elasticsearch_tpu.search.queries import scan_doc_values

        def match(shape) -> bool:
            if isinstance(shape, dict) and "envelope" in shape:
                return self._relates(tuple(shape["envelope"]))
            if isinstance(shape, tuple) and len(shape) == 2:
                lat, lon = shape  # geo_point doc value
                return self._relates((lon, lat, lon, lat))
            return False

        return scan_doc_values(
            ctx, ctx.mapper_service.resolve_field(self.field), match)

    def to_dict(self):
        return {"geo_shape": {self.field: {"relation": self.relation}}}


class GeoPolygonQuery(Query):
    def __init__(self, field: str, points: List[Tuple[float, float]]):
        self.field = field
        self.points = points    # [(lat, lon)]

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        lat, lon, present = _gather_geo(ctx, rows, self.field)
        # vectorized ray casting over the polygon edges
        inside = np.zeros(len(rows), dtype=bool)
        pts = self.points
        n = len(pts)
        for i in range(n):
            y1, x1 = pts[i]
            y2, x2 = pts[(i + 1) % n]
            cond = ((y1 > lat) != (y2 > lat))
            with np.errstate(divide="ignore", invalid="ignore"):
                xint = (x2 - x1) * (lat - y1) / (y2 - y1 + 1e-300) + x1
            inside ^= cond & (lon < xint)
        mask = present & inside
        return DocSet(rows[mask], np.ones(int(mask.sum()), dtype=np.float32))

    def to_dict(self):
        return {"geo_polygon": {self.field: {
            "points": [{"lat": a, "lon": b} for a, b in self.points]}}}


class DistanceFeatureQuery(Query):
    """Boosts by closeness to an origin: score = boost * pivot/(pivot+dist).
    Works on geo_point and date fields (reference:
    DistanceFeatureQueryBuilder)."""

    def __init__(self, field: str, origin: Any, pivot: Any, boost: float = 1.0):
        self.field = field
        self.origin = origin
        self.pivot = pivot
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        mapper = ctx.mapper_service.get(self.field)
        type_name = getattr(mapper, "type_name", None)
        if type_name == "geo_point":
            lat0, lon0 = parse_geo_point(self.origin)
            pivot_m = parse_distance(self.pivot)
            lat, lon, present = _gather_geo(ctx, rows, self.field)
            dist = haversine_m(lat, lon, lat0, lon0)
            score = self.boost * pivot_m / (pivot_m + dist)
        else:
            from elasticsearch_tpu.common.settings import parse_time_value
            from elasticsearch_tpu.index.mapping import (
                parse_date_millis, parse_date_nanos)
            if type_name == "date_nanos":
                # nanosecond storage: keep origin/pivot in the field's unit
                origin_ms = float(parse_date_nanos(self.origin))
                pivot_ms = parse_time_value(self.pivot, "pivot") * 1e9
            else:
                origin_ms = parse_date_millis(self.origin)
                pivot_ms = parse_time_value(self.pivot, "pivot") * 1000.0
            vals = np.zeros(len(rows))
            present = np.zeros(len(rows), dtype=bool)
            for i, row in enumerate(rows):
                v = ctx.reader.get_doc_value(self.field, int(row))
                if v is None:
                    continue
                if isinstance(v, list):
                    v = v[0] if v else None
                    if v is None:
                        continue
                vals[i] = float(v)
                present[i] = True
            dist = np.abs(vals - origin_ms)
            score = self.boost * pivot_ms / (pivot_ms + dist)
        mask = present
        return DocSet(rows[mask], score[mask].astype(np.float32))

    def to_dict(self):
        return {"distance_feature": {"field": self.field,
                                     "origin": self.origin, "pivot": self.pivot}}


# ---------------------------------------------------------------------------
# rank features
# ---------------------------------------------------------------------------

class RankFeatureQuery(Query):
    def __init__(self, field: str, saturation: Optional[dict] = None,
                 log: Optional[dict] = None, sigmoid: Optional[dict] = None,
                 linear: Optional[dict] = None, boost: float = 1.0):
        self.field = field
        self.saturation = saturation
        self.log = log
        self.sigmoid = sigmoid
        self.linear = linear
        self.boost = boost

    def _feature_values(self, ctx: SearchContext,
                        rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        vals = np.zeros(len(rows))
        present = np.zeros(len(rows), dtype=bool)
        root, _, feature = self.field.partition(".")
        mapper = ctx.mapper_service.get(root)
        use_features_map = (feature and mapper is not None and
                            getattr(mapper, "type_name", "") == "rank_features")
        lookup_field = root if use_features_map else self.field
        for i, row in enumerate(rows):
            v = ctx.reader.get_doc_value(lookup_field, int(row))
            if use_features_map and isinstance(v, dict):
                v = v.get(feature)
            if isinstance(v, list):
                v = v[0] if v else None
            if v is None:
                continue
            vals[i] = float(v)
            present[i] = True
        return vals, present

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        vals, present = self._feature_values(ctx, rows)
        rows = rows[present]
        v = vals[present]
        if self.log is not None:
            score = np.log(float(self.log.get("scaling_factor", 1.0)) + v)
        elif self.sigmoid is not None:
            k = float(self.sigmoid["pivot"])
            a = float(self.sigmoid["exponent"])
            score = v ** a / (k ** a + v ** a)
        elif self.linear is not None:
            score = v
        else:
            pivot = float((self.saturation or {}).get(
                "pivot", max(float(np.mean(v)) if len(v) else 1.0, 1e-9)))
            score = v / (v + pivot)
        return DocSet(rows, (self.boost * score).astype(np.float32))

    def to_dict(self):
        return {"rank_feature": {"field": self.field}}


# ---------------------------------------------------------------------------
# learned-sparse / late-interaction (host reference walkers)
# ---------------------------------------------------------------------------

class WeightedTokensQuery(Query):
    """`sparse_vector` / `weighted_tokens` (reference: x-pack ml
    WeightedTokensQueryBuilder): score = sum over overlapping tokens of
    stored_weight * query_weight * boost — the learned-sparse dot
    product over `rank_features` doc values.

    This walker is the byte-parity ORACLE for the device leg
    (`ops/sparse.py`): accumulation is f32, FEATURE-major in the query
    dict's iteration order — exactly the device kernel's term-major
    scan order, where each (feature, doc) posting lands in one tile —
    so per-doc f32 sums fold in the same order and the scores (and
    their ties, broken by ascending row downstream) are bit-identical
    to the `sparse.topk` board."""

    def __init__(self, field: str, tokens: Dict[str, float],
                 boost: float = 1.0):
        self.field = field
        self.tokens = {str(k): float(v) for k, v in tokens.items()}
        self.boost = float(boost)

    def execute(self, ctx: SearchContext) -> DocSet:
        rows: List[int] = []
        vals: List[Any] = []
        for view in ctx.reader.views:
            seg = view.segment
            col = seg.doc_values.get(self.field)
            for loc in np.nonzero(view.live)[0]:
                v = col.values[int(loc)] if col is not None else None
                if isinstance(v, dict):
                    rows.append(seg.base + int(loc))
                    vals.append(v)
        if not rows:
            return DocSet(np.zeros(0, dtype=np.int64),
                          np.zeros(0, dtype=np.float32))
        wanted = set(self.tokens)
        postings: Dict[str, Tuple[List[int], List[float]]] = {}
        for i, v in enumerate(vals):
            for feat, w in v.items():
                if feat in wanted:
                    lists = postings.get(feat)
                    if lists is None:
                        lists = postings[feat] = ([], [])
                    lists[0].append(i)
                    lists[1].append(w)
        scores = np.zeros(len(rows), dtype=np.float32)
        counts = np.zeros(len(rows), dtype=np.int64)
        for t, w in self.tokens.items():        # query dict order
            lists = postings.get(t)
            if lists is None:
                continue
            b = np.float32(np.float32(w) * np.float32(self.boost))
            idx = np.asarray(lists[0], dtype=np.int64)
            scores[idx] += np.asarray(lists[1], dtype=np.float32) * b
            counts[idx] += 1
        keep = counts > 0
        return DocSet(np.asarray(rows, dtype=np.int64)[keep], scores[keep])

    def to_dict(self):
        return {"sparse_vector": {"field": self.field,
                                  "query_vector": dict(self.tokens)}}


class LateInteractionQuery(Query):
    """`late_interaction`: exact MaxSim over `rank_vectors` doc values —
    score = sum over query tokens of max over doc tokens of their dot
    product (cosine similarity normalizes both sides per token, per the
    field mapping).

    This walker IS the exact oracle the fused device leg
    (`ops/pallas_maxsim.py`) is recall-gated against: it reads the raw
    f32 stored token vectors (no quantization) and prunes nothing (no
    coarse centroid phase), in f32 numpy."""

    def __init__(self, field: str, query_tokens, boost: float = 1.0):
        self.field = field
        q = np.asarray(query_tokens, dtype=np.float32)
        if q.ndim == 1:
            q = q.reshape(1, -1)
        if q.ndim != 2 or not q.size:
            raise ParsingError(
                "[late_interaction] query_tokens must be a non-empty "
                "array of vectors")
        self.query_tokens = q
        self.boost = float(boost)

    def execute(self, ctx: SearchContext) -> DocSet:
        mapper = ctx.mapper_service.get(self.field)
        cosine = getattr(mapper, "similarity", "cosine") == "cosine"
        q = self.query_tokens
        if cosine:
            q = q / np.maximum(
                np.linalg.norm(q, axis=-1, keepdims=True), 1e-30)
        rows: List[int] = []
        scores: List[float] = []
        for view in ctx.reader.views:
            seg = view.segment
            col = seg.doc_values.get(self.field)
            if col is None:
                continue
            for loc in np.nonzero(view.live)[0]:
                v = col.values[int(loc)]
                if v is None:
                    continue
                # multi-valued doc values land as a list of per-token
                # rows; coerce exactly like the columnar extractor does
                t = np.asarray(v, dtype=np.float32).reshape(
                    -1, int(getattr(mapper, "dims", 0)) or
                    np.shape(v)[-1])
                if not t.size:
                    continue
                if cosine:
                    t = t / np.maximum(
                        np.linalg.norm(t, axis=-1, keepdims=True), 1e-30)
                dots = q @ t.T                           # [Tq, Td] f32
                rows.append(seg.base + int(loc))
                scores.append(float(dots.max(axis=1).sum()) * self.boost)
        return DocSet(np.asarray(rows, dtype=np.int64),
                      np.asarray(scores, dtype=np.float32))

    def to_dict(self):
        return {"late_interaction": {
            "field": self.field,
            "query_tokens": self.query_tokens.tolist()}}


# ---------------------------------------------------------------------------
# more_like_this
# ---------------------------------------------------------------------------

class MoreLikeThisQuery(Query):
    def __init__(self, fields: List[str], like: List[Any],
                 min_term_freq: int = 2, min_doc_freq: int = 5,
                 max_query_terms: int = 25,
                 minimum_should_match: Any = "30%",
                 include: bool = False, unlike: Optional[List[Any]] = None):
        self.fields = fields
        self.like = like
        self.unlike = unlike or []
        self.min_term_freq = min_term_freq
        self.min_doc_freq = min_doc_freq
        self.max_query_terms = max_query_terms
        self.minimum_should_match = minimum_should_match
        self.include = include

    def execute(self, ctx: SearchContext) -> DocSet:
        from elasticsearch_tpu.search.queries import MatchNoneQuery, TermQuery
        id_rows = _id_to_row(ctx)
        # unspecified fields default to every analyzed text field
        # (MoreLikeThisQueryBuilder: "all fields" when none given)
        fields = self.fields or [
            p for p, m in ctx.mapper_service.all_mappers()
            if getattr(m, "type_name", None) == "text"]
        liked_rows: List[int] = []
        term_freqs: Dict[Tuple[str, str], int] = {}
        for like in self.like:
            if isinstance(like, str):
                texts = {f: like for f in fields}
            elif isinstance(like, dict) and "_id" in like:
                row = id_rows.get(str(like["_id"]))
                if row is None:
                    continue
                liked_rows.append(row)
                texts = {}
                for f in fields:
                    src = self._source_of(ctx, row)
                    v = src.get(f) if src else None
                    if isinstance(v, str):
                        texts[f] = v
            elif isinstance(like, dict) and "doc" in like:
                texts = {f: like["doc"].get(f) for f in fields
                         if isinstance(like["doc"].get(f), str)}
            else:
                continue
            for f, text in texts.items():
                if not text:
                    continue
                mapper = ctx.mapper_service.get(f)
                tokens = (mapper.analyze(text)
                          if hasattr(mapper, "analyze") else text.lower().split())
                for t in tokens:
                    term_freqs[(f, t)] = term_freqs.get((f, t), 0) + 1
        # unlike docs/texts REMOVE their terms from the candidate set
        # (MoreLikeThisQueryBuilder#unlike)
        unlike_terms: set = set()
        for unl in self.unlike:
            texts = {}
            if isinstance(unl, str):
                texts = {f: unl for f in fields}
            elif isinstance(unl, dict) and "_id" in unl:
                row = id_rows.get(str(unl["_id"]))
                if row is not None:
                    src_doc = self._source_of(ctx, row)
                    texts = {f: src_doc.get(f) for f in fields
                             if src_doc
                             and isinstance(src_doc.get(f), str)}
            elif isinstance(unl, dict) and "doc" in unl:
                texts = {f: unl["doc"].get(f) for f in fields
                         if isinstance(unl["doc"].get(f), str)}
            for f, text in texts.items():
                if not text:
                    continue
                mapper = ctx.mapper_service.get(f)
                tokens = (mapper.analyze(text) if hasattr(mapper, "analyze")
                          else text.lower().split())
                unlike_terms.update((f, t) for t in tokens)
        for key in unlike_terms:
            term_freqs.pop(key, None)

        # select interesting terms by tf·idf (reference: MoreLikeThis.java)
        n_docs = max(ctx.reader.num_docs, 1)
        scored_terms = []
        for (f, t), tf in term_freqs.items():
            if tf < self.min_term_freq:
                continue
            df = ctx.reader.doc_freq(f, t)
            if df < self.min_doc_freq:
                continue
            idf = math.log(1 + (n_docs - df + 0.5) / (df + 0.5))
            scored_terms.append((tf * idf, f, t))
        scored_terms.sort(reverse=True)
        scored_terms = scored_terms[: self.max_query_terms]
        if not scored_terms:
            return DocSet.empty()
        should = [TermQuery(f, t) for _, f, t in scored_terms]
        inner = BoolQuery(must=[], filter=[], should=should, must_not=[],
                          minimum_should_match=self.minimum_should_match)
        result = inner.execute(ctx)
        if not self.include and liked_rows:
            mask = ~np.isin(result.rows, np.asarray(liked_rows, dtype=np.int64))
            result = DocSet(result.rows[mask],
                            None if result.scores is None
                            else result.scores[mask])
        return result

    @staticmethod
    def _source_of(ctx: SearchContext, row: int) -> Optional[dict]:
        for view in ctx.reader.views:
            seg = view.segment
            if seg.base <= row < seg.base + seg.num_docs:
                return seg.sources[row - seg.base]
        return None

    def to_dict(self):
        return {"more_like_this": {"fields": self.fields, "like": self.like}}


# ---------------------------------------------------------------------------
# terms_set
# ---------------------------------------------------------------------------

class TermsSetQuery(Query):
    def __init__(self, field: str, terms: List[Any],
                 minimum_should_match_field: Optional[str] = None,
                 minimum_should_match_script: Optional[dict] = None):
        self.field = field
        self.terms = terms
        self.msm_field = minimum_should_match_field
        self.msm_script = minimum_should_match_script

    def execute(self, ctx: SearchContext) -> DocSet:
        from elasticsearch_tpu.search.queries import TermQuery
        match_counts: Dict[int, int] = {}
        score_sum: Dict[int, float] = {}
        for term in self.terms:
            ds = TermQuery(self.field, term).execute(ctx).with_scores()
            for row, sc in zip(ds.rows, ds.scores):
                match_counts[int(row)] = match_counts.get(int(row), 0) + 1
                score_sum[int(row)] = score_sum.get(int(row), 0.0) + float(sc)
        if not match_counts:
            return DocSet.empty()
        rows = np.asarray(sorted(match_counts), dtype=np.int64)
        required = np.ones(len(rows))
        if self.msm_field:
            for i, row in enumerate(rows):
                v = ctx.reader.get_doc_value(self.msm_field, int(row))
                if isinstance(v, list):
                    v = v[0] if v else None
                required[i] = float(v) if v is not None else len(self.terms) + 1
        elif self.msm_script:
            src = self.msm_script.get("source", "")
            env = {"num_terms": len(self.terms)}
            try:
                required[:] = eval(compile(src.replace("params.num_terms",
                                                       "num_terms"),
                                           "<msm>", "eval"),
                                   {"__builtins__": {}},
                                   {"num_terms": len(self.terms),
                                    "Math": math, "min": min, "max": max})
            except Exception as e:
                raise IllegalArgumentError(
                    f"failed to evaluate minimum_should_match_script: {e}")
        counts = np.asarray([match_counts[int(r)] for r in rows])
        mask = counts >= required
        rows = rows[mask]
        scores = np.asarray([score_sum[int(r)] for r in rows], dtype=np.float32)
        return DocSet(rows, scores)

    def to_dict(self):
        return {"terms_set": {self.field: {"terms": self.terms}}}


# ---------------------------------------------------------------------------
# source-level matcher (shared by nested + percolate)
# ---------------------------------------------------------------------------

def _values_at(obj: Any, path: str) -> List[Any]:
    """All values at a dotted path inside a plain source object."""
    parts = path.split(".")
    current = [obj]
    for p in parts:
        nxt: List[Any] = []
        for c in current:
            if isinstance(c, dict) and p in c:
                v = c[p]
                if isinstance(v, list):
                    nxt.extend(v)
                else:
                    nxt.append(v)
        current = nxt
    return current


def source_matches(query: dict, source: dict, mapper_service=None) -> bool:
    """Evaluate a query DSL dict directly against one source document.

    The percolator's `MemoryIndex` analog (reference:
    percolator/PercolateQuery.java builds a one-doc in-memory index); nested
    queries reuse it per nested object.
    """
    if not isinstance(query, dict) or len(query) != 1:
        raise ParsingError("query must have exactly one key")
    kind, spec = next(iter(query.items()))
    if kind == "match_all":
        return True
    if kind == "match_none":
        return False
    if kind == "bool":
        for q in _as_list(spec.get("must")) + _as_list(spec.get("filter")):
            if not source_matches(q, source, mapper_service):
                return False
        for q in _as_list(spec.get("must_not")):
            if source_matches(q, source, mapper_service):
                return False
        should = _as_list(spec.get("should"))
        if should:
            msm = spec.get("minimum_should_match")
            need = int(msm) if msm is not None else (
                1 if not (spec.get("must") or spec.get("filter")) else 0)
            got = sum(1 for q in should
                      if source_matches(q, source, mapper_service))
            return got >= need
        return True
    if kind == "term":
        field, v = _single(spec)
        target = v.get("value") if isinstance(v, dict) else v
        return any(_term_eq(val, target, field, mapper_service)
                   for val in _values_at(source, field))
    if kind == "terms":
        field, targets = _single(spec)
        return any(_term_eq(val, t, field, mapper_service)
                   for val in _values_at(source, field) for t in targets)
    if kind == "match":
        field, v = _single(spec)
        text = v.get("query") if isinstance(v, dict) else v
        operator = (v.get("operator", "or") if isinstance(v, dict) else "or")
        tokens = _analyze(field, text, mapper_service)
        doc_tokens: set = set()
        for val in _values_at(source, field):
            if isinstance(val, str):
                doc_tokens.update(_analyze(field, val, mapper_service))
            else:
                doc_tokens.add(str(val).lower())
        hits = [t in doc_tokens for t in tokens]
        return all(hits) if operator == "and" else any(hits)
    if kind == "range":
        field, v = _single(spec)
        from elasticsearch_tpu.index.mapping import parse_date_millis
        for val in _values_at(source, field):
            try:
                x = float(val) if not isinstance(val, str) else (
                    float(val) if val.replace(".", "").replace("-", "").isdigit()
                    else parse_date_millis(val))
            except Exception:
                continue

            def conv(bound):
                if isinstance(bound, str) and not bound.replace(
                        ".", "").replace("-", "").isdigit():
                    return parse_date_millis(bound)
                return float(bound)
            ok = True
            if v.get("gte") is not None and not x >= conv(v["gte"]):
                ok = False
            if v.get("gt") is not None and not x > conv(v["gt"]):
                ok = False
            if v.get("lte") is not None and not x <= conv(v["lte"]):
                ok = False
            if v.get("lt") is not None and not x < conv(v["lt"]):
                ok = False
            if ok:
                return True
        return False
    if kind == "exists":
        return len(_values_at(source, spec["field"])) > 0
    if kind == "prefix":
        field, v = _single(spec)
        p = (v.get("value") if isinstance(v, dict) else v) or ""
        return any(isinstance(val, str) and val.lower().startswith(p.lower())
                   for val in _values_at(source, field))
    if kind == "wildcard":
        import fnmatch
        field, v = _single(spec)
        pat = (v.get("value") or v.get("wildcard")) if isinstance(v, dict) else v
        return any(isinstance(val, str) and
                   fnmatch.fnmatchcase(val.lower(), pat.lower())
                   for val in _values_at(source, field))
    if kind == "ids":
        return False   # no _id inside a bare source document
    raise ParsingError(
        f"[{kind}] query is not supported in this context (percolate/nested)")


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _single(spec):
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingError("expected a single-field query object")
    return next(iter(spec.items()))


def _analyze(field: str, text: str, mapper_service) -> List[str]:
    mapper = mapper_service.get(field) if mapper_service else None
    if mapper is not None and hasattr(mapper, "analyze"):
        return mapper.analyze(text)
    return str(text).lower().split()


def _term_eq(doc_val, target, field, mapper_service) -> bool:
    if isinstance(doc_val, str) and isinstance(target, str):
        mapper = mapper_service.get(field) if mapper_service else None
        if mapper is not None and getattr(mapper, "type_name", "") == "text":
            return target in _analyze(field, doc_val, mapper_service)
        return doc_val == target
    if isinstance(doc_val, bool) or isinstance(target, bool):
        return doc_val == target
    try:
        return float(doc_val) == float(target)
    except (TypeError, ValueError):
        return doc_val == target


# ---------------------------------------------------------------------------
# nested
# ---------------------------------------------------------------------------

class NestedQuery(Query):
    """Matches docs where at least one nested object at `path` satisfies the
    whole inner query (reference: nested docs are hidden sub-documents with
    a BitSet join — here objects evaluate in place, same semantics)."""

    def __init__(self, path: str, query_dict: dict, score_mode: str = "avg"):
        self.path = path
        self.query_dict = query_dict
        self.score_mode = score_mode

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "joining")
        rows_out: List[int] = []
        for view in ctx.reader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                objs = seg.sources[local].get(self.path)
                if objs is None and "." in self.path:
                    vals = _values_at(seg.sources[local], self.path)
                    objs = [v for v in vals if isinstance(v, dict)]
                if not isinstance(objs, list):
                    objs = [objs] if isinstance(objs, dict) else []
                inner = _strip_path_prefix(self.query_dict, self.path)
                if any(source_matches(inner, obj, ctx.mapper_service)
                       for obj in objs if isinstance(obj, dict)):
                    rows_out.append(seg.base + local)
        rows = np.asarray(sorted(rows_out), dtype=np.int64)
        return DocSet(rows, np.ones(len(rows), dtype=np.float32))

    def to_dict(self):
        return {"nested": {"path": self.path, "query": self.query_dict}}


def _strip_path_prefix(query: dict, path: str) -> dict:
    """Rewrite `path.field` references to `field` for per-object matching."""
    out: Any = json.loads(json.dumps(query))
    prefix = path + "."

    def walk(node):
        if isinstance(node, dict):
            for k in list(node):
                v = node.pop(k)
                nk = k[len(prefix):] if k.startswith(prefix) else k
                node[nk] = walk(v)
            return node
        if isinstance(node, list):
            return [walk(x) for x in node]
        return node
    return walk(out)


# ---------------------------------------------------------------------------
# parent-join
# ---------------------------------------------------------------------------

def _join_mapper(ctx: SearchContext):
    for name, mapper in ctx.mapper_service.all_mappers():
        if getattr(mapper, "type_name", "") == "join":
            return name, mapper
    raise IllegalArgumentError("no [join] field defined in the mapping")


class HasChildQuery(Query):
    def __init__(self, child_type: str, query: Query, score_mode: str = "none"):
        self.child_type = child_type
        self.query = query
        self.score_mode = score_mode

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "joining")
        join_field, _ = _join_mapper(ctx)
        child_hits = self.query.execute(ctx)
        id_rows = _id_to_row(ctx)
        parent_rows = set()
        for row in child_hits.rows:
            jv = ctx.reader.get_doc_value(join_field, int(row))
            if isinstance(jv, list):
                jv = jv[0] if jv else None
            if not isinstance(jv, dict) or jv.get("name") != self.child_type:
                continue
            parent_id = jv.get("parent")
            if parent_id is not None and parent_id in id_rows:
                parent_rows.add(id_rows[parent_id])
        rows = np.asarray(sorted(parent_rows), dtype=np.int64)
        return DocSet(rows, np.ones(len(rows), dtype=np.float32))

    def to_dict(self):
        return {"has_child": {"type": self.child_type,
                              "query": self.query.to_dict()}}


class HasParentQuery(Query):
    def __init__(self, parent_type: str, query: Query, score: bool = False):
        self.parent_type = parent_type
        self.query = query
        self.score = score

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "joining")
        join_field, _ = _join_mapper(ctx)
        parent_hits = self.query.execute(ctx)
        # restrict to parents of the right relation name
        parent_ids = set()
        for row in parent_hits.rows:
            jv = ctx.reader.get_doc_value(join_field, int(row))
            if isinstance(jv, list):
                jv = jv[0] if jv else None
            if isinstance(jv, dict) and jv.get("name") == self.parent_type:
                for view in ctx.reader.views:
                    seg = view.segment
                    if seg.base <= row < seg.base + seg.num_docs:
                        parent_ids.add(seg.ids[row - seg.base])
        rows_out = []
        for view in ctx.reader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                jv = ctx.reader.get_doc_value(join_field, seg.base + local)
                if isinstance(jv, list):
                    jv = jv[0] if jv else None
                if isinstance(jv, dict) and jv.get("parent") in parent_ids:
                    rows_out.append(seg.base + local)
        rows = np.asarray(sorted(rows_out), dtype=np.int64)
        return DocSet(rows, np.ones(len(rows), dtype=np.float32))

    def to_dict(self):
        return {"has_parent": {"parent_type": self.parent_type,
                               "query": self.query.to_dict()}}


class ParentIdQuery(Query):
    def __init__(self, child_type: str, parent_id: str):
        self.child_type = child_type
        self.parent_id = parent_id

    def execute(self, ctx: SearchContext) -> DocSet:
        join_field, _ = _join_mapper(ctx)
        rows_out = []
        for view in ctx.reader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                jv = ctx.reader.get_doc_value(join_field, seg.base + local)
                if isinstance(jv, list):
                    jv = jv[0] if jv else None
                if isinstance(jv, dict) and jv.get("name") == self.child_type \
                        and jv.get("parent") == self.parent_id:
                    rows_out.append(seg.base + local)
        rows = np.asarray(sorted(rows_out), dtype=np.int64)
        return DocSet(rows, np.ones(len(rows), dtype=np.float32))

    def to_dict(self):
        return {"parent_id": {"type": self.child_type, "id": self.parent_id}}


# ---------------------------------------------------------------------------
# percolate
# ---------------------------------------------------------------------------

class PercolateQuery(Query):
    def __init__(self, field: str, documents: List[dict]):
        self.field = field
        self.documents = documents

    def execute(self, ctx: SearchContext) -> DocSet:
        rows_out = []
        for view in ctx.reader.views:
            seg = view.segment
            for local in range(seg.num_docs):
                if not view.live[local]:
                    continue
                stored = ctx.reader.get_doc_value(self.field, seg.base + local)
                if isinstance(stored, list):
                    stored = stored[0] if stored else None
                if not isinstance(stored, dict):
                    continue
                try:
                    if any(source_matches(stored, doc, ctx.mapper_service)
                           for doc in self.documents):
                        rows_out.append(seg.base + local)
                except ParsingError:
                    continue   # stored query uses unsupported constructs
        rows = np.asarray(sorted(rows_out), dtype=np.int64)
        return DocSet(rows, np.ones(len(rows), dtype=np.float32))

    def to_dict(self):
        return {"percolate": {"field": self.field, "documents": self.documents}}


# ---------------------------------------------------------------------------
# span + intervals (position machinery)
# ---------------------------------------------------------------------------

def _term_spans(ctx: SearchContext, field: str,
                term: str) -> Dict[int, List[Tuple[int, int]]]:
    """global row → [(start, end)) spans] for one term."""
    out: Dict[int, List[Tuple[int, int]]] = {}
    for view in ctx.reader.views:
        seg = view.segment
        postings = seg.get_postings(field, term)
        if postings is None or postings.positions is None:
            continue
        for i, local in enumerate(postings.doc_ids):
            if not view.live[local]:
                continue
            poss = postings.positions[i]
            if poss:
                out[seg.base + int(local)] = [(p, p + 1) for p in poss]
    return out


def _combine_near(a: Dict[int, List[Tuple[int, int]]],
                  b: Dict[int, List[Tuple[int, int]]],
                  slop: int, in_order: bool) -> Dict[int, List[Tuple[int, int]]]:
    out: Dict[int, List[Tuple[int, int]]] = {}
    for row in set(a) & set(b):
        spans = []
        for s1, e1 in a[row]:
            for s2, e2 in b[row]:
                if in_order:
                    if s2 >= e1 and s2 - e1 <= slop:
                        spans.append((s1, e2))
                else:
                    lo, hi = min(s1, s2), max(e1, e2)
                    gap = hi - lo - (e1 - s1) - (e2 - s2)
                    if gap <= slop and not (s1 < e2 and s2 < e1):
                        spans.append((lo, hi))
                    elif (s1 < e2 and s2 < e1):
                        pass   # overlapping spans don't pair (Lucene semantics)
        if spans:
            out[row] = sorted(set(spans))
    return out


class SpanQuery(Query):
    """Evaluates the span tree to row→spans, then matches docs with ≥1 span."""

    def __init__(self, spec_kind: str, spec: dict):
        self.kind = spec_kind
        self.spec = spec

    def _spans(self, ctx: SearchContext, kind: str,
               spec: dict) -> Dict[int, List[Tuple[int, int]]]:
        if kind == "span_term":
            field, v = _single(spec)
            term = v.get("value") if isinstance(v, dict) else v
            mapper = ctx.mapper_service.get(field)
            if mapper is not None and hasattr(mapper, "analyze"):
                toks = mapper.analyze(str(term))
                term = toks[0] if toks else str(term)
            return _term_spans(ctx, field, str(term))
        if kind == "span_near":
            clauses = spec.get("clauses", [])
            slop = int(spec.get("slop", 0))
            in_order = bool(spec.get("in_order", True))
            if not clauses:
                return {}
            acc = self._spans_of(ctx, clauses[0])
            for c in clauses[1:]:
                acc = _combine_near(acc, self._spans_of(ctx, c), slop, in_order)
            return acc
        if kind == "span_or":
            out: Dict[int, List[Tuple[int, int]]] = {}
            for c in spec.get("clauses", []):
                for row, spans in self._spans_of(ctx, c).items():
                    out.setdefault(row, []).extend(spans)
            return {r: sorted(set(s)) for r, s in out.items()}
        if kind == "span_first":
            inner = self._spans_of(ctx, spec["match"])
            end = int(spec.get("end", 1))
            return {r: [sp for sp in spans if sp[1] <= end]
                    for r, spans in inner.items()
                    if any(sp[1] <= end for sp in spans)}
        if kind == "span_not":
            include = self._spans_of(ctx, spec["include"])
            exclude = self._spans_of(ctx, spec["exclude"])
            out = {}
            for row, spans in include.items():
                ex = exclude.get(row, [])
                keep = [sp for sp in spans
                        if not any(sp[0] < e and s < sp[1] for s, e in ex)]
                if keep:
                    out[row] = keep
            return out
        if kind == "span_multi":
            # SpanMultiTermQueryWrapper: expand the multi-term query into
            # matching terms, union their spans
            inner = spec.get("match") or {}
            ((ikind, ispec),) = list(inner.items())[:1] if inner else ((
                "match_all", {}),)
            if ikind in ("prefix", "wildcard"):
                field, v = _single(ispec)
                pat = v.get("value", v.get("prefix", v.get("wildcard"))) \
                    if isinstance(v, dict) else v
                pat = str(pat).lower()
                import fnmatch as _fn
                if ikind == "prefix":
                    pred = lambda t: str(t).startswith(pat)  # noqa: E731
                else:
                    pred = lambda t: _fn.fnmatch(str(t), pat)  # noqa: E731
                out: Dict[int, List[Tuple[int, int]]] = {}
                terms = set()
                for view in ctx.reader.views:
                    terms.update(t for t in view.segment.terms_of(field)
                                 if pred(t))
                for t in terms:
                    for row, spans in _term_spans(ctx, field, t).items():
                        out.setdefault(row, []).extend(spans)
                return {r: sorted(set(s)) for r, s in out.items()}
            raise ParsingError(
                f"unsupported span_multi inner query [{ikind}]")
        raise ParsingError(f"unknown span query [{kind}]")

    def _spans_of(self, ctx, clause: dict):
        k, s = next(iter(clause.items()))
        return self._spans(ctx, k, s)

    def execute(self, ctx: SearchContext) -> DocSet:
        span_map = self._spans(ctx, self.kind, self.spec)
        rows = np.asarray(sorted(span_map), dtype=np.int64)
        scores = np.asarray([float(len(span_map[int(r)])) for r in rows],
                            dtype=np.float32)
        return DocSet(rows, scores)

    def to_dict(self):
        return {self.kind: self.spec}


class IntervalsQuery(Query):
    """`intervals` query over the span machinery: match (ordered/max_gaps),
    all_of/any_of combinators, prefix/wildcard/fuzzy term expansion, and
    IntervalFilter rules (containing / not_containing / contained_by /
    not_contained_by / overlapping / not_overlapping / before / after) at
    any nesting level (reference: `index/query/IntervalsSourceProvider`)."""

    def __init__(self, field: str, rule: dict):
        self.field = field
        self.rule = rule

    # ------------------------------------------------------------- evaluation
    # internal spans carry (start, end, covered) — `covered` is the token
    # mass inside the span, so total-gaps = (end-start) - covered can bound
    # the WHOLE combination the way Lucene's Intervals.maxgaps does, not
    # each adjacent pair
    def _analyzed_terms(self, ctx, text: str) -> List[str]:
        mapper = ctx.mapper_service.get(self.field)
        if mapper is not None and hasattr(mapper, "search_analyzer"):
            return mapper.search_analyzer.terms(str(text))
        if mapper is not None and hasattr(mapper, "analyze"):
            return mapper.analyze(str(text))
        return str(text).lower().split()

    def _term_spans3(self, ctx, term: str):
        return {row: [(s, e, e - s) for s, e in spans]
                for row, spans in _term_spans(ctx, self.field, term).items()}

    def _union_terms(self, ctx, terms):
        out: Dict[int, list] = {}
        for t in terms:
            for row, spans in self._term_spans3(ctx, t).items():
                out.setdefault(row, []).extend(spans)
        return {r: sorted(set(s)) for r, s in out.items()}

    @staticmethod
    def _near3(a, b, max_gaps: int, ordered: bool):
        """Pair spans with a TOTAL internal-gap budget."""
        out: Dict[int, list] = {}
        bound = max_gaps if max_gaps >= 0 else 10 ** 9
        for row in set(a) & set(b):
            spans = []
            for s1, e1, c1 in a[row]:
                for s2, e2, c2 in b[row]:
                    if s1 < e2 and s2 < e1:
                        continue  # overlapping spans don't pair
                    if ordered and s2 < e1:
                        continue
                    lo, hi = min(s1, s2), max(e1, e2)
                    covered = c1 + c2
                    if (hi - lo) - covered <= bound:
                        spans.append((lo, hi, covered))
            if spans:
                out[row] = sorted(set(spans))
        return out

    def _spans_for(self, ctx, rule: dict) -> Dict[int, list]:
        from elasticsearch_tpu.search.queries import (
            _edit_distance_le as _ed_le, _pattern_terms,
        )
        kind, spec = next(iter(rule.items()))
        spec = spec if isinstance(spec, dict) else {"query": spec}
        filt = spec.get("filter")
        if kind == "match":
            terms = self._analyzed_terms(ctx, spec.get("query", ""))
            ordered = bool(spec.get("ordered", False))
            max_gaps = int(spec.get("max_gaps", -1))
            spans = self._term_spans3(ctx, terms[0]) if terms else {}
            for t in terms[1:]:
                spans = self._near3(spans, self._term_spans3(ctx, t),
                                    max_gaps, ordered)
        elif kind == "all_of":
            children = [self._spans_for(ctx, r)
                        for r in spec.get("intervals", [])]
            max_gaps = int(spec.get("max_gaps", -1))
            ordered = bool(spec.get("ordered", False))
            spans = children[0] if children else {}
            for child in children[1:]:
                spans = self._near3(spans, child, max_gaps, ordered)
        elif kind == "any_of":
            spans = {}
            for r in spec.get("intervals", []):
                for row, ss in self._spans_for(ctx, r).items():
                    spans.setdefault(row, []).extend(ss)
            spans = {r: sorted(set(s)) for r, s in spans.items()}
        elif kind == "prefix":
            p = str(spec.get("prefix", spec.get("query", ""))).lower()
            spans = self._union_terms(
                ctx, _pattern_terms(ctx, self.field,
                                    lambda t: t.startswith(p)))
        elif kind == "wildcard":
            # ES wildcard: only * and ? are special — NOT fnmatch classes
            pat = str(spec.get("pattern", spec.get("query", ""))).lower()
            rx = re.compile("^" + re.escape(pat).replace(r"\*", ".*")
                            .replace(r"\?", ".") + "$")
            spans = self._union_terms(
                ctx, _pattern_terms(ctx, self.field,
                                    lambda t: rx.match(t) is not None))
        elif kind == "fuzzy":
            term = str(spec.get("term", spec.get("query", ""))).lower()
            fuzz = spec.get("fuzziness", "auto")
            if str(fuzz).lower() == "auto":
                max_ed = 0 if len(term) < 3 else (1 if len(term) < 6 else 2)
            else:
                max_ed = int(fuzz)
            spans = self._union_terms(
                ctx, _pattern_terms(ctx, self.field,
                                    lambda t: _ed_le(term, t, max_ed)))
        else:
            raise ParsingError(f"unsupported intervals rule [{kind}]")
        if filt:
            spans = self._apply_filter(ctx, spans, filt)
        return spans

    def _apply_filter(self, ctx, spans, filt: dict):
        if not isinstance(filt, dict) or len(filt) != 1:
            raise ParsingError(
                "intervals [filter] must define exactly one rule")
        ((mode, inner_rule),) = filt.items()
        fspans = self._spans_for(ctx, inner_rule)
        out = {}
        for row, ss in spans.items():
            fs = fspans.get(row, [])

            def containing(sp):
                return any(sp[0] <= s and e <= sp[1] for s, e, _ in fs)

            def contained_by(sp):
                return any(s <= sp[0] and sp[1] <= e for s, e, _ in fs)

            def overlapping(sp):
                return any(sp[0] < e and s < sp[1] for s, e, _ in fs)

            def before(sp):
                return any(sp[1] <= s for s, e, _ in fs)

            def after(sp):
                return any(sp[0] >= e for s, e, _ in fs)

            preds = {"containing": containing,
                     "not_containing": lambda sp: not containing(sp),
                     "contained_by": contained_by,
                     "not_contained_by": lambda sp: not contained_by(sp),
                     "overlapping": overlapping,
                     "not_overlapping": lambda sp: not overlapping(sp),
                     "before": before, "after": after}
            pred = preds.get(mode)
            if pred is None:
                raise ParsingError(f"unknown intervals filter [{mode}]")
            keep = [sp for sp in ss if pred(sp)]
            if keep:
                out[row] = keep
        return out

    def execute(self, ctx: SearchContext) -> DocSet:
        span_map = self._spans_for(ctx, self.rule)
        rows = np.asarray(sorted(span_map), dtype=np.int64)
        scores = np.asarray([float(len(span_map[int(r)])) for r in rows],
                            dtype=np.float32)
        return DocSet(rows, scores)

    def to_dict(self):
        return {"intervals": {self.field: self.rule}}


# ---------------------------------------------------------------------------
# wrapper + pinned
# ---------------------------------------------------------------------------

class PinnedQuery(Query):
    """Promoted ids rank first, in order, above organic results
    (reference: x-pack search-business-rules PinnedQueryBuilder)."""

    def __init__(self, ids: List[str], organic: Query):
        self.ids = ids
        self.organic = organic

    def execute(self, ctx: SearchContext) -> DocSet:
        organic = self.organic.execute(ctx).with_scores()
        id_rows = _id_to_row(ctx)
        pinned_rows = [id_rows[i] for i in self.ids if i in id_rows]
        max_organic = float(organic.scores.max()) if len(organic.scores) else 0.0
        rows: List[int] = []
        scores: List[float] = []
        for rank, row in enumerate(pinned_rows):
            rows.append(row)
            scores.append(max_organic + len(pinned_rows) - rank + 1.0)
        pinned_set = set(pinned_rows)
        for row, sc in zip(organic.rows, organic.scores):
            if int(row) not in pinned_set:
                rows.append(int(row))
                scores.append(float(sc))
        order = np.argsort(np.asarray(rows, dtype=np.int64), kind="stable")
        rows_arr = np.asarray(rows, dtype=np.int64)[order]
        scores_arr = np.asarray(scores, dtype=np.float32)[order]
        return DocSet(rows_arr, scores_arr)

    def to_dict(self):
        return {"pinned": {"ids": self.ids, "organic": self.organic.to_dict()}}


# ---------------------------------------------------------------------------
# dispatch (called from queries.parse_query on unknown kinds)
# ---------------------------------------------------------------------------

def parse_extended(kind: str, spec: Any) -> Optional[Query]:
    if kind == "geo_distance":
        spec = dict(spec)
        distance = parse_distance(spec.pop("distance"))
        spec.pop("distance_type", None)
        spec.pop("validation_method", None)
        field, point = next(iter(spec.items()))
        lat, lon = parse_geo_point(point)
        return GeoDistanceQuery(field, lat, lon, distance)
    if kind == "geo_bounding_box":
        spec = dict(spec)
        spec.pop("validation_method", None)
        field, box = next(iter(spec.items()))
        tl = parse_geo_point(box["top_left"])
        br = parse_geo_point(box["bottom_right"])
        return GeoBoundingBoxQuery(field, tl[0], tl[1], br[0], br[1])
    if kind == "geo_shape":
        spec = dict(spec)
        spec.pop("ignore_unmapped", None)
        field, body = next(iter(spec.items()))
        shape = body.get("shape")
        if shape is None and "indexed_shape" in body:
            raise ParsingError("[geo_shape] indexed_shape is not supported; "
                               "inline the shape")
        return GeoShapeQuery(field, shape,
                             str(body.get("relation", "intersects")).lower())
    if kind == "geo_polygon":
        spec = dict(spec)
        spec.pop("validation_method", None)
        field, poly = next(iter(spec.items()))
        points = [parse_geo_point(p) for p in poly["points"]]
        return GeoPolygonQuery(field, points)
    if kind == "distance_feature":
        return DistanceFeatureQuery(spec["field"], spec["origin"],
                                    spec["pivot"],
                                    float(spec.get("boost", 1.0)))
    if kind == "rank_feature":
        return RankFeatureQuery(spec["field"],
                                saturation=spec.get("saturation"),
                                log=spec.get("log"),
                                sigmoid=spec.get("sigmoid"),
                                linear=spec.get("linear"),
                                boost=float(spec.get("boost", 1.0)))
    if kind == "sparse_vector":
        return WeightedTokensQuery(spec["field"],
                                   dict(spec.get("query_vector") or {}),
                                   float(spec.get("boost", 1.0)))
    if kind == "weighted_tokens":
        field, v = _single(spec)
        return WeightedTokensQuery(field, dict(v.get("tokens") or {}),
                                   float(v.get("boost", 1.0)))
    if kind == "late_interaction":
        return LateInteractionQuery(spec["field"], spec["query_tokens"],
                                    float(spec.get("boost", 1.0)))
    if kind == "more_like_this":
        like = spec.get("like", [])
        if not isinstance(like, list):
            like = [like]
        unlike = spec.get("unlike", [])
        if not isinstance(unlike, list):
            unlike = [unlike]
        return MoreLikeThisQuery(
            fields=spec.get("fields", []), like=like,
            min_term_freq=int(spec.get("min_term_freq", 2)),
            min_doc_freq=int(spec.get("min_doc_freq", 5)),
            max_query_terms=int(spec.get("max_query_terms", 25)),
            minimum_should_match=spec.get("minimum_should_match", "30%"),
            include=bool(spec.get("include", False)),
            unlike=unlike)
    if kind == "terms_set":
        field, v = _single(spec)
        return TermsSetQuery(field, v.get("terms", []),
                             v.get("minimum_should_match_field"),
                             v.get("minimum_should_match_script"))
    if kind == "nested":
        return NestedQuery(spec["path"], spec.get("query", {"match_all": {}}),
                           spec.get("score_mode", "avg"))
    if kind == "has_child":
        return HasChildQuery(spec["type"],
                             parse_query(spec.get("query", {"match_all": {}})),
                             spec.get("score_mode", "none"))
    if kind == "has_parent":
        return HasParentQuery(spec["parent_type"],
                              parse_query(spec.get("query", {"match_all": {}})),
                              bool(spec.get("score", False)))
    if kind == "parent_id":
        return ParentIdQuery(spec["type"], str(spec["id"]))
    if kind == "percolate":
        docs = spec.get("documents")
        if docs is None:
            docs = [spec["document"]] if "document" in spec else []
        return PercolateQuery(spec["field"], docs)
    if kind in ("span_term", "span_near", "span_or", "span_first", "span_not"):
        return SpanQuery(kind, spec)
    if kind == "intervals":
        field, rule = _single(spec)
        return IntervalsQuery(field, rule)
    if kind == "wrapper":
        decoded = base64.b64decode(spec["query"])
        return parse_query(json.loads(decoded))
    if kind == "pinned":
        return PinnedQuery([str(i) for i in spec.get("ids", [])],
                           parse_query(spec.get("organic", {"match_all": {}})))
    # plugin-contributed parsers (reference: SearchPlugin.getQueries)
    from elasticsearch_tpu.plugins import EXTRA_QUERY_PARSERS
    parser = EXTRA_QUERY_PARSERS.get(kind)
    if parser is not None:
        return parser(spec)
    return None
