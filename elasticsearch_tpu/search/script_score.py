"""script_score: a sandboxed painless-lite expression scorer.

The reference compiles Painless to JVM bytecode (`modules/lang-painless`,
34.8k LoC) and whitelists vector kernels into it
(`DocValuesWhitelistExtension.java:30`). Here scripts are parsed with
Python's `ast` into a restricted evaluator: arithmetic, comparisons,
`doc['field'].value`, `params.x` / `params['x']`, `Math.*`, and the vector
functions (`cosineSimilarity`, `dotProduct`, `l1norm`, `l2norm`) from
`ScoreScriptUtils.java:86-171` — evaluated **batched over all candidate
docs** with numpy instead of per-doc.

Security: only whitelisted AST node types and names resolve; no attribute
access outside `doc/params/Math/_score`, no calls outside the function
whitelist — the moral equivalent of the Painless allowlist.
"""

from __future__ import annotations

import ast
import math
from typing import Any, Dict, Optional

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError
from elasticsearch_tpu.search.queries import DocSet, Query, SearchContext

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.Compare, ast.BoolOp, ast.IfExp,
    ast.Call, ast.Name, ast.Attribute, ast.Subscript, ast.Constant, ast.Load,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow, ast.FloorDiv,
    ast.USub, ast.UAdd, ast.Not, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt,
    ast.GtE, ast.And, ast.Or,
)

_MATH = {
    "log": np.log, "log10": np.log10, "log1p": np.log1p, "exp": np.exp,
    "sqrt": np.sqrt, "abs": np.abs, "pow": np.power, "min": np.minimum,
    "max": np.maximum, "floor": np.floor, "ceil": np.ceil, "E": math.e,
    "PI": math.pi,
}


class _DocFieldValues:
    """`doc['field']` — exposes .value / .length over the candidate batch."""

    def __init__(self, values: np.ndarray, present: np.ndarray):
        self._values = values
        self._present = present

    @property
    def value(self):
        return self._values

    @property
    def empty(self):
        return ~self._present

    def size(self):
        return self._present.astype(np.int64)


class _DocAccessor:
    def __init__(self, ctx: SearchContext, rows: np.ndarray):
        self._ctx = ctx
        self._rows = rows
        self._cache: Dict[str, _DocFieldValues] = {}

    def __getitem__(self, field: str) -> _DocFieldValues:
        if field in self._cache:
            return self._cache[field]
        vals = np.zeros(len(self._rows), dtype=np.float64)
        present = np.zeros(len(self._rows), dtype=bool)
        str_vals: Optional[list] = None
        for i, row in enumerate(self._rows):
            v = self._ctx.reader.get_doc_value(field, int(row))
            if v is None:
                continue
            if isinstance(v, list):
                v = v[0] if v else None
                if v is None:
                    continue
            if isinstance(v, bool):
                vals[i] = 1.0 if v else 0.0
            elif isinstance(v, (int, float)):
                vals[i] = float(v)
            else:
                if str_vals is None:
                    str_vals = [None] * len(self._rows)
                str_vals[i] = str(v)
            present[i] = True
        if str_vals is not None:
            arr = np.asarray([s if s is not None else "" for s in str_vals], dtype=object)
            return _DocFieldValues(arr, present)
        out = _DocFieldValues(vals, present)
        self._cache[field] = out
        return out


def _gather_vectors(ctx: SearchContext, rows: np.ndarray, field: str) -> np.ndarray:
    dims = None
    mapper = ctx.mapper_service.get(field)
    if mapper is not None and hasattr(mapper, "dims"):
        dims = mapper.dims
    out = None
    for view in ctx.reader.views:
        seg = view.segment
        if field not in seg.vectors:
            continue
        mat, present = seg.vectors[field]
        if out is None:
            out = np.zeros((len(rows), mat.shape[1]), dtype=np.float32)
        in_seg = (rows >= seg.base) & (rows < seg.base + seg.num_docs)
        locs = (rows[in_seg] - seg.base).astype(np.int64)
        out[in_seg] = mat[locs]
    if out is None:
        d = dims or 1
        out = np.zeros((len(rows), d), dtype=np.float32)
    return out


class _Evaluator:
    def __init__(self, ctx: SearchContext, rows: np.ndarray,
                 params: Dict[str, Any], base_scores: np.ndarray):
        self.ctx = ctx
        self.rows = rows
        self.params = params
        self.doc = _DocAccessor(ctx, rows)
        self.base_scores = base_scores

    # -- vector functions (ScoreScriptUtils.java:86-171) ----------------------
    def _qvec(self, v) -> np.ndarray:
        return np.asarray(v, dtype=np.float32)

    def cosine_similarity(self, query_vector, field: str) -> np.ndarray:
        q = self._qvec(query_vector)
        mat = _gather_vectors(self.ctx, self.rows, field)
        qn = np.linalg.norm(q) or 1e-30
        mn = np.maximum(np.linalg.norm(mat, axis=1), 1e-30)
        return (mat @ q) / (qn * mn)

    def dot_product(self, query_vector, field: str) -> np.ndarray:
        return _gather_vectors(self.ctx, self.rows, field) @ self._qvec(query_vector)

    def l1norm(self, query_vector, field: str) -> np.ndarray:
        mat = _gather_vectors(self.ctx, self.rows, field)
        return np.abs(mat - self._qvec(query_vector)[None, :]).sum(axis=1)

    def l2norm(self, query_vector, field: str) -> np.ndarray:
        mat = _gather_vectors(self.ctx, self.rows, field)
        return np.sqrt(((mat - self._qvec(query_vector)[None, :]) ** 2).sum(axis=1))

    FUNCTIONS = {
        "cosineSimilarity": "cosine_similarity",
        "dotProduct": "dot_product",
        "l1norm": "l1norm",
        "l2norm": "l2norm",
        "saturation": None,   # handled inline
        "sigmoid": None,
    }

    # -- AST walk -------------------------------------------------------------
    def eval(self, node) -> Any:
        if not isinstance(node, _ALLOWED_NODES):
            raise IllegalArgumentError(
                f"script construct [{type(node).__name__}] is not allowed")
        if isinstance(node, ast.Expression):
            return self.eval(node.body)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float, str, bool)):
                return node.value
            raise IllegalArgumentError("unsupported constant in script")
        if isinstance(node, ast.Name):
            if node.id == "doc":
                return self.doc
            if node.id == "params":
                return self.params
            if node.id == "Math":
                return _MATH
            if node.id == "_score":
                return self.base_scores
            raise IllegalArgumentError(f"unknown variable [{node.id}]")
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value)
            if isinstance(base, dict):
                if node.attr in base:
                    return base[node.attr]
                raise IllegalArgumentError(f"unknown attribute [{node.attr}]")
            if isinstance(base, _DocFieldValues) and node.attr in ("value", "empty"):
                return getattr(base, node.attr)
            raise IllegalArgumentError(f"attribute access [{node.attr}] not allowed")
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            key = self.eval(node.slice)
            if isinstance(base, (_DocAccessor, dict)):
                return base[key]
            raise IllegalArgumentError("subscript not allowed here")
        if isinstance(node, ast.BinOp):
            left, right = self.eval(node.left), self.eval(node.right)
            ops = {ast.Add: np.add, ast.Sub: np.subtract, ast.Mult: np.multiply,
                   ast.Div: np.divide, ast.Mod: np.mod, ast.Pow: np.power,
                   ast.FloorDiv: np.floor_divide}
            return ops[type(node.op)](left, right)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand)
            if isinstance(node.op, ast.USub):
                return np.negative(v)
            if isinstance(node.op, ast.Not):
                return np.logical_not(v)
            return v
        if isinstance(node, ast.Compare):
            left = self.eval(node.left)
            result = None
            for op, comp in zip(node.ops, node.comparators):
                right = self.eval(comp)
                ops = {ast.Eq: np.equal, ast.NotEq: np.not_equal, ast.Lt: np.less,
                       ast.LtE: np.less_equal, ast.Gt: np.greater,
                       ast.GtE: np.greater_equal}
                r = ops[type(op)](left, right)
                result = r if result is None else np.logical_and(result, r)
                left = right
            return result
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = np.logical_and(out, v) if isinstance(node.op, ast.And) else np.logical_or(out, v)
            return out
        if isinstance(node, ast.IfExp):
            cond = self.eval(node.test)
            return np.where(cond, self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, ast.Call):
            return self._call(node)
        raise IllegalArgumentError(f"unsupported script node [{type(node).__name__}]")

    def _call(self, node: ast.Call) -> Any:
        args = [self.eval(a) for a in node.args]
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name in ("cosineSimilarity", "dotProduct", "l1norm", "l2norm"):
                if len(args) != 2:
                    raise IllegalArgumentError(f"[{name}] takes (query_vector, field)")
                return getattr(self, self.FUNCTIONS[name])(args[0], args[1])
            if name == "saturation":
                return args[0] / (args[0] + args[1])
            if name == "sigmoid":
                v, k, a = args
                return v ** a / (k ** a + v ** a)
            raise IllegalArgumentError(f"unknown function [{name}]")
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id == "Math":
                fn = _MATH.get(node.func.attr)
                if callable(fn):
                    return fn(*args)
                raise IllegalArgumentError(f"unknown Math function [{node.func.attr}]")
            obj = self.eval(base)
            if isinstance(obj, _DocFieldValues) and node.func.attr == "size":
                return obj.size()
            raise IllegalArgumentError("method calls not allowed in scripts")
        raise IllegalArgumentError("unsupported call in script")


class Script:
    """A compiled script (source + params). Reference: `script/Script.java`."""

    def __init__(self, spec: Any):
        if isinstance(spec, str):
            spec = {"source": spec}
        if isinstance(spec, dict) and "id" in spec and "source" not in spec:
            # stored-script reference — resolved against the cluster-wide
            # registry (reference: ScriptService looks up ScriptMetaData
            # from cluster state at compile time)
            from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
            resolved = GLOBAL_SCRIPTS.resolve(spec)
            if resolved["lang"] == "mustache":
                raise IllegalArgumentError(
                    f"stored script [{spec['id']}] is a [mustache] template, "
                    "not usable in this context")
            spec = {"source": resolved["source"],
                    "params": spec.get("params", {})}
        if not isinstance(spec, dict) or "source" not in spec:
            raise ParsingError("script must define [source]")
        self.source = spec["source"]
        self.params = spec.get("params", {})
        # pure expressions run BATCHED over the candidate set (numpy /
        # device); statement scripts (loops, if/else, defs) compile to the
        # sandboxed Painless interpreter and run per document — scripts
        # steer control flow, the hot loops stay vectorized
        self.tree = None
        self.program = None
        try:
            self.tree = ast.parse(self.source, mode="eval")
        except SyntaxError:
            from elasticsearch_tpu.script.painless import compile_painless
            try:
                self.program = compile_painless(self.source)
            except ParsingError as e:
                raise ParsingError(
                    f"compile error in script [{self.source}]: {e}")

    def evaluate(self, ctx: SearchContext, rows: np.ndarray,
                 base_scores: np.ndarray) -> np.ndarray:
        if self.tree is not None:
            # expression fast path: one batched numpy evaluation; genuine
            # script errors (unknown names/attrs) propagate as 400s
            ev = _Evaluator(ctx, rows, self.params, base_scores)
            out = ev.eval(self.tree)
            return np.broadcast_to(np.asarray(out, dtype=np.float64),
                                   (len(rows),)).astype(np.float32)
        return self._evaluate_painless(ctx, rows, base_scores)

    def _evaluate_painless(self, ctx: SearchContext, rows: np.ndarray,
                           base_scores: np.ndarray) -> np.ndarray:
        from elasticsearch_tpu.script.painless import execute

        out = np.zeros(len(rows), dtype=np.float32)
        batch_ev = _Evaluator(ctx, rows, self.params, base_scores)
        cur = {"i": 0}
        # vector kernels are computed ONCE for the whole candidate batch and
        # indexed per document — a per-row call would redo the full matmul
        kernel_cache: Dict[tuple, np.ndarray] = {}

        def batched(kernel_name):
            fn = getattr(batch_ev, kernel_name)

            def call(q, field):
                key = (kernel_name, field, tuple(np.ravel(q)))
                if key not in kernel_cache:
                    kernel_cache[key] = fn(q, field)
                return float(kernel_cache[key][cur["i"]])
            return call

        from elasticsearch_tpu.script.painless import FrozenParams
        bindings = {
            "doc": None, "params": FrozenParams(self.params), "_score": 0.0,
            "cosineSimilarity": batched("cosine_similarity"),
            "dotProduct": batched("dot_product"),
            "l1norm": batched("l1norm"),
            "l2norm": batched("l2norm"),
            "saturation": lambda v, k: v / (v + k),
            "sigmoid": lambda v, k, a: v ** a / (k ** a + v ** a),
        }
        for i, row in enumerate(rows):
            cur["i"] = i
            bindings["doc"] = _ScalarDoc(ctx, int(row))
            bindings["_score"] = float(base_scores[i])
            value = execute(self.program, bindings)
            try:
                out[i] = float(value) if value is not None else 0.0
            except (TypeError, ValueError):
                raise IllegalArgumentError(
                    f"script_score script returned a non-numeric value "
                    f"[{value!r}]")
        return out


class _ScalarDocField:
    """doc['field'] for one document in the per-doc interpreter."""

    _painless_fields = ("value", "empty", "values", "length")

    def __init__(self, raw):
        if raw is None:
            self._values = []
        elif isinstance(raw, list):
            self._values = raw
        else:
            self._values = [raw]

    @property
    def value(self):
        if not self._values:
            raise IllegalArgumentError(
                "A document doesn't have a value for a field! "
                "Use doc[<field>].size()==0 to check if a document is "
                "missing a field!")
        return self._values[0]

    @property
    def values(self):
        return list(self._values)

    @property
    def empty(self):
        return not self._values

    @property
    def length(self):
        return len(self._values)

    def _painless_methods(self):
        return {"size": lambda: len(self._values),
                "isEmpty": lambda: not self._values,
                "get": lambda i: self._values[int(i)],
                "contains": lambda x: x in self._values}


class _ScalarDoc:
    def __init__(self, ctx: SearchContext, row: int):
        self._ctx = ctx
        self._row = row

    def __getitem__(self, field: str) -> _ScalarDocField:
        return _ScalarDocField(self._ctx.reader.get_doc_value(field, self._row))

    def _painless_methods(self):
        return {"containsKey": lambda f:
                self._ctx.reader.get_doc_value(f, self._row) is not None}


class ScriptScoreQuery(Query):
    """`script_score` (reference: ScriptScoreQueryBuilder): score candidates
    of the inner query with the script, batched."""

    def __init__(self, query: Query, script_spec: Any):
        self.query = query
        self.script = Script(script_spec)

    def execute(self, ctx: SearchContext) -> DocSet:
        base = self.query.execute(ctx).with_scores()
        if len(base.rows) == 0:
            return base
        scores = self.script.evaluate(ctx, base.rows, base.scores)
        return DocSet(base.rows, scores)

    def to_dict(self):
        return {"script_score": {"query": self.query.to_dict(),
                                 "script": {"source": self.script.source,
                                            "params": self.script.params}}}
