"""kNN query: the `_search { "knn": ... }` device path.

The north-star query (SURVEY.md §2.8, BASELINE.json): where the reference
runs `script_score` with a per-doc Painless CosineSimilarity loop
(`ScoreScriptUtils.java:145-171`), this query dispatches to the shard's
device vector store — batched matmul + lax.top_k — and composes with an
optional boolean pre-filter evaluated host-side and shipped as a mask
(SURVEY.md §7 "Filtered kNN").

Scores follow the `_search` knn `_score` convention via
`similarity.to_es_score`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.search.queries import DocSet, Query, SearchContext


class KnnQuery(Query):
    def __init__(self, field: str, query_vector, k: int = 10,
                 num_candidates: int = 10, filter_query: Optional[Query] = None,
                 boost: float = 1.0):
        self.field = field
        self.query_vector = np.asarray(query_vector, dtype=np.float32)
        self.k = k
        self.num_candidates = max(num_candidates, k)
        self.filter_query = filter_query
        self.boost = boost

    def _metric(self, ctx: SearchContext) -> str:
        mapper = ctx.mapper_service.get(self.field)
        if not isinstance(mapper, DenseVectorFieldMapper):
            raise IllegalArgumentError(
                f"[knn] field [{self.field}] is not a dense_vector field")
        if self.query_vector.shape[0] != mapper.dims:
            raise IllegalArgumentError(
                f"[knn] query vector has {self.query_vector.shape[0]} dims, "
                f"field [{self.field}] expects {mapper.dims}")
        from elasticsearch_tpu.vectors.store import _METRIC_MAP
        return _METRIC_MAP[mapper.similarity]

    def execute(self, ctx: SearchContext) -> DocSet:
        metric = self._metric(ctx)
        filter_rows = None
        if self.filter_query is not None:
            filter_rows = self.filter_query.execute(ctx).rows

        store = getattr(ctx, "vector_store", None)
        if store is not None and store.field(self.field) is not None:
            rows, raw = store.search(self.field, self.query_vector, self.k,
                                     filter_rows=filter_rows,
                                     num_candidates=self.num_candidates,
                                     deadline_at=getattr(
                                         ctx, "deadline_at", None))
            # per-phase engine timings (route/score/merge for tpu_ivf) for
            # the profiler and shard result; plus the columnar refresh
            # ledger for this field (segment block store): how the last
            # sync composed — cached / delta / full extraction — so
            # profile.knn shows the O(delta) claim per search instead of
            # burying it in node stats
            phases = getattr(store, "last_knn_phases", None)
            col = getattr(store, "columnar_refresh", None)
            if col and self.field in col:
                phases = dict(phases or {})
                phases.setdefault("columnar", col[self.field])
            if phases:
                ctx.knn_phases = phases
        else:
            rows, raw = self._host_fallback(ctx, metric, filter_rows)

        scores = np.asarray(sim.to_es_score(raw, metric)) * self.boost
        order = np.argsort(rows, kind="stable")
        return DocSet(rows[order].astype(np.int64), scores[order].astype(np.float32))

    def _host_fallback(self, ctx: SearchContext, metric: str,
                       filter_rows: Optional[np.ndarray]):
        """Exact numpy path when no device store is attached (unit tests,
        tiny shards): same math, same ordering semantics."""
        mats, rows = [], []
        for view in ctx.reader.views:
            seg = view.segment
            if self.field not in seg.vectors:
                continue
            mat, present = seg.vectors[self.field]
            keep = present & view.live
            locs = np.nonzero(keep)[0]
            if len(locs):
                mats.append(mat[locs])
                rows.append(locs.astype(np.int64) + seg.base)
        if not mats:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32)
        mat = np.concatenate(mats)
        rows = np.concatenate(rows)
        if filter_rows is not None:
            keep = np.isin(rows, filter_rows)
            mat, rows = mat[keep], rows[keep]
            if len(rows) == 0:
                return rows, np.zeros(0, dtype=np.float32)
        q = self.query_vector
        if metric == sim.COSINE:
            qn = q / max(np.linalg.norm(q), 1e-30)
            cn = mat / np.maximum(np.linalg.norm(mat, axis=1, keepdims=True), 1e-30)
            raw = cn @ qn
        elif metric in (sim.DOT_PRODUCT, sim.MAX_INNER_PRODUCT):
            raw = mat @ q
        else:  # l2
            raw = -((mat - q[None, :]) ** 2).sum(axis=1)
        k = min(self.k, len(rows))
        top = np.argpartition(-raw, k - 1)[:k] if k < len(rows) else np.arange(len(rows))
        top = top[np.argsort(-raw[top], kind="stable")]
        return rows[top], raw[top].astype(np.float32)

    def to_dict(self):
        d = {"field": self.field, "query_vector": self.query_vector.tolist(),
             "k": self.k, "num_candidates": self.num_candidates}
        if self.filter_query is not None:
            d["filter"] = self.filter_query.to_dict()
        return {"knn": d}
