"""Suggesters and ranking evaluation.

- Suggesters (`search/suggest/`, SURVEY.md §2.5): term suggester (edit-
  distance candidates over indexed terms, scored by similarity then
  frequency), phrase suggester (per-token best corrections composed),
  completion suggester (prefix match over any keyword-ish field with
  optional weights).
- Rank eval (`modules/rank-eval`, §4.8): Precision@K / Recall@K / MRR /
  DCG / NDCG / ERR over rated search results — the harness BASELINE.md uses
  to prove recall@10 >= 0.95.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError,
)
from elasticsearch_tpu.index.mapping import TextFieldMapper
from elasticsearch_tpu.search.queries import (
    SearchContext, _edit_distance_le, _pattern_terms, _term_postings,
)

# ---------------------------------------------------------------------------
# suggesters
# ---------------------------------------------------------------------------


def _term_freq(ctx: SearchContext, field: str, term: str) -> int:
    rows, freqs = _term_postings(ctx, field, term)
    return int(freqs.sum())


def _candidates(ctx: SearchContext, field: str, token: str,
                max_edits: int = 2, size: int = 5) -> List[dict]:
    out = []
    for term in _pattern_terms(ctx, field,
                               lambda t: t != token and _edit_distance_le(token, t, max_edits)):
        dist = 1 if _edit_distance_le(token, term, 1) else 2
        freq = _term_freq(ctx, field, term)
        score = 1.0 - dist / max(len(token), len(term), 1)
        out.append({"text": term, "score": round(score, 6), "freq": freq})
    out.sort(key=lambda c: (-c["score"], -c["freq"], c["text"]))
    return out[:size]


def term_suggest(ctx: SearchContext, text: str, field: str,
                 size: int = 5, max_edits: int = 2) -> List[dict]:
    mapper = ctx.mapper_service.get(field)
    if isinstance(mapper, TextFieldMapper):
        tokens = mapper.search_analyzer.analyze(str(text))
    else:
        from elasticsearch_tpu.index.analysis import Token
        tokens = [Token(str(text), 0, 0, len(str(text)))]
    entries = []
    for tok in tokens:
        exists = _term_freq(ctx, field, tok.term) > 0
        options = [] if exists else _candidates(ctx, field, tok.term, max_edits, size)
        entries.append({"text": tok.term, "offset": tok.start_offset,
                        "length": tok.end_offset - tok.start_offset,
                        "options": options})
    return entries


def phrase_suggest(ctx: SearchContext, text: str, field: str,
                   size: int = 3, max_edits: int = 2) -> List[dict]:
    entries = term_suggest(ctx, text, field, size=3, max_edits=max_edits)
    corrected = []
    any_correction = False
    score = 1.0
    for e in entries:
        if e["options"]:
            corrected.append(e["options"][0]["text"])
            score *= e["options"][0]["score"]
            any_correction = True
        else:
            corrected.append(e["text"])
    options = []
    if any_correction:
        options.append({"text": " ".join(corrected), "score": round(score, 6)})
    return [{"text": text, "offset": 0, "length": len(text), "options": options}]


# geohash level -> approx cell size in meters (GeoUtils.geoHashCellSize)
_GEOHASH_LEVEL_M = {1: 5_009_400.0, 2: 1_252_300.0, 3: 156_500.0,
                    4: 39_100.0, 5: 4_890.0, 6: 1_220.0, 7: 153.0,
                    8: 38.2, 9: 4.77, 10: 1.19, 11: 0.149, 12: 0.037}


def _parse_precision_m(precision) -> float:
    """Geo-context precision: a bare int is a GEOHASH LEVEL (default 6),
    a string is a distance (GeoContextMapping)."""
    from elasticsearch_tpu.search.queries_ext import parse_distance
    if isinstance(precision, int) and not isinstance(precision, bool):
        return _GEOHASH_LEVEL_M.get(min(max(precision, 1), 12), 1_220.0)
    try:
        return parse_distance(precision)
    except Exception:
        raise IllegalArgumentError(
            f"invalid geo context precision [{precision}]")


def _contexts_match(ctx, row, entry, ctx_defs, query_contexts) -> bool:
    """Category: any queried value among the doc's values; geo: within the
    context's precision radius (CategoryContextMapping/GeoContextMapping)."""
    from elasticsearch_tpu.search.queries_ext import haversine_m
    for name, want in query_contexts.items():
        cdef = next((d for d in ctx_defs if d.get("name") == name), None)
        if cdef is None:
            return False
        have = (entry.get("contexts") or {}).get(name)
        if have is None and cdef.get("path"):
            have = ctx.reader.get_doc_value(cdef["path"], int(row))
            if have is None:  # dynamic text fields store under .keyword
                have = ctx.reader.get_doc_value(
                    f"{cdef['path']}.keyword", int(row))
        if have is None:
            return False
        if cdef.get("type") == "geo":
            specs = want if isinstance(want, list) else [want]
            point = have
            if isinstance(point, list) and point and \
                    isinstance(point[0], (list, tuple)):
                point = point[0]
            if isinstance(point, dict):
                plat, plon = float(point["lat"]), float(point["lon"])
            elif isinstance(point, (list, tuple)) and len(point) == 2:
                plat, plon = float(point[0]), float(point[1])
            else:
                return False
            radius = _parse_precision_m(cdef.get("precision", "5km"))
            ok = False
            for spec in specs:
                g = spec.get("context", spec) if isinstance(spec, dict) else {}
                if not isinstance(g, dict):
                    continue
                try:
                    if haversine_m(plat, plon, float(g["lat"]),
                                   float(g["lon"])) <= radius:
                        ok = True
                        break
                except (KeyError, TypeError, ValueError):
                    continue
            if not ok:
                return False
        else:  # category
            have_vals = have if isinstance(have, list) else [have]
            want_specs = want if isinstance(want, list) else [want]
            want_vals = [w.get("context") if isinstance(w, dict) else w
                         for w in want_specs]
            if not {str(v) for v in have_vals} & {str(v) for v in want_vals}:
                return False
    return True


def completion_suggest(ctx: SearchContext, prefix: str, field: str,
                       size: int = 5, contexts=None,
                       index_name: str = "index",
                       skip_duplicates: bool = False) -> List[dict]:
    """Doc-based completion: weight-ordered prefix matches over the stored
    inputs, with category/geo context filtering and full option payloads
    (CompletionSuggester + TopSuggestDocsCollector)."""
    from elasticsearch_tpu.index.mapping import CompletionFieldMapper
    mapper = ctx.mapper_service.get(field)
    if not isinstance(mapper, CompletionFieldMapper):
        # prefix scan over any keyword-ish field's terms (the pre-FST
        # convenience path; real completion fields get weights/contexts)
        terms = _pattern_terms(ctx, field, lambda t: t.startswith(prefix))
        scored = [(t, _term_freq(ctx, field, t)) for t in terms]
        scored.sort(key=lambda kv: (-kv[1], kv[0]))
        return [{"text": prefix, "offset": 0, "length": len(prefix),
                 "options": [{"text": t, "_score": float(f)}
                             for t, f in scored[:size]]}]
    ctx_defs = list(mapper.params.get("contexts") or [])
    if ctx_defs:
        # the query must resolve to at least one concrete context value
        # ({name: []} is as missing as no contexts at all)
        provided = {k: (v if isinstance(v, list) else [v])
                    for k, v in (contexts or {}).items()}
        if not any(vals for vals in provided.values()):
            raise IllegalArgumentError(
                "Missing mandatory contexts in context query")
    plc = str(prefix or "").lower()
    best_per_doc: Dict[int, Tuple[str, float]] = {}
    for row in ctx.all_rows():
        dv = ctx.reader.get_doc_value(field, int(row))
        if dv is None:
            continue
        for entry in (dv if isinstance(dv, list) else [dv]):
            if not isinstance(entry, dict):
                continue
            matched = [i for i in entry.get("input", [])
                       if str(i).lower().startswith(plc)]
            if not matched:
                continue
            if ctx_defs and contexts and not _contexts_match(
                    ctx, row, entry, ctx_defs, contexts):
                continue
            weight = float(entry.get("weight", 1))
            prev = best_per_doc.get(int(row))
            # ONE option per document — the best-weighted suggestion wins
            # (TopSuggestDocsCollector dedupes by doc)
            if prev is None or weight > prev[1]:
                best_per_doc[int(row)] = (str(matched[0]), weight)
    ranked = sorted(best_per_doc.items(),
                    key=lambda kv: (-kv[1][1], kv[1][0]))
    if skip_duplicates:
        seen, deduped = set(), []
        for row, (text, weight) in ranked:
            if text not in seen:
                seen.add(text)
                deduped.append((row, (text, weight)))
        ranked = deduped
    # materialize _id/_source only for the survivors
    options = [{"text": text, "_index": index_name,
                "_id": ctx.reader.get_id(row), "_score": weight,
                "_source": ctx.reader.get_source(row)}
               for row, (text, weight) in ranked[:size]]
    return [{"text": prefix, "offset": 0, "length": len(prefix),
             "options": options}]


def execute_suggest(ctx: SearchContext, spec: dict,
                    index_name: str = "index") -> Dict[str, list]:
    out = {}
    global_text = spec.get("text")
    for name, body in spec.items():
        if name == "text" or not isinstance(body, dict):
            continue
        text = body.get("text", global_text)
        if "term" in body:
            t = body["term"]
            out[name] = term_suggest(ctx, text, t["field"],
                                     size=int(t.get("size", 5)),
                                     max_edits=int(t.get("max_edits", 2)))
        elif "phrase" in body:
            t = body["phrase"]
            out[name] = phrase_suggest(ctx, text, t["field"],
                                       size=int(t.get("size", 3)))
        elif "completion" in body:
            t = body["completion"]
            out[name] = completion_suggest(
                ctx, body.get("prefix", t.get("prefix", text)),
                t["field"], size=int(t.get("size", 5)),
                contexts=t.get("contexts"),
                index_name=index_name,
                skip_duplicates=bool(t.get("skip_duplicates", False)))
        else:
            raise ParsingError(f"unknown suggester in [{name}]")
    return out


# ---------------------------------------------------------------------------
# rank evaluation
# ---------------------------------------------------------------------------

def _rated_map(ratings: List[dict]) -> Dict[Tuple[str, str], int]:
    return {(r["_index"], r["_id"]): int(r["rating"]) for r in ratings}


def _metric_value(metric_name: str, spec: dict, hits: List[dict],
                  ratings: List[dict]) -> Tuple[float, List[dict]]:
    rated = _rated_map(ratings)
    threshold = int(spec.get("relevant_rating_threshold", 1))
    k = int(spec.get("k", 10))
    hit_details = []
    rels = []
    for h in hits[:k]:
        key = (h["_index"], h["_id"])
        rating = rated.get(key)
        hit_details.append({"hit": {"_index": h["_index"], "_id": h["_id"]},
                            "rating": rating})
        rels.append(rating)

    if metric_name == "precision":
        got = [r for r in rels if r is not None] if spec.get(
            "ignore_unlabeled") else [r or 0 for r in rels]
        if not got:
            return 0.0, hit_details
        return sum(1 for r in got if r >= threshold) / len(got), hit_details
    if metric_name == "recall":
        total_relevant = sum(1 for r in rated.values() if r >= threshold)
        if total_relevant == 0:
            return 0.0, hit_details
        found = sum(1 for r in rels if r is not None and r >= threshold)
        return found / total_relevant, hit_details
    if metric_name == "mean_reciprocal_rank":
        for rank, r in enumerate(rels, 1):
            if r is not None and r >= threshold:
                return 1.0 / rank, hit_details
        return 0.0, hit_details
    if metric_name == "dcg":
        normalize = bool(spec.get("normalize", False))
        dcg = sum((2 ** (r or 0) - 1) / math.log2(rank + 1)
                  for rank, r in enumerate(rels, 1))
        if not normalize:
            return dcg, hit_details
        ideal = sorted((r for r in rated.values()), reverse=True)[:k]
        idcg = sum((2 ** r - 1) / math.log2(rank + 1)
                   for rank, r in enumerate(ideal, 1))
        return (dcg / idcg if idcg > 0 else 0.0), hit_details
    if metric_name == "expected_reciprocal_rank":
        max_rel = int(spec.get("maximum_relevance", max([r or 0 for r in rels] + [1])))
        p = 1.0
        err = 0.0
        for rank, r in enumerate(rels, 1):
            ri = (2 ** (r or 0) - 1) / (2 ** max_rel)
            err += p * ri / rank
            p *= (1 - ri)
        return err, hit_details
    raise ParsingError(f"unknown rank-eval metric [{metric_name}]")


def rank_eval(search_fn, body: dict, default_index: Optional[str]) -> dict:
    """Execute a _rank_eval request: run each rated request via search_fn
    (index_expr, search_body) -> response, score with the metric."""
    metric_spec = body.get("metric", {"precision": {}})
    ((metric_name, mspec),) = metric_spec.items()
    details = {}
    scores = []
    failures = {}
    for req in body.get("requests", []):
        rid = req["id"]
        try:
            resp = search_fn(default_index, req.get("request", {}))
            hits = resp["hits"]["hits"]
            value, hit_details = _metric_value(metric_name, mspec, hits,
                                               req.get("ratings", []))
            scores.append(value)
            details[rid] = {"metric_score": value, "hits": hit_details,
                            "unrated_docs": [
                                {"_index": h["hit"]["_index"], "_id": h["hit"]["_id"]}
                                for h in hit_details if h["rating"] is None]}
        except Exception as e:
            failures[rid] = {"error": str(e)}
    return {"metric_score": sum(scores) / len(scores) if scores else 0.0,
            "details": details, "failures": failures}
