"""Query DSL: executable queries over a ShardReader.

Re-design of the reference's query layer (`index/query/` — 73 builder files —
plus Lucene's scorers; SURVEY.md §2.5). Instead of per-document iterator
scorers (BulkScorer over postings), every query evaluates **vectorized**:

    execute(ctx) -> DocSet(rows: int64[], scores: float32[] | None)

Rows are sorted global row ids; scores align with rows. Boolean composition
is set algebra on sorted arrays (intersect/union/diff) with score summing —
the same query/filter-context semantics as the reference (filter clauses
never score, `BoolQueryBuilder`), shaped so score math stays in numpy and
can batch to the device.

BM25 matches Lucene's BM25Similarity (k1=1.2, b=0.75):
    idf = ln(1 + (N - df + 0.5) / (df + 0.5))
    tf  = f / (f + k1 * (1 - b + b * len / avg_len))
    score = idf * tf * (k1 + 1)
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu import native
from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError
from elasticsearch_tpu.index.mapping import (
    BooleanFieldMapper, DateFieldMapper, DateNanosFieldMapper,
    DenseVectorFieldMapper, IpFieldMapper,
    KeywordFieldMapper, MapperService, RangeFieldMapperBase, TextFieldMapper,
    _NumericMapper, parse_date_millis,
)
from elasticsearch_tpu.index.segment import ShardReader

BM25_K1 = 1.2
BM25_B = 0.75


class DocSet:
    """Sorted matching rows + aligned scores (None in filter context)."""

    __slots__ = ("rows", "scores")

    def __init__(self, rows: np.ndarray, scores: Optional[np.ndarray] = None):
        self.rows = rows
        self.scores = scores

    @staticmethod
    def empty() -> "DocSet":
        return DocSet(np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32))

    def with_scores(self) -> "DocSet":
        if self.scores is None:
            return DocSet(self.rows, np.zeros(len(self.rows), dtype=np.float32))
        return self

    def constant(self, value: float = 0.0) -> "DocSet":
        return DocSet(self.rows, np.full(len(self.rows), value, dtype=np.float32))


class SearchContext:
    """Per-shard execution context (reference: SearchContext/QueryShardContext)."""

    def __init__(self, reader: ShardReader, mapper_service: MapperService,
                 query_cache=None):
        self.reader = reader
        self.mapper_service = mapper_service
        self._all_rows: Optional[np.ndarray] = None
        # node query cache (search/caches.py): filter-context row arrays
        # keyed on (reader gen, filter source); None disables caching
        self.query_cache = query_cache
        # search.max_buckets cluster setting (MultiBucketConsumerService);
        # None = unlimited, set by the search entry from cluster settings
        self.max_buckets: Optional[int] = None

    def all_rows(self) -> np.ndarray:
        if self._all_rows is None:
            self._all_rows = np.sort(self.reader.live_global_rows())
        return self._all_rows


class Query:
    def execute(self, ctx: SearchContext) -> DocSet:
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Leaf queries
# ---------------------------------------------------------------------------

class MatchAllQuery(Query):
    def __init__(self, boost: float = 1.0):
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        rows = ctx.all_rows()
        return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))

    def to_dict(self):
        return {"match_all": {}}


class MatchNoneQuery(Query):
    def execute(self, ctx):
        return DocSet.empty()

    def to_dict(self):
        return {"match_none": {}}


def _id_rows(ctx: SearchContext, ids) -> np.ndarray:
    """Rows for _id metadata-field lookups (term/terms/ids queries on _id).
    The id→row map is built once per reader (the Lucene _id terms dict)."""
    cache = getattr(ctx.reader, "_id_row_cache", None)
    if cache is None:
        cache = {}
        for v in ctx.reader.views:
            seg = v.segment
            for local, did in enumerate(seg.ids):
                if v.live[local]:
                    cache[did] = seg.base + local
        ctx.reader._id_row_cache = cache
    rows = sorted(r for r in (cache.get(str(i)) for i in ids) if r is not None)
    return np.asarray(rows, dtype=np.int64)


def _term_postings(ctx: SearchContext, field: str, term: str):
    """Collect (rows, freqs) for a term across segments, live docs only."""
    field = ctx.mapper_service.resolve_field(field)
    rows_parts, freq_parts = [], []
    for view in ctx.reader.views:
        p = view.segment.get_postings(field, term)
        if p is None:
            continue
        live = view.live[p.doc_ids]
        ids = p.doc_ids[live]
        rows_parts.append(ids.astype(np.int64) + view.segment.base)
        freq_parts.append(p.freqs[live])
    if not rows_parts:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int32)
    return np.concatenate(rows_parts), np.concatenate(freq_parts)


def _field_lengths_for(ctx: SearchContext, field: str, rows: np.ndarray) -> np.ndarray:
    out = np.zeros(len(rows), dtype=np.float32)
    for view in ctx.reader.views:
        seg = view.segment
        fl = seg.field_lengths.get(field)
        if fl is None:
            continue
        in_seg = (rows >= seg.base) & (rows < seg.base + seg.num_docs)
        out[in_seg] = fl[rows[in_seg] - seg.base]
    return out


def bm25_scores(ctx: SearchContext, field: str, rows: np.ndarray,
                freqs: np.ndarray, boost: float = 1.0) -> np.ndarray:
    field = ctx.mapper_service.resolve_field(field)
    n = max(ctx.reader.docs_with_field_count(field), 1)
    df = len(rows)
    idf = math.log(1.0 + (n - df + 0.5) / (df + 0.5))
    avg_len = ctx.reader.avg_field_length(field) or 1.0
    lengths = _field_lengths_for(ctx, field, rows)
    return native.bm25_score(freqs, lengths, idf, avg_len,
                             BM25_K1, BM25_B, boost)


def _index_term_for(mapper, value: Any) -> Optional[str]:
    """Coerce a query value to the indexed term representation."""
    if mapper is None:
        return str(value)
    try:
        terms = mapper.index_terms(value)
    except Exception:
        return None
    return terms[0] if terms else None


class TermQuery(Query):
    def __init__(self, field: str, value: Any, boost: float = 1.0):
        self.field = field
        self.value = value
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        if self.field == "_id":
            rows = _id_rows(ctx, [self.value])
            return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))
        mapper = ctx.mapper_service.get(self.field)
        if isinstance(mapper, RangeFieldMapperBase):
            # membership: the queried point lies inside the stored interval
            v = mapper.query_bound(self.value)
            return _scan_range_docs(
                ctx, ctx.mapper_service.resolve_field(self.field),
                lambda lo, hi: lo <= v <= hi, self.boost)
        if isinstance(mapper, TextFieldMapper):
            # term query on text matches the single analyzed-or-raw token as-is
            term = str(self.value)
        else:
            term = _index_term_for(mapper, self.value)
            if term is None:
                return DocSet.empty()
        rows, freqs = _term_postings(ctx, self.field, term)
        order = np.argsort(rows, kind="stable")
        rows, freqs = rows[order], freqs[order]
        if isinstance(mapper, TextFieldMapper):
            scores = bm25_scores(ctx, self.field, rows, freqs, self.boost)
        else:
            scores = np.full(len(rows), self.boost, dtype=np.float32)
        return DocSet(rows, scores)

    def to_dict(self):
        return {"term": {self.field: {"value": self.value, "boost": self.boost}}}


class TermsQuery(Query):
    def __init__(self, field: str, values: List[Any], boost: float = 1.0,
                 user_supplied: bool = False):
        self.field = field
        self.values = values
        self.boost = boost
        # index.max_terms_count bounds only caller-provided term arrays;
        # internal multi-term rewrites (prefix/wildcard/regexp expansion)
        # are governed by max_clause_count in the reference
        self.user_supplied = user_supplied

    def execute(self, ctx: SearchContext) -> DocSet:
        max_terms = int(getattr(ctx, "index_settings", {})
                        .get("index.max_terms_count", 65536))
        if self.user_supplied and len(self.values) > max_terms:
            raise IllegalArgumentError(
                f"The number of terms [{len(self.values)}] used in the "
                f"Terms Query request has exceeded the allowed maximum "
                f"of [{max_terms}]. This maximum can be set by changing "
                f"the [index.max_terms_count] index level setting.")
        if self.field == "_id":
            rows = _id_rows(ctx, self.values)
            return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))
        mapper = ctx.mapper_service.get(self.field)
        all_rows = []
        for v in self.values:
            term = str(v) if isinstance(mapper, TextFieldMapper) else _index_term_for(mapper, v)
            if term is None:
                continue
            rows, _ = _term_postings(ctx, self.field, term)
            all_rows.append(rows)
        if not all_rows:
            return DocSet.empty()
        rows = np.unique(np.concatenate(all_rows))
        return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))

    def to_dict(self):
        return {"terms": {self.field: self.values}}


class MatchQuery(Query):
    def __init__(self, field: str, text: Any, operator: str = "or",
                 minimum_should_match: Optional[int] = None, boost: float = 1.0,
                 fuzziness: Optional[str] = None):
        self.field = field
        self.text = text
        self.operator = operator.lower()
        self.minimum_should_match = minimum_should_match
        self.boost = boost
        self.fuzziness = fuzziness

    def _analyzed_terms(self, ctx: SearchContext) -> List[str]:
        mapper = ctx.mapper_service.get(self.field)
        if isinstance(mapper, TextFieldMapper):
            return mapper.search_analyzer.terms(str(self.text))
        term = str(self.text) if mapper is None else _index_term_for(mapper, self.text)
        return [term] if term is not None else []

    def execute(self, ctx: SearchContext) -> DocSet:
        terms = self._analyzed_terms(ctx)
        if not terms:
            return DocSet.empty()
        if self.fuzziness is not None:
            expanded = []
            for t in terms:
                expanded.extend(_fuzzy_expand(ctx, self.field, t, self.fuzziness))
            terms = expanded or terms
        clause_sets = []
        for t in terms:
            rows, freqs = _term_postings(ctx, self.field, t)
            order = np.argsort(rows, kind="stable")
            rows, freqs = rows[order], freqs[order]
            scores = bm25_scores(ctx, self.field, rows, freqs, self.boost)
            clause_sets.append(DocSet(rows, scores))
        if self.operator == "and":
            required = len(clause_sets)
        else:
            required = resolve_msm(self.minimum_should_match, len(clause_sets))
        return _combine_should(clause_sets, required)

    def to_dict(self):
        return {"match": {self.field: {"query": self.text, "operator": self.operator}}}


class MatchPhraseQuery(Query):
    def __init__(self, field: str, text: str, slop: int = 0, boost: float = 1.0):
        self.field = field
        self.text = text
        self.slop = slop
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        mapper = ctx.mapper_service.get(self.field)
        if not isinstance(mapper, TextFieldMapper):
            return TermQuery(self.field, self.text, self.boost).execute(ctx)
        terms = mapper.search_analyzer.terms(str(self.text))
        if not terms:
            return DocSet.empty()
        rows_out, scores_out = [], []
        for view in ctx.reader.views:
            seg = view.segment
            plists = [seg.get_postings(self.field, t) for t in terms]
            if any(p is None or p.positions is None for p in plists):
                if any(p is None for p in plists):
                    continue
            # candidate docs: intersection of all term postings
            cand = plists[0].doc_ids
            for p in plists[1:]:
                cand = np.intersect1d(cand, p.doc_ids, assume_unique=True)
            for local in cand:
                if not view.live[local]:
                    continue
                pos_lists = []
                ok = True
                for p in plists:
                    idx = int(np.searchsorted(p.doc_ids, local))
                    pl = p.positions[idx] if p.positions else None
                    if pl is None:
                        ok = False
                        break
                    pos_lists.append(set(pl))
                if not ok:
                    continue
                if _phrase_match(pos_lists, self.slop):
                    rows_out.append(seg.base + int(local))
        if not rows_out:
            return DocSet.empty()
        rows = np.asarray(sorted(rows_out), dtype=np.int64)
        # phrase scoring: sum of member-term BM25, like Lucene's PhraseQuery approx
        total = np.zeros(len(rows), dtype=np.float32)
        for t in terms:
            trows, tfreqs = _term_postings(ctx, self.field, t)
            order = np.argsort(trows, kind="stable")
            trows, tfreqs = trows[order], tfreqs[order]
            ts = bm25_scores(ctx, self.field, trows, tfreqs, self.boost)
            idx = np.searchsorted(trows, rows)
            idx = np.clip(idx, 0, len(trows) - 1)
            hit = trows[idx] == rows
            total[hit] += ts[idx][hit]
        return DocSet(rows, total)

    def to_dict(self):
        return {"match_phrase": {self.field: {"query": self.text, "slop": self.slop}}}


def _phrase_match(pos_sets: List[set], slop: int) -> bool:
    first = pos_sets[0]
    for start in first:
        if _phrase_from(pos_sets, 1, start, slop):
            return True
    return False


def _phrase_from(pos_sets, i, prev, slop) -> bool:
    if i == len(pos_sets):
        return True
    for p in pos_sets[i]:
        if 0 < p - prev <= 1 + slop:
            if _phrase_from(pos_sets, i + 1, p, slop):
                return True
    return False


def scan_doc_values(ctx: SearchContext, field: str, value_match,
                    boost: float = 1.0) -> DocSet:
    """Docs whose (possibly multi-valued) doc value satisfies value_match —
    the shared scan for fields matched by value inspection rather than
    postings (range fields, geo shapes)."""
    rows_parts = []
    for view in ctx.reader.views:
        seg = view.segment
        col = seg.doc_values.get(field)
        if col is None:
            continue
        locs = []
        for i, v in enumerate(col.values):
            if v is None or not view.live[i]:
                continue
            if any(value_match(item) for item in
                   (v if isinstance(v, list) else [v])):
                locs.append(i)
        if locs:
            rows_parts.append(np.asarray(locs, dtype=np.int64) + seg.base)
    if not rows_parts:
        return DocSet.empty()
    rows = np.sort(np.concatenate(rows_parts))
    return DocSet(rows, np.full(len(rows), boost, dtype=np.float32))


def _scan_range_docs(ctx: SearchContext, field: str, predicate,
                     boost: float) -> DocSet:
    """Range-field scan: predicate over the stored inclusive interval."""
    return scan_doc_values(
        ctx, field,
        lambda v: isinstance(v, dict) and predicate(v.get("gte", -np.inf),
                                                    v.get("lte", np.inf)),
        boost)


class RangeQuery(Query):
    def __init__(self, field: str, gte=None, gt=None, lte=None, lt=None,
                 boost: float = 1.0, fmt: Optional[str] = None,
                 relation: str = "intersects"):
        self.field = field
        self.gte, self.gt, self.lte, self.lt = gte, gt, lte, lt
        self.boost = boost
        self.relation = relation

    def _coerce_bound(self, ctx, value, round_up: bool = False):
        from elasticsearch_tpu.index.mapping import parse_date_nanos
        mapper = ctx.mapper_service.get(self.field)
        if isinstance(mapper, DateNanosFieldMapper):
            if isinstance(value, str) and ("||" in value
                                           or value.startswith("now")
                                           or round_up):
                return float(parse_date_millis(value, round_up=round_up)
                             * 1_000_000)
            return float(parse_date_nanos(value))
        if isinstance(mapper, DateFieldMapper):
            # same unit as storage; gt/lte round date math UP to unit end
            # (JavaDateMathParser roundUp semantics); custom locale-aware
            # formats parse through the mapper's formatter
            fmt = str(mapper.params.get("format", ""))
            if isinstance(value, str) and fmt \
                    and ("E" in fmt or "MMM" in fmt):
                try:
                    return float(mapper._parse(value))
                except Exception:
                    pass
            return float(parse_date_millis(value, round_up=round_up))
        if isinstance(mapper, IpFieldMapper):
            return float(mapper.coerce(value))
        if isinstance(mapper, RangeFieldMapperBase):
            return mapper.query_bound(value, round_up=round_up)
        return float(value)

    def execute(self, ctx: SearchContext) -> DocSet:
        mapper = ctx.mapper_service.get(self.field)
        if isinstance(mapper, (TextFieldMapper, KeywordFieldMapper)) \
                and getattr(ctx, "allow_expensive", True) is False:
            # term-scan ranges over strings are the expensive path
            # (TermBasedFieldType rangeQuery gate)
            raise IllegalArgumentError(
                "[range] queries on [text] or [keyword] fields cannot be "
                "executed when 'search.allow_expensive_queries' is set to "
                "false.")
        lo = -np.inf
        hi = np.inf
        lo_inc = hi_inc = True
        numeric_bounds = True
        try:
            if self.gte is not None:
                lo = self._coerce_bound(ctx, self.gte)
            if self.gt is not None:
                lo, lo_inc = self._coerce_bound(ctx, self.gt,
                                                round_up=True), False
            if self.lte is not None:
                hi = self._coerce_bound(ctx, self.lte, round_up=True)
            if self.lt is not None:
                hi, hi_inc = self._coerce_bound(ctx, self.lt), False
        except (ValueError, TypeError) as e:
            # on a NUMERIC/date/ip field an unparseable bound is the
            # caller's error — never silently degrade to string compare
            mapper = ctx.mapper_service.get(self.field)
            if isinstance(mapper, (_NumericMapper, DateFieldMapper,
                                   IpFieldMapper, RangeFieldMapperBase,
                                   BooleanFieldMapper)):
                raise IllegalArgumentError(
                    f"failed to parse range bound on field "
                    f"[{self.field}]: {e}")
            # keyword/text/unmapped: the string-doc-values path applies
            numeric_bounds = False

        mapper = ctx.mapper_service.get(self.field)
        if isinstance(mapper, RangeFieldMapperBase):
            # interval-vs-interval with the requested relation
            # (reference: RangeFieldMapper query relations)
            qlo = lo if lo_inc else (lo + 1 if mapper.discrete
                                     else float(np.nextafter(lo, np.inf)))
            qhi = hi if hi_inc else (hi - 1 if mapper.discrete
                                     else float(np.nextafter(hi, -np.inf)))
            if self.relation == "within":     # stored ⊆ query
                pred = lambda slo, shi: slo >= qlo and shi <= qhi
            elif self.relation == "contains":  # stored ⊇ query
                pred = lambda slo, shi: slo <= qlo and shi >= qhi
            else:                              # intersects
                pred = lambda slo, shi: slo <= qhi and shi >= qlo
            return _scan_range_docs(
                ctx, ctx.mapper_service.resolve_field(self.field),
                pred, self.boost)

        field = ctx.mapper_service.resolve_field(self.field)
        rows_parts = []
        for view in ctx.reader.views:
            seg = view.segment
            col = seg.doc_values.get(field)
            if col is None or col.numeric is None or not numeric_bounds:
                # fall back to string doc values (keyword ranges)
                if col is not None:
                    locs = [i for i, v in enumerate(col.values)
                            if v is not None and view.live[i]
                            and _str_in_range(v, self.gte, self.gt, self.lte, self.lt)]
                    if locs:
                        rows_parts.append(np.asarray(locs, dtype=np.int64) + seg.base)
                continue
            vals = col.numeric
            mask = col.present & view.live
            mask &= (vals >= lo) if lo_inc else (vals > lo)
            mask &= (vals <= hi) if hi_inc else (vals < hi)
            locs = np.nonzero(mask)[0]
            if len(locs):
                rows_parts.append(locs.astype(np.int64) + seg.base)
        if not rows_parts:
            return DocSet.empty()
        rows = np.sort(np.concatenate(rows_parts))
        return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))

    def to_dict(self):
        body = {}
        for k in ("gte", "gt", "lte", "lt"):
            v = getattr(self, k)
            if v is not None:
                body[k] = v
        return {"range": {self.field: body}}


def _str_in_range(v, gte, gt, lte, lt) -> bool:
    s = str(v)
    if gte is not None and s < str(gte):
        return False
    if gt is not None and s <= str(gt):
        return False
    if lte is not None and s > str(lte):
        return False
    if lt is not None and s >= str(lt):
        return False
    return True


class ExistsQuery(Query):
    def __init__(self, field: str, boost: float = 1.0):
        self.field = field
        self.boost = boost

    _META_ALWAYS = {"_id", "_index", "_type", "_seq_no", "_primary_term",
                    "_version"}

    def execute(self, ctx: SearchContext) -> DocSet:
        from elasticsearch_tpu.common.errors import QueryShardError
        field = ctx.mapper_service.resolve_field(self.field)
        if field == "_source":
            # ExistsQueryBuilder rejects _source outright
            raise QueryShardError(
                "Cannot run exists query on [_source]")
        if field in self._META_ALWAYS:
            # metadata every live doc carries: all docs match
            rows_parts = [
                (np.nonzero(view.live)[0].astype(np.int64)
                 + view.segment.base)
                for view in ctx.reader.views]
            rows = (np.sort(np.concatenate(rows_parts))
                    if rows_parts else np.zeros(0, dtype=np.int64))
            return DocSet(rows, np.full(len(rows), self.boost,
                                        dtype=np.float32))
        prefix = field + "."
        rows_parts = []
        for view in ctx.reader.views:
            seg = view.segment
            mask = None
            # direct columns plus subfield columns: an `object` field
            # exists wherever ANY of its properties does (the reference
            # rewrites object exists to a sub-field disjunction)
            for store, extract in ((seg.doc_values,
                                    lambda c: c.present),
                                   (seg.field_lengths, lambda fl: fl > 0),
                                   (seg.vectors, lambda v: v[1])):
                for name, col in store.items():
                    if name == field or name.startswith(prefix):
                        m = extract(col)
                        mask = m.copy() if mask is None else (mask | m)
            if mask is None:
                continue
            locs = np.nonzero(mask & view.live)[0]
            if len(locs):
                rows_parts.append(locs.astype(np.int64) + seg.base)
        if not rows_parts:
            # columnless MAPPED fields (e.g. binary with doc_values:
            # false, object with unindexed members): fall back to a
            # stored-source presence walk — unmapped fields still return
            # empty without scanning
            mapper = ctx.mapper_service.get(field)
            if mapper is not None or self._maps_object(ctx, prefix):
                rows_parts = self._source_walk(ctx, field)
        if not rows_parts:
            return DocSet.empty()
        rows = np.sort(np.concatenate(rows_parts))
        return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))

    @staticmethod
    def _maps_object(ctx, prefix: str) -> bool:
        to_dict = getattr(ctx.mapper_service, "to_dict", None)
        if to_dict is None:
            return False

        def walk(props, pre=""):
            for name, d in (props or {}).items():
                full = pre + name
                if full == prefix[:-1] or full.startswith(prefix):
                    return True
                if isinstance(d, dict) and "properties" in d:
                    if walk(d["properties"], full + "."):
                        return True
            return False
        return walk((to_dict() or {}).get("properties"))

    def _source_walk(self, ctx, field: str):
        parts = field.split(".")
        rows_parts = []
        for view in ctx.reader.views:
            seg = view.segment
            hits = []
            for local in np.nonzero(view.live)[0]:
                node = ctx.reader.get_source(int(seg.base + local)) or {}
                for p in parts:
                    node = node.get(p) if isinstance(node, dict) else None
                    if node is None:
                        break
                if node is not None:
                    hits.append(int(seg.base + local))
            if hits:
                rows_parts.append(np.asarray(hits, dtype=np.int64))
        return rows_parts

    def to_dict(self):
        return {"exists": {"field": self.field}}


class IdsQuery(Query):
    def __init__(self, values: List[str], boost: float = 1.0):
        self.values = values
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        wanted = set(map(str, self.values))
        rows = []
        for view in ctx.reader.views:
            seg = view.segment
            for local, doc_id in enumerate(seg.ids):
                if doc_id in wanted and view.live[local]:
                    rows.append(seg.base + local)
        rows = np.asarray(sorted(rows), dtype=np.int64)
        return DocSet(rows, np.full(len(rows), self.boost, dtype=np.float32))

    def to_dict(self):
        return {"ids": {"values": self.values}}


def _pattern_terms(ctx: SearchContext, field: str, predicate) -> List[str]:
    field = ctx.mapper_service.resolve_field(field)
    seen = set()
    for view in ctx.reader.views:
        for term in view.segment.terms_of(field):
            if term not in seen and predicate(term):
                seen.add(term)
    return sorted(seen)


def _check_expensive(ctx: SearchContext, qtype: str, extra: str = "") -> None:
    """search.allow_expensive_queries gate (QueryShardContext
    allowExpensiveQueries)."""
    if getattr(ctx, "allow_expensive", True) is False:
        raise IllegalArgumentError(
            f"[{qtype}] queries cannot be executed when "
            f"'search.allow_expensive_queries' is set to false.{extra}")


class PrefixQuery(Query):
    def __init__(self, field: str, value: str, boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "prefix",
                         " For optimised prefix queries on text fields "
                         "please enable [index_prefixes].")
        terms = _pattern_terms(ctx, self.field, lambda t: t.startswith(self.value))
        return TermsQuery(self.field, terms, self.boost).execute(ctx) if terms else DocSet.empty()

    def to_dict(self):
        return {"prefix": {self.field: {"value": self.value}}}


class WildcardQuery(Query):
    def __init__(self, field: str, value: str, boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "wildcard")
        pattern = re.compile(
            "^" + "".join(".*" if c == "*" else "." if c == "?" else re.escape(c)
                          for c in self.value) + "$")
        terms = _pattern_terms(ctx, self.field, lambda t: pattern.match(t) is not None)
        return TermsQuery(self.field, terms, self.boost).execute(ctx) if terms else DocSet.empty()

    def to_dict(self):
        return {"wildcard": {self.field: {"value": self.value}}}


class RegexpQuery(Query):
    def __init__(self, field: str, value: str, boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "regexp")
        max_len = int(getattr(ctx, "index_settings", {}).get(
            "index.max_regex_length", 1000))
        if len(self.value) > max_len:
            raise IllegalArgumentError(
                f"The length of regex [{len(self.value)}] used in the "
                f"Regexp Query request has exceeded the allowed maximum "
                f"of [{max_len}]. This maximum can be set by changing the "
                f"[index.max_regex_length] index level setting.")
        try:
            pattern = re.compile("^" + self.value + "$")
        except re.error as e:
            raise IllegalArgumentError(f"invalid regexp [{self.value}]: {e}")
        terms = _pattern_terms(ctx, self.field, lambda t: pattern.match(t) is not None)
        return TermsQuery(self.field, terms, self.boost).execute(ctx) if terms else DocSet.empty()

    def to_dict(self):
        return {"regexp": {self.field: {"value": self.value}}}


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Restricted Damerau-Levenshtein (OSA) — Lucene's fuzzy automata count
    an adjacent transposition as ONE edit (transpositions=true default)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev2: Optional[List[int]] = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        best = cur[0]
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            if (prev2 is not None and i > 1 and j > 1
                    and ca == b[j - 2] and a[i - 2] == cb):
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            best = min(best, cur[j])
        if best > k:
            return False
        prev2, prev = prev, cur
    return prev[-1] <= k


def _fuzzy_expand(ctx: SearchContext, field: str, term: str, fuzziness) -> List[str]:
    if fuzziness in ("AUTO", "auto", None):
        k = 0 if len(term) <= 2 else 1 if len(term) <= 5 else 2
    else:
        k = int(fuzziness)
    if k == 0:
        return [term]
    return _pattern_terms(ctx, field, lambda t: _edit_distance_le(term, t, k))


class FuzzyQuery(Query):
    def __init__(self, field: str, value: str, fuzziness="AUTO", boost: float = 1.0):
        self.field = field
        self.value = str(value)
        self.fuzziness = fuzziness
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        _check_expensive(ctx, "fuzzy")
        terms = _fuzzy_expand(ctx, self.field, self.value, self.fuzziness)
        if not terms:
            return DocSet.empty()
        sets = [TermQuery(self.field, t, self.boost).execute(ctx) for t in terms]
        return _combine_should(sets, 1)

    def to_dict(self):
        return {"fuzzy": {self.field: {"value": self.value, "fuzziness": self.fuzziness}}}


class MatchPhrasePrefixQuery(Query):
    def __init__(self, field: str, text: str, boost: float = 1.0):
        self.field = field
        self.text = str(text)
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        mapper = ctx.mapper_service.get(self.field)
        if not isinstance(mapper, TextFieldMapper):
            return PrefixQuery(self.field, self.text, self.boost).execute(ctx)
        terms = mapper.search_analyzer.terms(self.text)
        if not terms:
            return DocSet.empty()
        *head, last = terms
        expansions = _pattern_terms(ctx, self.field, lambda t: t.startswith(last))[:50]
        if not expansions:
            return DocSet.empty()
        sets = []
        for exp in expansions:
            phrase = " ".join(head + [exp]) if head else exp
            sets.append(MatchPhraseQuery(self.field, phrase, boost=self.boost).execute(ctx))
        return _combine_should(sets, 1)

    def to_dict(self):
        return {"match_phrase_prefix": {self.field: {"query": self.text}}}


class MatchBoolPrefixQuery(Query):
    """`match_bool_prefix` (reference: MatchBoolPrefixQueryBuilder): analyze
    the text; every term is a SHOULD term clause except the last, which
    matches as a prefix. The canonical companion of search_as_you_type."""

    def __init__(self, field: str, text: str, boost: float = 1.0,
                 operator: str = "or",
                 minimum_should_match=None, analyzer: Optional[str] = None,
                 fuzziness=None):
        self.field = field
        self.text = str(text)
        self.boost = boost
        self.operator = str(operator).lower()
        self.minimum_should_match = minimum_should_match
        self.analyzer = analyzer
        self.fuzziness = fuzziness

    def execute(self, ctx: SearchContext) -> DocSet:
        mapper = ctx.mapper_service.get(self.field)
        if self.analyzer is not None:
            terms = ctx.mapper_service.registry.get(self.analyzer).terms(
                self.text)
        elif isinstance(mapper, TextFieldMapper):
            terms = mapper.search_analyzer.terms(self.text)
        else:
            terms = [self.text]
        if not terms:
            return DocSet.empty()
        *head, last = terms
        if self.fuzziness is not None:
            # fuzziness applies to the complete (non-prefix) terms only
            # (MatchBoolPrefixQueryBuilder setFuzziness)
            sets = [FuzzyQuery(self.field, t, self.fuzziness,
                               self.boost).execute(ctx) for t in head]
        else:
            sets = [TermQuery(self.field, t, self.boost).execute(ctx)
                    for t in head]
        sets.append(PrefixQuery(self.field, last, self.boost).execute(ctx))
        if self.minimum_should_match is not None:
            required = resolve_msm(self.minimum_should_match, len(sets))
        else:
            required = len(sets) if self.operator == "and" else 1
        return _combine_should(sets, required)

    def to_dict(self):
        return {"match_bool_prefix": {self.field: {"query": self.text}}}


class QueryStringQuery(Query):
    """Lucene-lite query_string (reference: `index/query/QueryStringQueryBuilder`
    via Lucene's classic QueryParser): supports `field:value`, quoted phrases,
    AND/OR/NOT operators, and free terms over default_field or all text
    fields."""

    def __init__(self, query: str, default_fields=None,
                 default_operator: str = "or", boost: float = 1.0):
        self.query = str(query)
        if isinstance(default_fields, str):
            default_fields = [default_fields]
        self.default_fields_param = list(default_fields or [])
        op = str(default_operator).strip().lower()
        if op not in ("and", "or"):
            raise ParsingError(f"invalid default_operator [{default_operator}], expected AND or OR")
        self.default_operator = op
        self.boost = boost

    _TOKEN_RE = re.compile(
        r'([+-]?)(?:(\w[\w.]*):)?'
        r'("(?:[^"]*)"|[\[{][^\]}]*[\]}]|\S+)')

    _RANGE_RE = re.compile(
        r'^([\[{])\s*(\S+)\s+TO\s+(\S+)\s*([\]}])$')

    def _default_fields(self, ctx: SearchContext) -> List[str]:
        fields = [f for f in self.default_fields_param if f != "*"]
        if fields:
            return [f.split("^")[0] for f in fields]
        return [p for p in ctx.mapper_service.field_names()
                if isinstance(ctx.mapper_service.get(p), TextFieldMapper)]

    def execute(self, ctx: SearchContext) -> DocSet:
        if self.query.strip() == "*":
            return MatchAllQuery(self.boost).execute(ctx)

        # Lucene regex syntax: a whole-query /re/ compiles to a RegexpQuery
        # per default field (QueryParserBase.getRegexpQuery) — length limits
        # apply before matching
        q = self.query.strip()
        if len(q) > 2 and q.startswith("/") and q.endswith("/"):
            fields = self._default_fields(ctx) or ["_all"]
            subs = [RegexpQuery(f, q[1:-1]) for f in fields]
            sub = subs[0] if len(subs) == 1 else DisMaxQuery(subs)
            return sub.execute(ctx)

        # pass 1: tokenize into clauses and the connectors between them
        clauses: List[dict] = []       # {sign, field, text, phrase, negated}
        connectors: List[Optional[str]] = []  # between clause i and i+1
        negate_next = False
        for m in self._TOKEN_RE.finditer(self.query):
            sign, field, text = m.group(1), m.group(2), m.group(3)
            if text in ("AND", "OR"):
                if connectors:
                    connectors[-1] = text
                continue
            if text == "NOT":
                negate_next = True
                continue
            phrase = text.startswith('"') and text.endswith('"')
            clauses.append({"sign": sign, "field": field,
                            "text": text[1:-1] if phrase else text,
                            "phrase": phrase, "negated": negate_next})
            negate_next = False
            connectors.append(None)

        if not clauses:
            return DocSet.empty()

        # pass 2: resolve required/optional — an explicit AND binds BOTH
        # neighbors; an explicit OR makes both optional; otherwise the
        # default operator decides (Lucene classic parser semantics).
        n = len(clauses)
        required = [self.default_operator == "and"] * n
        for i in range(n - 1):
            if connectors[i] == "AND":
                required[i] = required[i + 1] = True
            elif connectors[i] == "OR":
                required[i] = required[i + 1] = False

        must: List[Query] = []
        should: List[Query] = []
        must_not: List[Query] = []
        for i, c in enumerate(clauses):
            # sub-queries carry boost 1.0 — the wrapping BoolQuery applies
            # self.boost exactly once
            if c["field"]:
                range_m = self._RANGE_RE.match(c["text"])
                if range_m and not c["phrase"]:
                    # Lucene range syntax: [a TO b] inclusive, {a TO b}
                    # exclusive, * = open bound
                    open_b, lo, hi, close_b = range_m.groups()
                    kw = {}
                    if lo != "*":
                        kw["gte" if open_b == "[" else "gt"] = lo
                    if hi != "*":
                        kw["lte" if close_b == "]" else "lt"] = hi
                    sub: Query = RangeQuery(c["field"], **kw)
                elif (len(c["text"]) > 2 and c["text"].startswith("/")
                      and c["text"].endswith("/") and not c["phrase"]):
                    sub = RegexpQuery(c["field"], c["text"][1:-1])
                elif not c["phrase"] and ("*" in c["text"]
                                          or "?" in c["text"]):
                    # wildcard terms normalize through the analyzer chain
                    # (QueryParserBase.getWildcardQuery + normalization)
                    sub = WildcardQuery(c["field"], c["text"].lower())
                else:
                    sub = (MatchPhraseQuery(c["field"], c["text"])
                           if c["phrase"]
                           else MatchQuery(c["field"], c["text"]))
            else:
                fields = self._default_fields(ctx)
                if not c["phrase"] and ("*" in c["text"]
                                        or "?" in c["text"]):
                    # default-field wildcards behave like the fielded form
                    subs: List[Query] = [
                        WildcardQuery(f, c["text"].lower()) for f in fields]
                else:
                    subs = [
                        MatchPhraseQuery(f, c["text"]) if c["phrase"]
                        else MatchQuery(f, c["text"])
                        for f in fields]
                if not subs:
                    continue
                sub = subs[0] if len(subs) == 1 else DisMaxQuery(subs)
            if c["sign"] == "-" or c["negated"]:
                must_not.append(sub)
            elif c["sign"] == "+" or required[i]:
                must.append(sub)
            else:
                should.append(sub)
        if not (must or should or must_not):
            return DocSet.empty()
        return BoolQuery(must=must, should=should, must_not=must_not,
                         boost=self.boost).execute(ctx)

    def to_dict(self):
        return {"query_string": {"query": self.query}}


class MultiMatchQuery(Query):
    def __init__(self, query: str, fields: List[str], mm_type: str = "best_fields",
                 operator: str = "or", boost: float = 1.0,
                 analyzer: Optional[str] = None, minimum_should_match=None,
                 fuzziness=None):
        self.query = query
        self.fields = fields
        self.mm_type = mm_type
        self.operator = operator
        self.boost = boost
        self.analyzer = analyzer
        self.minimum_should_match = minimum_should_match
        self.fuzziness = fuzziness

    def execute(self, ctx: SearchContext) -> DocSet:
        def split_boost(f):
            if "^" in f:
                name, b = f.split("^", 1)
                return name, float(b)
            return f, 1.0

        # wildcard field patterns expand against the mapping
        # (QueryParserHelper.resolveMappingFields)
        import fnmatch as _fn
        resolved: List[str] = []
        for f in self.fields:
            name, _b = split_boost(f)
            if "*" in name:
                suffix = f[len(name):]
                for path, m in ctx.mapper_service.all_mappers():
                    if getattr(m, "type_name", None) in ("text", "keyword",
                                                         "search_as_you_type") \
                            and _fn.fnmatch(path, name):
                        resolved.append(path + suffix)
            else:
                resolved.append(f)

        sets = []
        for f in resolved:
            name, fboost = split_boost(f)
            if self.mm_type == "bool_prefix":
                # search_as_you_type target: all terms match, last as prefix
                # (reference: MatchBoolPrefixQueryBuilder)
                sets.append(MatchBoolPrefixQuery(
                    name, self.query, boost=self.boost * fboost,
                    operator=self.operator,
                    minimum_should_match=self.minimum_should_match,
                    analyzer=self.analyzer,
                    fuzziness=self.fuzziness).execute(ctx))
            else:
                sets.append(MatchQuery(name, self.query, operator=self.operator,
                                       boost=self.boost * fboost).execute(ctx))
        if not sets:
            return DocSet.empty()
        if self.mm_type == "best_fields":
            return _combine_max(sets)
        return _combine_should(sets, 1)  # most_fields / bool_prefix: sum

    def to_dict(self):
        return {"multi_match": {"query": self.query, "fields": self.fields,
                                "type": self.mm_type}}


class ConstantScoreQuery(Query):
    def __init__(self, filter_query: Query, boost: float = 1.0):
        self.filter_query = filter_query
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        inner = self.filter_query.execute(ctx)
        return DocSet(inner.rows, np.full(len(inner.rows), self.boost, dtype=np.float32))

    def to_dict(self):
        return {"constant_score": {"filter": self.filter_query.to_dict(),
                                   "boost": self.boost}}


class BoostingQuery(Query):
    def __init__(self, positive: Query, negative: Query, negative_boost: float):
        self.positive = positive
        self.negative = negative
        self.negative_boost = negative_boost

    def execute(self, ctx: SearchContext) -> DocSet:
        pos = self.positive.execute(ctx).with_scores()
        neg = self.negative.execute(ctx)
        scores = pos.scores.copy()
        in_neg = np.isin(pos.rows, neg.rows)
        scores[in_neg] *= self.negative_boost
        return DocSet(pos.rows, scores)

    def to_dict(self):
        return {"boosting": {"positive": self.positive.to_dict(),
                             "negative": self.negative.to_dict(),
                             "negative_boost": self.negative_boost}}


class DisMaxQuery(Query):
    def __init__(self, queries: List[Query], tie_breaker: float = 0.0, boost: float = 1.0):
        self.queries = queries
        self.tie_breaker = tie_breaker
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        sets = [q.execute(ctx).with_scores() for q in self.queries]
        if not sets:
            return DocSet.empty()
        rows = np.unique(np.concatenate([s.rows for s in sets]))
        best = np.zeros(len(rows), dtype=np.float32)
        total = np.zeros(len(rows), dtype=np.float32)
        for s in sets:
            idx = np.searchsorted(rows, s.rows)
            np.maximum.at(best, idx, s.scores)
            np.add.at(total, idx, s.scores)
        scores = best + self.tie_breaker * (total - best)
        return DocSet(rows, scores * self.boost)

    def to_dict(self):
        return {"dis_max": {"queries": [q.to_dict() for q in self.queries],
                            "tie_breaker": self.tie_breaker}}


# ---------------------------------------------------------------------------
# Bool composition
# ---------------------------------------------------------------------------

def resolve_msm(msm, n_clauses: int) -> int:
    """Parse minimum_should_match: int, numeric string, or 'N%' of clauses
    (reference: `Queries.calculateMinShouldMatch`). Negative values mean
    'all but N'."""
    if msm is None:
        return 1
    if isinstance(msm, int):
        value = msm
    else:
        s = str(msm).strip()
        try:
            if s.endswith("%"):
                pct = int(s[:-1])
                value = (n_clauses * pct) // 100 if pct >= 0 else \
                    n_clauses + (n_clauses * pct) // 100
            else:
                value = int(s)
        except ValueError:
            raise ParsingError(f"invalid minimum_should_match [{msm}]")
    if value < 0:
        value = n_clauses + value
    return max(min(value, n_clauses), 0)


def _combine_should(sets: List[DocSet], minimum_match: int) -> DocSet:
    """Union with score summing; keep docs matching >= minimum_match clauses."""
    sets = [s for s in sets]
    if not sets:
        return DocSet.empty()
    if minimum_match <= 1:
        # pure union-sum: fold through the native streaming merge
        rows, scores = sets[0].rows, sets[0].scores
        for s in sets[1:]:
            rows, scores = native.union_sum(rows, scores, s.rows, s.scores)
        return DocSet(rows, scores if scores is not None
                      else np.zeros(len(rows), dtype=np.float32))
    rows = np.unique(np.concatenate([s.rows for s in sets]))
    scores = np.zeros(len(rows), dtype=np.float32)
    counts = np.zeros(len(rows), dtype=np.int32)
    for s in sets:
        if len(s.rows) == 0:
            continue
        idx = np.searchsorted(rows, s.rows)
        np.add.at(scores, idx, s.scores if s.scores is not None else 0.0)
        np.add.at(counts, idx, 1)
    keep = counts >= minimum_match
    return DocSet(rows[keep], scores[keep])


def _combine_max(sets: List[DocSet]) -> DocSet:
    rows = np.unique(np.concatenate([s.rows for s in sets])) if sets else np.zeros(0, np.int64)
    scores = np.zeros(len(rows), dtype=np.float32)
    for s in sets:
        if len(s.rows) == 0:
            continue
        idx = np.searchsorted(rows, s.rows)
        np.maximum.at(scores, idx, s.scores if s.scores is not None else 0.0)
    return DocSet(rows, scores)


def _cached_filter_rows(ctx: SearchContext, q: Query) -> np.ndarray:
    """Filter-context execution through the node query cache: filters never
    score, so the row array alone is the full result (Lucene caches filter
    bitsets the same way; scoring clauses are never cached)."""
    cache = ctx.query_cache
    if cache is None:
        return q.execute(ctx).rows
    try:
        import json
        source = json.dumps(q.to_dict(), sort_keys=True, default=str)
    except Exception:
        return q.execute(ctx).rows
    gen = getattr(ctx.reader, "gen", None)
    if gen is None:
        return q.execute(ctx).rows
    rows = cache.get_rows(gen, source)
    if rows is None:
        rows = q.execute(ctx).rows
        cache.put_rows(gen, source, rows)
    return rows


class BoolQuery(Query):
    """must/filter/should/must_not with reference semantics
    (`index/query/BoolQueryBuilder.java`): filter and must_not never score;
    should adds to the score; minimum_should_match defaults to 1 when there
    are no must/filter clauses, else 0."""

    def __init__(self, must: List[Query] = (), filter: List[Query] = (),
                 should: List[Query] = (), must_not: List[Query] = (),
                 minimum_should_match: Optional[int] = None, boost: float = 1.0):
        self.must = list(must)
        self.filter = list(filter)
        self.should = list(should)
        self.must_not = list(must_not)
        self.minimum_should_match = minimum_should_match
        self.boost = boost

    def execute(self, ctx: SearchContext) -> DocSet:
        rows: Optional[np.ndarray] = None
        scores: Optional[np.ndarray] = None

        for q in self.must:
            s = q.execute(ctx).with_scores()
            if rows is None:
                rows, scores = s.rows, s.scores.copy()
            else:
                i1, i2 = native.intersect_sorted(rows, s.rows)
                rows = rows[i1]
                scores = scores[i1] + s.scores[i2]

        for q in self.filter:
            f_rows = _cached_filter_rows(ctx, q)
            if rows is None:
                rows = f_rows
                scores = np.zeros(len(rows), dtype=np.float32)
            else:
                i1, _ = native.intersect_sorted(rows, f_rows)
                rows = rows[i1]
                scores = scores[i1]

        msm = self.minimum_should_match
        if msm is not None:
            msm = resolve_msm(msm, len(self.should))
        if self.should:
            should_set = _combine_should([q.execute(ctx).with_scores() for q in self.should],
                                         msm if msm is not None else 1)
            if rows is None:
                rows, scores = should_set.rows, should_set.scores
            else:
                if msm is None or msm == 0:
                    # optional should: add scores where they match
                    idx = np.searchsorted(should_set.rows, rows)
                    idx = np.clip(idx, 0, max(len(should_set.rows) - 1, 0))
                    if len(should_set.rows):
                        hit = should_set.rows[idx] == rows
                        scores[hit] += should_set.scores[idx][hit]
                else:
                    i1, i2 = native.intersect_sorted(rows, should_set.rows)
                    rows = rows[i1]
                    scores = scores[i1] + should_set.scores[i2]

        if rows is None:
            rows = ctx.all_rows()
            scores = np.zeros(len(rows), dtype=np.float32)

        for q in self.must_not:
            s = q.execute(ctx)
            keep = ~np.isin(rows, s.rows, assume_unique=True)
            rows, scores = rows[keep], scores[keep]

        return DocSet(rows, scores * self.boost)

    def to_dict(self):
        out = {}
        if self.must:
            out["must"] = [q.to_dict() for q in self.must]
        if self.filter:
            out["filter"] = [q.to_dict() for q in self.filter]
        if self.should:
            out["should"] = [q.to_dict() for q in self.should]
        if self.must_not:
            out["must_not"] = [q.to_dict() for q in self.must_not]
        if self.minimum_should_match is not None:
            out["minimum_should_match"] = self.minimum_should_match
        return {"bool": out}


# ---------------------------------------------------------------------------
# Scoring wrappers
# ---------------------------------------------------------------------------

class FunctionScoreQuery(Query):
    """Subset of function_score (`index/query/functionscore/`): weight,
    field_value_factor, and script-free boost_mode/score_mode algebra."""

    def __init__(self, query: Query, functions: List[dict],
                 boost_mode: str = "multiply", score_mode: str = "multiply"):
        self.query = query
        self.functions = functions
        self.boost_mode = boost_mode
        self.score_mode = score_mode

    def execute(self, ctx: SearchContext) -> DocSet:
        base = self.query.execute(ctx).with_scores()
        if len(base.rows) == 0 or not self.functions:
            return base
        func_scores = []
        for fn in self.functions:
            weight = float(fn.get("weight", 1.0))
            if "field_value_factor" in fn:
                spec = fn["field_value_factor"]
                field = spec["field"]
                factor = float(spec.get("factor", 1.0))
                missing = float(spec.get("missing", 1.0))
                modifier = spec.get("modifier", "none")
                vals = np.full(len(base.rows), missing, dtype=np.float64)
                for i, row in enumerate(base.rows):
                    v = ctx.reader.get_doc_value(field, int(row))
                    if v is not None and not isinstance(v, (list, str, bool)):
                        vals[i] = float(v)
                vals = vals * factor
                if modifier == "log1p":
                    vals = np.log1p(np.maximum(vals, 0))
                elif modifier == "sqrt":
                    vals = np.sqrt(np.maximum(vals, 0))
                elif modifier == "square":
                    vals = vals ** 2
                func_scores.append(weight * vals.astype(np.float32))
            else:
                func_scores.append(np.full(len(base.rows), weight, dtype=np.float32))
        combined = func_scores[0]
        for fs in func_scores[1:]:
            if self.score_mode == "sum":
                combined = combined + fs
            elif self.score_mode == "max":
                combined = np.maximum(combined, fs)
            elif self.score_mode == "min":
                combined = np.minimum(combined, fs)
            elif self.score_mode == "avg":
                combined = (combined + fs) / 2
            else:
                combined = combined * fs
        if self.boost_mode == "replace":
            new = combined
        elif self.boost_mode == "sum":
            new = base.scores + combined
        elif self.boost_mode == "max":
            new = np.maximum(base.scores, combined)
        elif self.boost_mode == "min":
            new = np.minimum(base.scores, combined)
        elif self.boost_mode == "avg":
            new = (base.scores + combined) / 2
        else:
            new = base.scores * combined
        return DocSet(base.rows, new.astype(np.float32))

    def to_dict(self):
        return {"function_score": {"query": self.query.to_dict(),
                                   "functions": self.functions}}


# ---------------------------------------------------------------------------
# Parser: DSL dict -> Query
# ---------------------------------------------------------------------------

def parse_query(body: Optional[dict]) -> Query:
    """Parse the JSON query DSL (reference: QueryBuilders registered in
    `SearchModule.registerQueryParsers`)."""
    if body is None:
        return MatchAllQuery()
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingError(f"query must be an object with exactly one key, got {body!r}")
    kind, spec = next(iter(body.items()))

    if kind == "match_all":
        return MatchAllQuery(boost=float(spec.get("boost", 1.0)) if isinstance(spec, dict) else 1.0)
    if kind == "match_none":
        return MatchNoneQuery()
    if kind == "term":
        field, v = _single(spec, "term")
        if isinstance(v, dict):
            return TermQuery(field, v.get("value"), float(v.get("boost", 1.0)))
        return TermQuery(field, v)
    if kind == "terms":
        spec = dict(spec)
        boost = float(spec.pop("boost", 1.0))
        field, values = _single(spec, "terms")
        if not isinstance(values, list):
            raise ParsingError("[terms] query requires an array of values")
        return TermsQuery(field, values, boost, user_supplied=True)
    if kind == "match":
        field, v = _single(spec, "match")
        if isinstance(v, dict):
            return MatchQuery(field, v.get("query"), v.get("operator", "or"),
                              v.get("minimum_should_match"),
                              float(v.get("boost", 1.0)), v.get("fuzziness"))
        return MatchQuery(field, v)
    if kind == "match_phrase":
        field, v = _single(spec, "match_phrase")
        if isinstance(v, dict):
            return MatchPhraseQuery(field, v.get("query"), int(v.get("slop", 0)),
                                    float(v.get("boost", 1.0)))
        return MatchPhraseQuery(field, v)
    if kind == "match_phrase_prefix":
        field, v = _single(spec, "match_phrase_prefix")
        text = v.get("query") if isinstance(v, dict) else v
        return MatchPhrasePrefixQuery(field, text)
    if kind == "match_bool_prefix":
        field, v = _single(spec, "match_bool_prefix")
        if isinstance(v, dict):
            return MatchBoolPrefixQuery(
                field, v.get("query"), float(v.get("boost", 1.0)),
                v.get("operator", "or"),
                minimum_should_match=v.get("minimum_should_match"),
                analyzer=v.get("analyzer"),
                fuzziness=v.get("fuzziness"))
        return MatchBoolPrefixQuery(field, v)
    if kind in ("query_string", "simple_query_string"):
        fields = spec.get("fields") or (
            [spec["default_field"]] if spec.get("default_field") else [])
        return QueryStringQuery(spec.get("query", ""), fields,
                                spec.get("default_operator", "or"),
                                float(spec.get("boost", 1.0)))
    if kind == "multi_match":
        mmt = spec.get("type", "best_fields")
        if spec.get("slop") is not None and mmt in ("bool_prefix",
                                                    "cross_fields"):
            raise ParsingError(f"[slop] not allowed for type [{mmt}]")
        return MultiMatchQuery(spec.get("query"), spec.get("fields", []),
                               mmt, spec.get("operator", "or"),
                               analyzer=spec.get("analyzer"),
                               minimum_should_match=spec.get(
                                   "minimum_should_match"),
                               fuzziness=spec.get("fuzziness"))
    if kind == "range":
        field, v = _single(spec, "range")
        return RangeQuery(field, gte=v.get("gte", v.get("from")), gt=v.get("gt"),
                          lte=v.get("lte", v.get("to")), lt=v.get("lt"),
                          boost=float(v.get("boost", 1.0)),
                          relation=v.get("relation", "intersects").lower())
    if kind == "exists":
        return ExistsQuery(spec["field"])
    if kind == "ids":
        return IdsQuery(spec.get("values", []))
    if kind == "prefix":
        field, v = _single(spec, "prefix")
        return PrefixQuery(field, v.get("value") if isinstance(v, dict) else v)
    if kind == "span_multi":
        # SpanMultiTermQueryWrapper: a multi-term query (prefix/wildcard/
        # fuzzy/regexp) used in span position; standalone it matches the
        # wrapped query's documents
        return parse_query(spec.get("match") or {"match_all": {}})
    if kind == "wildcard":
        field, v = _single(spec, "wildcard")
        return WildcardQuery(field, (v.get("value") or v.get("wildcard")) if isinstance(v, dict) else v)
    if kind == "regexp":
        field, v = _single(spec, "regexp")
        return RegexpQuery(field, v.get("value") if isinstance(v, dict) else v)
    if kind == "fuzzy":
        field, v = _single(spec, "fuzzy")
        if isinstance(v, dict):
            return FuzzyQuery(field, v.get("value"), v.get("fuzziness", "AUTO"))
        return FuzzyQuery(field, v)
    if kind == "bool":
        def clause(name):
            c = spec.get(name, [])
            if isinstance(c, dict):
                c = [c]
            return [parse_query(q) for q in c]

        return BoolQuery(must=clause("must"), filter=clause("filter"),
                         should=clause("should"), must_not=clause("must_not"),
                         minimum_should_match=spec.get("minimum_should_match"),
                         boost=float(spec.get("boost", 1.0)))
    if kind == "constant_score":
        return ConstantScoreQuery(parse_query(spec["filter"]),
                                  float(spec.get("boost", 1.0)))
    if kind == "boosting":
        return BoostingQuery(parse_query(spec["positive"]),
                             parse_query(spec["negative"]),
                             float(spec.get("negative_boost", 0.5)))
    if kind == "dis_max":
        return DisMaxQuery([parse_query(q) for q in spec.get("queries", [])],
                           float(spec.get("tie_breaker", 0.0)))
    if kind == "function_score":
        inner = parse_query(spec.get("query", {"match_all": {}}))
        functions = spec.get("functions")
        if functions is None:
            functions = [{k: v for k, v in spec.items()
                          if k in ("field_value_factor", "weight")}]
        return FunctionScoreQuery(inner, functions,
                                  spec.get("boost_mode", "multiply"),
                                  spec.get("score_mode", "multiply"))
    if kind == "script_score":
        from elasticsearch_tpu.search.script_score import ScriptScoreQuery
        return ScriptScoreQuery(parse_query(spec.get("query", {"match_all": {}})),
                                spec.get("script", {}))
    if kind == "knn":
        from elasticsearch_tpu.search.knn_query import KnnQuery
        return KnnQuery(field=spec["field"], query_vector=spec["query_vector"],
                        k=int(spec.get("k", 10)),
                        num_candidates=int(spec.get("num_candidates", spec.get("k", 10))),
                        filter_query=parse_query(spec["filter"]) if "filter" in spec else None,
                        boost=float(spec.get("boost", 1.0)))
    # extended query types (geo, nested, join, percolate, span, …) register
    # in queries_ext — the analog of plugin-contributed query parsers
    # (reference: SearchPlugin.getQueries)
    from elasticsearch_tpu.search.queries_ext import parse_extended
    q = parse_extended(kind, spec)
    if q is not None:
        return q
    import difflib
    known = ("match", "match_all", "match_none", "match_phrase",
             "match_phrase_prefix", "multi_match", "term", "terms", "range",
             "bool", "exists", "prefix", "wildcard", "regexp", "fuzzy", "ids",
             "query_string", "simple_query_string", "nested", "knn",
             "constant_score", "function_score", "script_score", "dis_max",
             "boosting", "more_like_this", "terms_set", "span_term",
             "span_near", "intervals", "percolate", "rank_feature", "shape",
             "geo_shape", "geo_distance", "geo_bounding_box")
    hint = difflib.get_close_matches(str(kind), known, n=1)
    suffix = f" did you mean [{hint[0]}]?" if hint else ""
    raise ParsingError(f"unknown query [{kind}]{suffix}")


def _single(spec: Any, kind: str) -> Tuple[str, Any]:
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingError(f"[{kind}] query malformed, expected a single field")
    return next(iter(spec.items()))
